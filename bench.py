"""Driver benchmark: Llama training step MFU on the real chip + Pallas
flash-attention vs XLA micro-benchmark with an on-device parity check.

Prints exactly ONE JSON line to stdout:
  {"metric": "llama_train_mfu", "value": <mfu>, "unit": "fraction_of_peak",
   "vs_baseline": <mfu / 0.40>, ...diagnostic keys...}

The 0.40 baseline is the BASELINE.md north star (Llama pretraining >= 40%
MFU). Reference bar for the harness itself: `tools/ci_op_benchmark.sh`,
`python/paddle/profiler/timer.py` (ips benchmarking).
"""

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# bf16 peak FLOP/s per chip by device kind (MXU peak, the MFU denominator)
PEAK_FLOPS = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def device_peak():
    import jax
    d = jax.devices()[0]
    if d.platform != "tpu":
        return 1e12, d.platform  # nominal; bench is only meaningful on TPU
    return PEAK_FLOPS.get(d.device_kind, 197e12), d.device_kind


#: bump when the snapshot layout changes; tools/bench_check.py refuses
#: to diff snapshots whose schema versions disagree
BENCH_SCHEMA_VERSION = 1

#: the knobs that change what a bench run measures — stamped into every
#: snapshot so a regression diff can rule out "different config"
_PROVENANCE_KNOBS = (
    "PADDLE_TPU_METRICS", "PADDLE_TPU_PERF",
    "PADDLE_TPU_PERF_FENCE_INTERVAL", "PADDLE_TPU_PEAK_FLOPS",
    "PADDLE_TPU_PEAK_HBM_GBS", "PADDLE_TPU_SERVING_Q8",
    "PADDLE_TPU_FUSED_KV", "PADDLE_TPU_FUSED_ROPE",
)


def bench_provenance():
    """The identity block every snapshot carries: what ran, where, and
    under which knobs — so a later ``bench_check`` diff can tell a real
    regression from a config or platform change."""
    from paddle_tpu.observability import perf as _perf

    info = _perf.build_info()
    return {
        "git_commit": info["git_commit"],
        "jax_version": info["jax_version"],
        "device_kind": info["device_kind"],
        "wall_clock_unix": round(time.time(), 3),
        "env": {k: os.environ[k] for k in _PROVENANCE_KNOBS
                if k in os.environ},
    }


def bench_train_step(cfg_kw, batch, seq, steps=10, amp=True):
    """Train-step wall time through to_static; returns a result dict.

    Every TIMED step consumes a FRESH batch through the
    ``DevicePrefetcher`` (double-buffered async host->device copy) with
    the step's ids/labels buffers donated — the real recipe's input
    path, so the measured MFU pays (or hides) the transfer cost a
    replayed device-resident batch would mask. ``input_stall_frac``
    reports the fraction of the timed window the loop spent blocked on
    input."""
    import paddle_tpu as paddle
    from paddle_tpu.io import DevicePrefetcher
    from paddle_tpu.models import LlamaForCausalLM, LlamaConfig

    paddle.seed(0)
    cfg = LlamaConfig(**cfg_kw)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                 parameters=model.parameters())

    use_amp = amp and hasattr(paddle.amp, "auto_cast")

    def step(ids, labels):
        if use_amp:
            with paddle.amp.auto_cast(dtype="bfloat16"):
                loss, _ = model(ids, labels)
        else:
            loss, _ = model(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    compiled = paddle.jit.to_static(step, state=[model, opt],
                                    warmup="once", donate_inputs=True)

    # the prefetch worker draws from its OWN stream: sharing one
    # RandomState with the main thread's warmup draw would make seeded
    # runs scheduler-dependent
    rng = np.random.RandomState(0)
    feed_rng = np.random.RandomState(1)

    def host_batches():
        while True:
            yield feed_rng.randint(0, cfg.vocab_size,
                                   (batch, seq + 1)).astype(np.int64)

    feed = DevicePrefetcher(
        host_batches(),
        transform=lambda ids: (np.ascontiguousarray(ids[:, :-1]),
                               np.ascontiguousarray(ids[:, 1:])))

    def batch_of():
        x, y = next(feed)
        return paddle.to_tensor(x), paddle.to_tensor(y)

    try:
        # eager warmup on a tiny shape (materializes optimizer
        # accumulators without holding full-size eager intermediates in
        # HBM) ...
        wids = rng.randint(0, cfg.vocab_size, (1, 257)).astype(np.int64)
        compiled(paddle.to_tensor(wids[:, :-1]),
                 paddle.to_tensor(wids[:, 1:]))
        # ... then the real shape compiles directly
        t0 = time.perf_counter()
        loss = compiled(*batch_of())
        compile_s = time.perf_counter() - t0
        log(f"compile {compile_s:.1f}s  first loss {float(loss):.4f}")

        compiled(*batch_of())  # one steady-state call before timing
        feed.mark()
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = compiled(*batch_of())
        lossf = float(loss)  # host sync: blocks until every step done
        elapsed = time.perf_counter() - t0
        stall, _ = feed.mark()
    finally:
        feed.close()
    step_time = elapsed / steps

    tokens = batch * seq
    flops = model.flops_per_token(seq) * tokens
    peak, kind = device_peak()
    mfu = flops / step_time / peak
    # pin the model for the decode bench only on SUCCESS — a failed
    # candidate must be garbage-collected before the fallback allocates
    bench_train_step.last_model = model
    return {
        "model": f"llama-h{cfg.hidden_size}-L{cfg.num_hidden_layers}",
        "n_params": model.num_params(),
        "batch": batch, "seq": seq,
        "amp_bf16": use_amp,
        "step_time_ms": round(step_time * 1e3, 3),
        "tokens_per_sec": round(tokens / step_time, 1),
        "mfu": round(mfu, 4),
        "input_stall_frac": round(stall / max(elapsed, 1e-9), 4),
        "final_loss": round(lossf, 4),
        "compile_s": round(compile_s, 1),
        "device": kind,
        "peak_flops": peak,
    }


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def bench_decode(model, batch=4, prompt=128, new_tokens=64):
    """Static-KV-cache serving throughput: steady-state decode tok/s."""
    import paddle_tpu as paddle

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(
        0, model.config.vocab_size, (batch, prompt)).astype(np.int64))
    model.eval()
    # warm both shapes (prefill + single-token step) to steady state
    model.generate(ids, max_new_tokens=new_tokens)
    model.generate(ids, max_new_tokens=new_tokens)
    model.generate(ids, max_new_tokens=1)
    # best-of-3 on both timed sections: the tunneled chip's dispatch
    # latency is noisy and this number is the serving comparisons'
    # denominator
    t_prefill = min(_timed(lambda: model.generate(ids, max_new_tokens=1))
                    for _ in range(3))
    t_full = min(_timed(lambda: model.generate(
        ids, max_new_tokens=new_tokens)) for _ in range(3))
    model.train()
    # steady-state decode: the extra (new_tokens - 1) steps beyond the
    # prefill-only call
    dt = max(t_full - t_prefill, 1e-9)
    steps = new_tokens - 1
    return {
        "decode_batch": batch,
        "decode_new_tokens": new_tokens,
        "decode_prefill_ms": round(t_prefill * 1e3, 3),
        "decode_tokens_per_sec": round(batch * steps / dt, 1),
        "decode_ms_per_token": round(dt / steps * 1e3, 3),
    }


def bench_flash(batch=4, seq=2048, heads=16, kv_heads=8, dim=128, iters=20):
    """Pallas flash kernel vs XLA attention, fwd+bwd, on device."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import flash_attention as FA
    from paddle_tpu.nn.functional.attention import _naive_attention

    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    q = jnp.asarray(rng.randn(batch, seq, heads, dim), dt)
    k = jnp.asarray(rng.randn(batch, seq, kv_heads, dim), dt)
    v = jnp.asarray(rng.randn(batch, seq, kv_heads, dim), dt)
    assert FA.supported(q, k, v, None, True), "Pallas preconditions not met"
    fa = FA._make_flash(1.0 / np.sqrt(dim), True, heads // kv_heads)

    def loss_fa(q, k, v):
        return jnp.sum(fa(q, k, v).astype(jnp.float32))

    def loss_xla(q, k, v):
        return jnp.sum(
            _naive_attention(q, k, v, None, 0.0, True, None)
            .astype(jnp.float32))

    def timeit(f, *args):
        g = jax.jit(jax.grad(f, argnums=(0, 1, 2)))
        out = g(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3

    pallas_ms = timeit(loss_fa, q, k, v)
    xla_ms = timeit(loss_xla, q, k, v)
    # parity on device: fwd outputs and dq
    o_p = fa(q, k, v).astype(jnp.float32)
    o_x = _naive_attention(q, k, v, None, 0.0, True, None).astype(jnp.float32)
    fwd_err = float(jnp.max(jnp.abs(o_p - o_x)))
    g_p = jax.grad(loss_fa)(q, k, v).astype(jnp.float32)
    g_x = jax.grad(loss_xla)(q, k, v).astype(jnp.float32)
    bwd_err = float(jnp.max(jnp.abs(g_p - g_x)))
    scale = float(jnp.max(jnp.abs(o_x)))
    gscale = float(jnp.max(jnp.abs(g_x)))
    return {
        "flash_pallas_ms": round(pallas_ms, 3),
        "flash_xla_ms": round(xla_ms, 3),
        "flash_speedup": round(xla_ms / pallas_ms, 3),
        "flash_fwd_max_err": round(fwd_err, 5),
        "flash_dq_max_err": round(bwd_err, 5),
        "flash_parity_ok": bool(fwd_err < 0.05 * max(scale, 1.0)
                                and bwd_err < 0.05 * max(gscale, 1.0)),
        "pallas_branch": True,
    }


def bench_paged(batch=8, heads=16, kv_heads=8, dim=128, page=64,
                ctx=2048, iters=50):
    """Paged-attention decode kernel vs XLA gather path, on device."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import paged_attention as PA

    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    max_pages = ctx // page
    num_pages = batch * max_pages + 8
    q = jnp.asarray(rng.randn(batch, heads, dim), dt)
    kp = jnp.asarray(rng.randn(num_pages, kv_heads, page, dim), dt)
    vp = jnp.asarray(rng.randn(num_pages, kv_heads, page, dim), dt)
    perm = rng.permutation(num_pages)[:batch * max_pages]
    tables = jnp.asarray(perm.reshape(batch, max_pages), jnp.int32)
    lens = jnp.asarray(
        rng.randint(ctx // 2, ctx + 1, (batch,)), jnp.int32)

    def timeit(f):
        g = jax.jit(f)
        out = g(q, kp, vp, tables, lens)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(q, kp, vp, tables, lens)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3, out

    def pallas_path(q, kp, vp, tables, lens):
        return PA._paged_impl(q, kp, vp, tables, lens,
                              scale=1.0 / float(np.sqrt(dim)))

    pallas_ms, o_p = timeit(pallas_path)
    xla_ms, o_x = timeit(PA.paged_attention_xla)
    err = float(jnp.max(jnp.abs(o_p.astype(jnp.float32)
                                - o_x.astype(jnp.float32))))
    scale = float(jnp.max(jnp.abs(o_x.astype(jnp.float32))))
    return {
        "paged_pallas_ms": round(pallas_ms, 3),
        "paged_xla_ms": round(xla_ms, 3),
        "paged_speedup": round(xla_ms / pallas_ms, 3),
        "paged_parity_ok": bool(err < 0.05 * max(scale, 1.0)),
    }


def bench_ragged(rows=8, qb=16, heads=16, kv_heads=8, dim=128, page=64,
                 ctx=2048, iters=50):
    """Ragged paged-attention kernel (mixed prefill chunks + decode
    rows, ONE dispatch) vs the XLA gather reference, on device — the
    `paged_parity_ok`-style gate for the chunked serving engine's
    kernel."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import ragged_paged_attention as RPA

    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    max_pages = ctx // page
    num_pages = rows * max_pages + 8
    q = jnp.asarray(rng.randn(rows, qb, heads, dim), dt)
    kp = jnp.asarray(rng.randn(num_pages, kv_heads, page, dim), dt)
    vp = jnp.asarray(rng.randn(num_pages, kv_heads, page, dim), dt)
    perm = rng.permutation(num_pages)[:rows * max_pages]
    tables = jnp.asarray(perm.reshape(rows, max_pages), jnp.int32)
    # half the rows decode (q_len 1), half are ragged prefill chunks
    q_lens = np.asarray([1 if i % 2 else 1 + rng.randint(qb)
                         for i in range(rows)], np.int32)
    kv = rng.randint(ctx // 2, ctx + 1, (rows,)).astype(np.int32)
    kv = np.maximum(kv, q_lens)
    q_starts = kv - q_lens
    kv_lens = jnp.asarray(kv)
    q_starts = jnp.asarray(q_starts)
    q_lens = jnp.asarray(q_lens)

    def timeit(f):
        g = jax.jit(f)
        out = g(q, kp, vp, tables, kv_lens, q_starts, q_lens)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(q, kp, vp, tables, kv_lens, q_starts, q_lens)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3, out

    def pallas_path(q, kp, vp, tables, kl, qs, ql):
        return RPA._ragged_impl(q, kp, vp, tables, kl, qs, ql,
                                scale=1.0 / float(np.sqrt(dim)))

    pallas_ms, o_p = timeit(pallas_path)
    xla_ms, o_x = timeit(RPA.ragged_paged_attention_xla)
    err = float(jnp.max(jnp.abs(o_p.astype(jnp.float32)
                                - o_x.astype(jnp.float32))))
    scale = float(jnp.max(jnp.abs(o_x.astype(jnp.float32))))
    return {
        "ragged_pallas_ms": round(pallas_ms, 3),
        "ragged_xla_ms": round(xla_ms, 3),
        "ragged_speedup": round(xla_ms / pallas_ms, 3),
        "ragged_parity_ok": bool(err < 0.05 * max(scale, 1.0)),
    }


def _fused_bench_case(rng, rows, qb, kv_heads, dim, page, ctx, dt):
    """Shared fused-kernel bench geometry (bench_fused_kv and
    bench_fused_rope): pools, disjoint per-row tables (dump page never
    referenced), half-decode/half-chunk row metadata, the w-metadata
    the fused contract needs, and the per-token scatter targets of the
    unfused reference path."""
    import jax.numpy as jnp

    max_pages = ctx // page
    num_pages = rows * max_pages + 8
    dump = num_pages - 1
    kp = jnp.asarray(rng.randn(num_pages, kv_heads, page, dim), dt)
    vp = jnp.asarray(rng.randn(num_pages, kv_heads, page, dim), dt)
    perm = rng.permutation(num_pages - 1)[:rows * max_pages]
    tables = jnp.asarray(perm.reshape(rows, max_pages), jnp.int32)
    q_lens = np.asarray([1 if i % 2 else 1 + rng.randint(qb)
                         for i in range(rows)], np.int32)
    kv = rng.randint(ctx // 2, ctx + 1, (rows,)).astype(np.int32)
    kv = np.maximum(kv, q_lens)
    q_starts = kv - q_lens
    w_starts = q_starts.copy()
    w_flats = np.concatenate([[0], np.cumsum(q_lens)[:-1]]) \
        .astype(np.int32)
    w_ends = kv.copy()
    t_total = int(q_lens.sum())
    new_k = jnp.asarray(rng.randn(t_total, kv_heads, dim), dt)
    new_v = jnp.asarray(rng.randn(t_total, kv_heads, dim), dt)
    pg = np.concatenate([
        np.asarray(tables)[i, np.arange(q_starts[i], kv[i]) // page]
        for i in range(rows)]).astype(np.int32)
    offs = np.concatenate([np.arange(q_starts[i], kv[i]) % page
                           for i in range(rows)]).astype(np.int32)
    args_i32 = [jnp.asarray(a) for a in
                (kv, q_starts, q_lens, w_starts, w_flats, w_ends)]
    return dict(num_pages=num_pages, dump=dump, kp=kp, vp=vp,
                perm=perm, tables=tables, q_lens=q_lens, kv=kv,
                q_starts=q_starts, w_flats=w_flats, t_total=t_total,
                new_k=new_k, new_v=new_v, pg=pg, offs=offs,
                args_i32=args_i32,
                scale=1.0 / float(np.sqrt(dim)))


def bench_fused_kv(model, rows=8, qb=16, heads=16, kv_heads=8, dim=128,
                   page=64, ctx=2048, iters=50, on_tpu=True):
    """Fused in-kernel KV page write (ROADMAP item 2, first stage) vs
    the unfused two-op path (scatter + ragged read), at two levels:

    - kernel microbench: ONE `fused_ragged_paged_attention` dispatch vs
      the `paged_kv_write` scatter followed by `ragged_paged_attention`
      over the same rows (`fused_kernel_ms` / `unfused_kernel_ms`).
    - engine e2e: `serving_chunked_tokens_per_sec`-style throughput
      under PADDLE_TPU_FUSED_KV on vs off, plus each path's
      `serving_mixed_hbm_bytes` (static cost_analysis of the mixed
      program) and their delta.

    Gates: ``fused_parity_ok`` — greedy engine outputs BITWISE equal
    fused vs unfused (fp), q8 kernel within the existing 5%-of-scale
    bar vs the write-then-read XLA reference, and non-trash pool bytes
    identical across paths. ``fused_hbm_decreased`` is asserted into
    ``fused_hbm_ok`` only on TPU: the CPU interpret-mode lowering of
    the Pallas call inflates cost_analysis with emulation machinery
    (aliasing copies, per-step slices) that does not exist in the
    compiled custom call, so off-chip the delta is recorded but the
    strict-decrease claim rides ROADMAP item 1's on-chip sweep."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.inference.serving import LlamaServingEngine, \
        _page_write
    from paddle_tpu.ops import ragged_paged_attention as RPA

    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    case = _fused_bench_case(rng, rows, qb, kv_heads, dim, page, ctx,
                             dt)
    num_pages, dump = case["num_pages"], case["dump"]
    kp, vp, perm, tables = (case[k] for k in
                            ("kp", "vp", "perm", "tables"))
    new_k, new_v, pg, offs = (case[k] for k in
                              ("new_k", "new_v", "pg", "offs"))
    args_i32, scale = case["args_i32"], case["scale"]
    q = jnp.asarray(rng.randn(rows, qb, heads, dim), dt)

    def fused_path(q, nk, nv, kp, vp):
        return RPA._fused_impl(q, nk, nv, kp, vp, tables, *args_i32,
                               dump, scale)

    def unfused_path(q, nk, nv, kp, vp):
        kp2 = _page_write(kp, nk, jnp.asarray(pg), jnp.asarray(offs))
        vp2 = _page_write(vp, nv, jnp.asarray(pg), jnp.asarray(offs))
        kp2 = getattr(kp2, "_data", kp2)
        vp2 = getattr(vp2, "_data", vp2)
        out = RPA._ragged_impl(q, kp2, vp2, tables, args_i32[0],
                               args_i32[1], args_i32[2], scale)
        return out, kp2, vp2

    def timeit(f):
        g = jax.jit(f)
        out = g(q, new_k, new_v, kp, vp)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(q, new_k, new_v, kp, vp)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3, out

    fused_ms, (o_f, kpf, vpf) = timeit(fused_path)
    unfused_ms, (o_u, kpu, vpu) = timeit(unfused_path)
    live = np.asarray(sorted(set(perm.tolist())))
    pools_equal = bool(
        np.array_equal(np.asarray(kpf)[live], np.asarray(kpu)[live])
        and np.array_equal(np.asarray(vpf)[live], np.asarray(vpu)[live]))
    out_equal = bool(np.array_equal(np.asarray(o_f), np.asarray(o_u)))

    # q8 kernel parity at the existing 5%-of-scale bar vs the
    # write-then-read XLA reference
    kq = jnp.asarray(rng.randint(-127, 128,
                                 (num_pages, kv_heads, page, dim)),
                     jnp.int8)
    vq = jnp.asarray(rng.randint(-127, 128,
                                 (num_pages, kv_heads, page, dim)),
                     jnp.int8)
    ks = jnp.asarray(np.abs(rng.randn(num_pages, kv_heads, page, 1))
                     .astype(np.float32) * 0.02)
    vs = jnp.asarray(np.abs(rng.randn(num_pages, kv_heads, page, 1))
                     .astype(np.float32) * 0.02)
    q8_args = (jnp.asarray(np.asarray(q, np.float32)),
               jnp.asarray(np.asarray(new_k, np.float32)),
               jnp.asarray(np.asarray(new_v, np.float32)),
               kq, vq, tables, *args_i32, dump)
    o8f = RPA.fused_ragged_paged_attention(*q8_args, k_scale=ks,
                                           v_scale=vs)[0]
    o8x = RPA.fused_ragged_paged_attention_xla(*q8_args, k_scale=ks,
                                               v_scale=vs)[0]
    o8f = np.asarray(getattr(o8f, "_data", o8f), np.float32)
    o8x = np.asarray(o8x, np.float32)
    err8 = float(np.max(np.abs(o8f - o8x)))
    bar8 = 0.05 * max(float(np.max(np.abs(o8x))), 1.0)

    # engine e2e under both paths: same workload, fused on vs off
    model.eval()
    rng2 = np.random.RandomState(1)
    v = model.config.vocab_size
    prompts = [rng2.randint(0, v, (int(rng2.randint(16, 96)),)).tolist()
               for _ in range(8 if on_tpu else 3)]
    n_new = 32 if on_tpu else 6

    def e2e(fused):
        # fused_rope pinned OFF: this bench measures stage 1 (the
        # fused KV write) against the two-op path — the engine default
        # would silently swap in the rope-fused program and the
        # 'fused' metrics would no longer mean PR-13's program
        engine = LlamaServingEngine(
            model, max_batch=8 if on_tpu else 2, page_size=64,
            num_pages=72 if on_tpu else 24, max_pages_per_seq=8,
            decode_ticks=16, fused_kv=fused, fused_rope=False)
        engine.generate(prompts, max_new_tokens=2)        # compile
        t0 = time.perf_counter()
        outs = engine.generate(prompts, max_new_tokens=n_new)
        dt_ = time.perf_counter() - t0
        # read THIS engine's cached analysis (budget-shape mixed
        # program, the largest t_cap) rather than the process-global
        # gauge: the gauge retains whatever engine last set it, so a
        # failed attribution in one run would silently compare against
        # a stale value from another. None (not 0.0) when no analysis
        # exists (METRICS=0: no AOT executables to cost-analyze) so a
        # 0-vs-0 comparison can't report a spurious gate failure.
        hbm = engine._mixed_bytes.get(max(engine._mixed_bytes)) \
            if engine._mixed_bytes else None
        engine.close()
        return outs, sum(len(o) for o in outs) / dt_, hbm

    outs_f, tps_f, hbm_f = e2e(True)
    outs_u, tps_u, hbm_u = e2e(False)
    model.train()
    parity = bool(out_equal and pools_equal and err8 < bar8
                  and outs_f == outs_u)
    res = {
        "fused_kernel_ms": round(fused_ms, 3),
        "unfused_kernel_ms": round(unfused_ms, 3),
        "fused_kernel_speedup": round(unfused_ms / fused_ms, 3),
        "fused_parity_ok": parity,
        "serving_fused_tokens_per_sec": round(tps_f, 1),
        "serving_unfused_tokens_per_sec": round(tps_u, 1),
        "fused_e2e_speedup": round(tps_f / max(tps_u, 1e-9), 3),
    }
    if hbm_f is not None and hbm_u is not None:
        res.update({
            "serving_mixed_hbm_bytes_fused": hbm_f,
            "serving_mixed_hbm_bytes_unfused": hbm_u,
            "fused_hbm_bytes_delta": hbm_u - hbm_f,
            "fused_hbm_decreased": bool(hbm_f < hbm_u),
        })
        if on_tpu:
            res["fused_hbm_ok"] = bool(hbm_f < hbm_u)
    return res


def bench_fused_rope(model, rows=8, qb=16, heads=16, kv_heads=8,
                     dim=128, page=64, ctx=2048, iters=50, on_tpu=True):
    """Fused rotary embedding (ROADMAP item 2, second stage) — rope +
    KV write + attention in ONE Pallas program — vs the PR-13 fused-KV
    path (separate rope op + q row-pack) and the fully-unfused two-op
    path, at two levels:

    - kernel microbench: one rope-fused dispatch vs rope + pack +
      `fused_ragged_paged_attention` vs rope + scatter + ragged read
      (`fused_rope_kernel_ms` / `fused_kv_kernel_ms` /
      `unfused_rope_kernel_ms`).
    - engine e2e: tok/s under PADDLE_TPU_FUSED_ROPE on / off (PR-13) /
      PADDLE_TPU_FUSED_KV off, plus each variant's
      `serving_mixed_hbm_bytes` (omitted under METRICS=0, matching
      `bench_fused_kv`).

    Gates: ``fused_rope_parity_ok`` — greedy engine outputs token-
    exact across all three variants, fp kernel outputs AND live pool
    bytes BITWISE rope-fused vs PR-13, q8 kernel within the existing
    5%-of-scale bar vs the rope-then-write-then-read XLA reference.
    ``fused_rope_hbm_ok`` (strict decrease vs the PR-13 program — the
    per-layer rope reads/writes and the q pack gone from the static
    cost analysis) is asserted on TPU only: CPU interpret-mode
    lowering inflates cost_analysis with emulation machinery."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.inference.serving import LlamaServingEngine, \
        _page_write
    from paddle_tpu.ops import ragged_paged_attention as RPA

    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    case = _fused_bench_case(rng, rows, qb, kv_heads, dim, page, ctx,
                             dt)
    num_pages, dump = case["num_pages"], case["dump"]
    kp, vp, perm, tables = (case[k] for k in
                            ("kp", "vp", "perm", "tables"))
    new_k, new_v, pg, offs = (case[k] for k in
                              ("new_k", "new_v", "pg", "offs"))
    args_i32, scale = case["args_i32"], case["scale"]
    q_lens, kv, q_starts, w_flats, t_total = (
        case[k] for k in ("q_lens", "kv", "q_starts", "w_flats",
                          "t_total"))
    q_packed = jnp.asarray(rng.randn(t_total, heads, dim), dt)
    pos = np.concatenate([np.arange(q_starts[i], kv[i])
                          for i in range(rows)]).astype(np.int32)
    sin, cos = RPA.rope_tables(jnp.asarray(pos), dim, 10000.0)
    # row-block gather indices for the PR-13 variant: token j of row i
    # sits at packed w_flats[i] + j (pad slot t_total reads zeros)
    ridx = np.full((rows, qb), t_total, np.int64)
    for i in range(rows):
        ridx[i, :q_lens[i]] = w_flats[i] + np.arange(q_lens[i])
    ridx = jnp.asarray(ridx)

    def _rope(x):
        xf = x.astype(jnp.float32)
        h = dim // 2
        rot = jnp.concatenate([-xf[..., h:], xf[..., :h]], -1)
        return (xf * cos[:, None, :] + rot * sin[:, None, :]) \
            .astype(x.dtype)

    def rope_fused_path(q, nk, nv, kp, vp):
        return RPA._fused_rope_impl(q, nk, nv, kp, vp, tables,
                                    *args_i32, sin, cos, dump, scale,
                                    qb)

    def pr13_path(q, nk, nv, kp, vp):
        qr = jnp.pad(_rope(q), ((0, 1), (0, 0), (0, 0)))[ridx]
        return RPA._fused_impl(qr, _rope(nk), nv, kp, vp, tables,
                               *args_i32, dump, scale)

    def unfused_path(q, nk, nv, kp, vp):
        qr = jnp.pad(_rope(q), ((0, 1), (0, 0), (0, 0)))[ridx]
        nk2 = _rope(nk)
        kp2 = _page_write(kp, nk2, jnp.asarray(pg), jnp.asarray(offs))
        vp2 = _page_write(vp, nv, jnp.asarray(pg), jnp.asarray(offs))
        kp2 = getattr(kp2, "_data", kp2)
        vp2 = getattr(vp2, "_data", vp2)
        out = RPA._ragged_impl(qr, kp2, vp2, tables, args_i32[0],
                               args_i32[1], args_i32[2], scale)
        return out, kp2, vp2

    def timeit(f):
        g = jax.jit(f)
        out = g(q_packed, new_k, new_v, kp, vp)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(q_packed, new_k, new_v, kp, vp)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3, out

    rope_ms, (o_r, kpr, vpr) = timeit(rope_fused_path)
    pr13_ms, (o_13, kp13, vp13) = timeit(pr13_path)
    unf_ms, (o_u, kpu, vpu) = timeit(unfused_path)
    live = np.asarray(sorted(set(perm.tolist())))
    kern_bitwise = bool(
        np.array_equal(np.asarray(o_r), np.asarray(o_13))
        and np.array_equal(np.asarray(kpr)[live], np.asarray(kp13)[live])
        and np.array_equal(np.asarray(vpr)[live], np.asarray(vp13)[live]))
    kern_vs_unfused = bool(
        np.array_equal(np.asarray(kpr)[live], np.asarray(kpu)[live])
        and np.array_equal(np.asarray(vpr)[live], np.asarray(vpu)[live])
        and np.array_equal(np.asarray(o_r), np.asarray(o_u)))

    # q8 at the existing 5%-of-scale bar vs the rope-then-write-then-
    # read reference
    kq = jnp.asarray(rng.randint(-127, 128,
                                 (num_pages, kv_heads, page, dim)),
                     jnp.int8)
    vq = jnp.asarray(np.roll(np.asarray(kq), 1, axis=0))
    ks = jnp.asarray(np.abs(rng.randn(num_pages, kv_heads, page, 1))
                     .astype(np.float32) * 0.02)
    vs = jnp.asarray(np.roll(np.asarray(ks), 1, axis=0))
    q8_args = (jnp.asarray(np.asarray(q_packed, np.float32)),
               jnp.asarray(np.asarray(new_k, np.float32)),
               jnp.asarray(np.asarray(new_v, np.float32)),
               kq, vq, tables, *args_i32, dump)
    o8f = RPA.fused_ragged_paged_attention(
        *q8_args, k_scale=ks, v_scale=vs, rope_sin=sin, rope_cos=cos,
        qblock=qb)[0]
    o8x = RPA.fused_ragged_paged_attention_xla(
        *q8_args, k_scale=ks, v_scale=vs, rope_sin=sin, rope_cos=cos,
        qblock=qb)[0]
    o8f = np.asarray(getattr(o8f, "_data", o8f), np.float32)
    o8x = np.asarray(o8x, np.float32)
    err8 = float(np.max(np.abs(o8f - o8x)))
    bar8 = 0.05 * max(float(np.max(np.abs(o8x))), 1.0)

    # engine e2e under the three programs: same workload
    model.eval()
    rng2 = np.random.RandomState(1)
    v = model.config.vocab_size
    prompts = [rng2.randint(0, v, (int(rng2.randint(16, 96)),)).tolist()
               for _ in range(8 if on_tpu else 3)]
    n_new = 32 if on_tpu else 6

    def e2e(**kw):
        engine = LlamaServingEngine(
            model, max_batch=8 if on_tpu else 2, page_size=64,
            num_pages=72 if on_tpu else 24, max_pages_per_seq=8,
            decode_ticks=16, **kw)
        engine.generate(prompts, max_new_tokens=2)        # compile
        t0 = time.perf_counter()
        outs = engine.generate(prompts, max_new_tokens=n_new)
        dt_ = time.perf_counter() - t0
        # each engine's own budget-shape analysis (None under
        # METRICS=0) — see bench_fused_kv for why not the global gauge
        hbm = engine._mixed_bytes.get(max(engine._mixed_bytes)) \
            if engine._mixed_bytes else None
        engine.close()
        return outs, sum(len(o) for o in outs) / dt_, hbm

    # every arm pins BOTH knobs explicitly: an ambient
    # PADDLE_TPU_FUSED_ROPE=0 / PADDLE_TPU_FUSED_KV=0 in the bench
    # environment must not silently swap which program an arm measures
    outs_r, tps_r, hbm_r = e2e(fused_kv=True, fused_rope=True)
    outs_13, tps_13, hbm_13 = e2e(fused_kv=True, fused_rope=False)
    outs_u, tps_u, hbm_u = e2e(fused_kv=False, fused_rope=False)
    model.train()
    parity = bool(kern_bitwise and kern_vs_unfused and err8 < bar8
                  and outs_r == outs_13 == outs_u)
    res = {
        "fused_rope_kernel_ms": round(rope_ms, 3),
        "fused_kv_kernel_ms": round(pr13_ms, 3),
        "unfused_rope_kernel_ms": round(unf_ms, 3),
        "fused_rope_kernel_speedup": round(pr13_ms / rope_ms, 3),
        "fused_rope_parity_ok": parity,
        "serving_fused_rope_tokens_per_sec": round(tps_r, 1),
        "serving_fused_kv_tokens_per_sec": round(tps_13, 1),
        "serving_unfused_rope_tokens_per_sec": round(tps_u, 1),
        "fused_rope_e2e_speedup": round(tps_r / max(tps_13, 1e-9), 3),
    }
    if hbm_r is not None and hbm_13 is not None:
        res.update({
            "serving_mixed_hbm_bytes_fused_rope": hbm_r,
            "serving_mixed_hbm_bytes_fused_kv": hbm_13,
            "fused_rope_hbm_bytes_delta": hbm_13 - hbm_r,
            "fused_rope_hbm_decreased": bool(hbm_r < hbm_13),
        })
        if hbm_u is not None:
            res["serving_mixed_hbm_bytes_unfused_rope"] = hbm_u
        if on_tpu:
            res["fused_rope_hbm_ok"] = bool(hbm_r < hbm_13)
    return res


def bench_serving(model, n_requests=24, new_tokens=48, max_batch=16,
                  decode_ceiling=None, on_tpu=True):
    """Chunked-prefill engine throughput: ragged prompts admitted on the
    fly over ONE mixed prefill+decode program (the ragged paged-
    attention kernel). Three regimes:

    - ``serving_tokens_per_sec``: the historical e2e number — admit
      n_requests ragged prompts, run to completion (prefill + decode +
      admission bookkeeping included).
    - ``serving_steady_tokens_per_sec`` (+ ``serving_ceiling_frac``):
      a full batch on the scanned decode path, no retirements — the
      sustained rate vs the raw decode ceiling.
    - ``serving_chunked_tokens_per_sec`` (+ TTFT p50/p99): the MIXED
      workload — long prompts admitted while a decode-heavy batch is
      live, chunks interleaving with decodes every step. The gate
      ``serving_chunked_ok`` requires >= 1.5x the e2e rate measured in
      the same run."""
    from paddle_tpu.inference.serving import LlamaServingEngine, Request

    model.eval()
    engine = LlamaServingEngine(model, max_batch=max_batch, page_size=64,
                                num_pages=max_batch * 8 + 8,
                                max_pages_per_seq=8, decode_ticks=32)
    rng = np.random.RandomState(0)
    v = model.config.vocab_size
    prompts = [rng.randint(0, v, (int(rng.randint(16, 128)),)).tolist()
               for _ in range(n_requests)]
    # warm TWICE: pass 1 traces, pass 2 lands both mixed-program shapes
    # and the full-length scan in the compile cache
    engine.generate(prompts, max_new_tokens=2)
    engine.generate(prompts, max_new_tokens=engine.decode_ticks + 2)
    t0 = time.perf_counter()
    outs = engine.generate(prompts, max_new_tokens=new_tokens)
    dt = time.perf_counter() - t0
    total = sum(len(o) for o in outs)
    e2e = total / dt

    # steady-state decode throughput: a full batch scanning with no
    # retirements (the engine's sustained rate, free of prefill and
    # admission bookkeeping)
    rng2 = np.random.RandomState(1)
    for _ in range(max_batch):
        engine.add_request(Request(
            rng2.randint(0, v, (32,)).tolist(),
            max_new_tokens=new_tokens * 8 + 64))
    engine.decode_many(engine.decode_ticks)  # warm the scan path
    # best-of-3: the tunneled chip's per-dispatch latency is noisy, and
    # a single timed window under-reports the engine's sustained rate
    steady = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        served = engine.decode_many(new_tokens * 2)
        steady = max(steady, served / (time.perf_counter() - t0))
    for r in list(engine._live.values()):
        engine.alloc.release(r.seq_id)
        engine._live.pop(r.seq_id)

    # mixed long-prompt + decode-heavy workload: decode-bound requests
    # stay live while multi-chunk prompts stream in; TTFT of each long
    # admission is measured with the batch busy (the number the old
    # wave/burst split could not bound)
    n_dec = max(1, max_batch - 2)
    decoders = [Request(rng2.randint(0, v, (32,)).tolist(),
                        max_new_tokens=100000)
                for _ in range(n_dec)]
    for r in decoders:
        engine.add_request(r)
    long_len = 4 * engine.page_size          # 4 pages, multi-chunk
    n_long = 6 if on_tpu else 3
    ttfts = []
    done0 = sum(len(r.output_ids) for r in decoders)
    longs = []
    t0 = time.perf_counter()
    for i in range(n_long):
        lr = Request(rng2.randint(0, v, (long_len,)).tolist(),
                     max_new_tokens=4)
        longs.append(lr)
        ts = time.perf_counter()
        engine.add_request(lr)               # chunks + decodes interleave
        ttfts.append(time.perf_counter() - ts)
        engine.decode_many(8 if on_tpu else 4)
    dt_mixed = time.perf_counter() - t0
    # mixed throughput counts every token the engine PROCESSED in the
    # window: decode tokens emitted plus prompt tokens chunk-prefilled
    # (the standard chunked-prefill accounting — prefill is the work
    # the old wave/burst split serialized)
    mixed_tokens = (sum(len(r.output_ids) for r in decoders) - done0
                    + sum(len(r.output_ids) + r._prefilled
                          for r in longs))
    chunked = mixed_tokens / dt_mixed
    for r in list(engine._live.values()):
        engine.cancel(r)
    engine.close()
    model.train()
    out = {
        "serving_requests": n_requests,
        "serving_tokens": total,
        "serving_tokens_per_sec": round(e2e, 1),
        "serving_steady_tokens_per_sec": round(steady, 1),
        "serving_chunked_tokens_per_sec": round(chunked, 1),
        "serving_chunked_speedup": round(chunked / max(e2e, 1e-9), 3),
        "serving_chunked_ok": bool(chunked >= 1.5 * e2e),
        "serving_ttft_p50_ms": round(
            float(np.percentile(ttfts, 50)) * 1e3, 2),
        "serving_ttft_p99_ms": round(
            float(np.percentile(ttfts, 99)) * 1e3, 2),
        "serving_max_batch": max_batch,
        "serving_chunk_budget": engine.chunk_budget,
        "serving_chunk_block": engine.chunk_block,
        "serving_decode_ticks": engine.decode_ticks,
    }
    if decode_ceiling:
        out["serving_ceiling_frac"] = round(steady / decode_ceiling, 3)
    return out


def bench_prefix_cluster(model, on_tpu=True):
    """Shared-prefix KV cache + multi-replica cluster (ROADMAP item 2):
    TTFT for a prompt whose page-aligned prefix is already cached vs a
    cold prompt of identical shape, the cache hit rate, and aggregate
    tokens/sec routed over in-process engine replicas. Tracks the
    scale-out trajectory the way serving_tokens_per_sec tracks the
    single engine."""
    from paddle_tpu.inference.cluster import ServingCluster
    from paddle_tpu.inference.serving import LlamaServingEngine, Request

    model.eval()
    page = 64 if on_tpu else 8
    prefix_pages = 16 if on_tpu else 32   # 1024- / 256-token prefix
    # CPU smoke runs measure the pure prefix win (1 un-cached token);
    # on the chip the margin is structural (a [B, 1088]-bucket dense
    # prefill vs a handful of decode dispatches), so a realistic
    # suffix is kept
    suffix = 8 if on_tpu else 1
    max_batch = 8 if on_tpu else 2
    pps = prefix_pages + 4
    kw = dict(max_batch=max_batch, page_size=page,
              num_pages=max_batch * pps + prefix_pages * 4 + 8,
              max_pages_per_seq=pps)
    engine = LlamaServingEngine(model, **kw)
    rng = np.random.RandomState(7)
    v = model.config.vocab_size

    def prompt_with(prefix, seed):
        sfx = np.random.RandomState(seed).randint(0, v, (suffix,))
        return prefix + sfx.tolist()

    # land the prefill bucket + decode programs outside the timed
    # windows, then drop the warmup prompt's cache entries
    warm = rng.randint(0, v, (prefix_pages * page,)).tolist()
    engine.generate([prompt_with(warm, 0)], max_new_tokens=2)
    engine.prefix.clear()
    shared = rng.randint(0, v, (prefix_pages * page,)).tolist()

    def ttft(prompt):
        r = Request(prompt, max_new_tokens=1)
        t0 = time.perf_counter()
        engine.add_request(r)      # prefill emits the first token
        return time.perf_counter() - t0

    ttft(prompt_with(shared, 1))   # cold fill: prefix enters the cache
    ttft(prompt_with(shared, 2))   # first hit pays the suffix-path warm
    t_cold = min(ttft(prompt_with(
        rng.randint(0, v, (prefix_pages * page,)).tolist(), 10 + i))
        for i in range(3))
    t_warm = min(ttft(prompt_with(shared, 20 + i)) for i in range(3))
    s = engine.prefix.stats()
    engine.close()
    out = {
        "serving_prefix_cold_ttft_ms": round(t_cold * 1e3, 3),
        "serving_prefix_ttft_ms": round(t_warm * 1e3, 3),
        "serving_prefix_ttft_speedup": round(t_cold / max(t_warm, 1e-9),
                                             3),
        "serving_prefix_hit_rate": round(s["hit_rate"], 4),
        "serving_prefix_saved_tokens": s["saved_tokens"],
    }

    # cluster throughput: shared-prefix workload over N replicas, each
    # with its own engine + prefix cache (prefill once PER REPLICA)
    n_replicas = 2
    cluster = ServingCluster(lambda: LlamaServingEngine(model, **kw),
                             num_replicas=n_replicas, ttl=60.0)
    cluster.start()
    new_toks = 32 if on_tpu else 4
    n_req = 16 if on_tpu else 4
    for c in [cluster.submit(prompt_with(shared, 50 + i),
                             max_new_tokens=2)
              for i in range(n_replicas * 2)]:
        c.result(timeout=600)      # warm both replicas' programs
    t0 = time.perf_counter()
    creqs = [cluster.submit(prompt_with(shared, 100 + i),
                            max_new_tokens=new_toks)
             for i in range(n_req)]
    outs = [c.result(timeout=600) for c in creqs]
    dt = time.perf_counter() - t0
    cluster.stop()
    out.update({
        "serving_cluster_replicas": n_replicas,
        "serving_cluster_requests": n_req,
        "serving_cluster_tokens_per_sec": round(
            sum(len(o) for o in outs) / dt, 1),
    })
    return out


def bench_speculative(model, on_tpu=True):
    """Speculative decoding gates (ROADMAP item 3a): a self-speculative
    (n-gram prompt-lookup) engine vs the same chunked engine with
    speculation off, on the same decode-heavy workload.

    Both engines are driven by the SERVING loop regime — one
    :meth:`step` per tick, the way a cluster replica's worker actually
    serves (a multi-tick decode scan would block admissions and prompt
    chunks for its whole length, so the admission-responsive tick is
    the production decode path). In that regime every non-speculative
    tick emits exactly one token per live row; speculation multiplies
    what one dispatch commits — exactly the dispatch-amortization lever
    named in ROADMAP item 3.

    - ``spec_parity_ok``: greedy outputs TOKEN-EXACT vs the
      non-speculative engine — the hard gate; speculation may only
      change dispatch counts, never a token.
    - ``spec_accept_rate`` / ``serving_spec_tokens_per_dispatch``: how
      much each verify dispatch commits.
    - ``serving_spec_tokens_per_sec`` + ``spec_throughput_ok``: >= 1.3x
      the chunked baseline measured in the same run (CPU smoke gate;
      greedy decode settles into repetition the drafter locks onto).
    - ``serving_spec_batch_tokens_per_sec`` (informational): the same
      engines under the batch :meth:`generate` regime, where the
      baseline may amortize host round trips with decode scans and the
      speculative engine auto-falls back to them when the drafter has
      nothing (speculation never costs more than not speculating)."""
    from paddle_tpu.inference.serving import LlamaServingEngine, Request

    model.eval()
    kw = dict(max_batch=2, page_size=16, num_pages=48,
              max_pages_per_seq=8, chunk_block=16, chunk_budget=16,
              prefix_cache=False)
    # long enough for greedy decode to settle into the repetition the
    # drafter locks onto — the first few dozen tokens are a cold
    # history with nothing to propose
    new_toks = 96
    rng = np.random.RandomState(0)
    v = model.config.vocab_size
    cands = [rng.randint(0, v, (12,)).tolist() for _ in range(4)]
    pairs = [[p, p[::-1]] for p in cands]

    def serve_loop(spec_k):
        e = LlamaServingEngine(model, spec_k=spec_k, **kw)
        # pair 0 warms every dispatched shape end to end; pairs 1..N
        # are the timed workload (one engine, compile excluded)
        e.generate(pairs[0], max_new_tokens=4)
        warm = [Request(p, max_new_tokens=new_toks) for p in pairs[0]]
        for r in warm:
            e.add_request(r)
        while not all(r.done for r in warm):
            e.step()
        tokens, dt, dispatches, outs = 0, 0.0, 0, []
        for pair in pairs[1:]:
            reqs = [Request(p, max_new_tokens=new_toks) for p in pair]
            for r in reqs:
                e.add_request(r)
            d0 = e._dispatch_count
            pre = sum(len(r.output_ids) for r in reqs)
            t0 = time.perf_counter()
            while not all(r.done for r in reqs):
                e.step()
            dt += time.perf_counter() - t0
            dispatches += e._dispatch_count - d0
            tokens += sum(len(r.output_ids) for r in reqs) - pre
            outs.append([r.output_ids for r in reqs])
        stats = e.spec_stats()
        # batch regime (scans allowed) on the same engine, second pass
        t0 = time.perf_counter()
        bouts = e.generate(pairs[1], max_new_tokens=new_toks)
        bt = sum(len(o) for o in bouts) / (time.perf_counter() - t0)
        e.close()
        return (tokens / dt, tokens / max(1, dispatches), stats, outs,
                bt)

    base_tps, base_tpd, _, outs_base, base_batch = serve_loop(0)
    spec_tps, spec_tpd, stats, outs_spec, spec_batch = serve_loop(7)
    model.train()
    return {
        "spec_parity_ok": bool(outs_spec == outs_base),
        "spec_k": stats["k"],
        "spec_accept_rate": round(stats["accept_rate"], 4),
        "serving_spec_tokens_per_dispatch": round(spec_tpd, 3),
        "serving_spec_baseline_tokens_per_dispatch": round(base_tpd, 3),
        "serving_spec_tokens_per_sec": round(spec_tps, 1),
        "serving_spec_baseline_tokens_per_sec": round(base_tps, 1),
        "spec_speedup": round(spec_tps / max(base_tps, 1e-9), 3),
        "spec_throughput_ok": bool(spec_tps >= 1.3 * base_tps),
        "serving_spec_batch_tokens_per_sec": round(spec_batch, 1),
        "serving_spec_batch_baseline_tokens_per_sec": round(base_batch,
                                                            1),
    }


def bench_kv_int8(model, on_tpu=True):
    """Int8 KV-page gates (ROADMAP item 3b).

    - ``kv_int8_parity_ok``: attention over int8 pages + scale
      sidecars within exact-logit tolerance of float pages (the same
      0.05x-scale bar as every other ``*_parity_ok`` kernel gate).
    - ``kv_int8_capacity_x``: float KV bytes / int8 KV bytes per cached
      token (sidecars counted) — how many times more tokens one HBM
      pool admits before the degradation ladder fires (~2x at bf16
      head_dim 128; higher for f32 pools).
    - ``kv_int8_tokens_per_sec``: the int8 engine on the e2e workload
      (the win is capacity, not speed — this guards against a
      dequant-path regression)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.inference.paged_cache import quantize_kv_int8
    from paddle_tpu.inference.serving import LlamaServingEngine
    from paddle_tpu.ops import ragged_paged_attention as RPA

    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    rows, qb, h, hk, d = (8, 16, 16, 8, 128) if on_tpu \
        else (4, 8, 4, 2, 32)
    page, w = (64, 32) if on_tpu else (8, 8)
    num_pages = rows * w + 8
    q = jnp.asarray(rng.randn(rows, qb, h, d), dt)
    kf = jnp.asarray(rng.randn(num_pages, hk, page, d), dt)
    vf = jnp.asarray(rng.randn(num_pages, hk, page, d), dt)
    kq, ks = quantize_kv_int8(kf)
    vq, vs = quantize_kv_int8(vf)
    ks, vs = ks[..., None], vs[..., None]
    tables = jnp.asarray(rng.permutation(num_pages)[:rows * w]
                         .reshape(rows, w), jnp.int32)
    q_lens = np.asarray([1 if i % 2 else qb for i in range(rows)],
                        np.int32)
    kv = np.maximum(rng.randint(page, page * w + 1, (rows,))
                    .astype(np.int32), q_lens)
    q_starts = jnp.asarray(kv - q_lens)
    kv_lens, q_lens = jnp.asarray(kv), jnp.asarray(q_lens)

    ref = jax.jit(RPA.ragged_paged_attention_xla)(
        q, kf, vf, tables, kv_lens, q_starts, q_lens)
    got = jax.jit(_q8_attention_fn(RPA))(
        q, kq, vq, ks, vs, tables, kv_lens, q_starts, q_lens)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    scale = float(jnp.max(jnp.abs(ref.astype(jnp.float32))))

    model.eval()
    kw = dict(max_batch=2, page_size=16 if on_tpu else 8, num_pages=64,
              max_pages_per_seq=16, chunk_block=8, chunk_budget=16,
              prefix_cache=False)
    rng2 = np.random.RandomState(1)
    v = model.config.vocab_size
    prompts = [rng2.randint(0, v, (12,)).tolist() for _ in range(2)]
    new_toks = 64 if on_tpu else 24
    q8e = LlamaServingEngine(model, kv_dtype="int8", **kw)
    q8e.generate(prompts, max_new_tokens=q8e.decode_ticks + 2)
    t0 = time.perf_counter()
    outs = q8e.generate(prompts, max_new_tokens=new_toks)
    dt_q8 = time.perf_counter() - t0
    q8_bytes = q8e.kv_bytes_per_token
    q8e.close()
    fpe = LlamaServingEngine(model, **kw)
    fp_bytes = fpe.kv_bytes_per_token
    fpe.close()
    model.train()
    return {
        "kv_int8_max_err": round(err, 5),
        "kv_int8_parity_ok": bool(err < 0.05 * max(scale, 1.0)),
        "kv_int8_capacity_x": round(fp_bytes / q8_bytes, 3),
        "kv_page_bytes_per_token": q8_bytes,
        "kv_fp_page_bytes_per_token": fp_bytes,
        "kv_int8_tokens_per_sec": round(
            sum(len(o) for o in outs) / dt_q8, 1),
    }


def _q8_attention_fn(RPA):
    """jit-able int8 ragged attention entry (module-level impl, scale
    operands positional)."""
    def fn(q, kq, vq, ks, vs, tables, kv_lens, q_starts, q_lens):
        return RPA._ragged_impl_q8(
            q, kq, vq, ks, vs, tables, kv_lens, q_starts, q_lens,
            scale=1.0 / float(np.sqrt(q.shape[-1])))
    return fn


def bench_weight_int8(model, on_tpu=True):
    """Weight-only int8 serving gates (ROADMAP item 3, weight side;
    ``paddle_tpu/quant``).

    - ``weight_int8_greedy_match`` / ``weight_int8_parity_ok``: the
      bundled-prompt quality gate (``quant/quality.py``) on a briefly
      prompt-fitted copy of the bench model — greedy-match >= 0.99 and
      logits error within the 0.05x-scale budget (the stated bars;
      random-init models measure tie-breaking noise instead, see
      ``quality.fit_on_prompts``).
    - ``weight_int8_capacity_x``: bf16 weight bytes / as-served bytes
      (int8 + f32 scale sidecars + the float leftovers — embeddings,
      norms, lm_head — all counted). ~2x on real configs where
      projections dominate; the small-vocab bench config lands lower
      because its embedding slice is proportionally large, so the gate
      is >= 1.4.
    - ``weight_int8_dequant_ms`` vs ``weight_int8_dequant_xla_ms``:
      fused (in-VMEM dequant) Pallas kernel vs the exact XLA
      formulation on the model's MLP projection shape (TPU only).
    - ``weight_int8_tokens_per_sec`` / ``weight_bf16_tokens_per_sec``:
      e2e serving throughput both paths, plus
      ``weight_int8_token_match`` (greedy e2e agreement)."""
    import copy

    import jax
    import jax.numpy as jnp

    from paddle_tpu.inference.serving import LlamaServingEngine
    from paddle_tpu.quant import quality
    from paddle_tpu.quant.format import (quantize_model, quantize_weight,
                                         serving_weight_bytes)
    from paddle_tpu.quant.kernels import _dequant_matmul

    block = 128 if on_tpu else 64

    # -- quality gate on prompt-fitted copies --------------------------
    mfp = copy.deepcopy(model)
    quality.fit_on_prompts(mfp, steps=40)
    mfp.eval()
    mq = copy.deepcopy(mfp)
    quantize_model(mq, block=block)
    rep = quality.logits_quality(mfp, mq)

    # -- capacity: judged against the bf16 counterfactual --------------
    if hasattr(mq, "bfloat16"):
        mcap = copy.deepcopy(mq).bfloat16()   # int8 buffers survive
    else:
        mcap = mq
    actual, bf16_base, _ = serving_weight_bytes(mcap)
    capacity_x = bf16_base / max(actual, 1)

    # -- fused vs XLA dequant-matmul micro-bench (TPU only) ------------
    h = model.config.hidden_size
    inter = model.config.intermediate_size
    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    wq, ws = quantize_weight(
        jnp.asarray(rng.randn(h, inter) * 0.05, jnp.float32), block)
    xs = jnp.asarray(rng.randn(256 if on_tpu else 16, h), dt)
    dq_ms = {}
    iters = 20 if on_tpu else 2
    for key, uk in (("weight_int8_dequant_ms", True),
                    ("weight_int8_dequant_xla_ms", False)):
        if uk and not on_tpu:
            continue    # interpret-mode timing is meaningless
        f = jax.jit(lambda a, q, s, uk=uk: _dequant_matmul(
            a, q, s, block, use_kernel=uk))
        f(xs, wq, ws).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(iters):
            y = f(xs, wq, ws)
        y.block_until_ready()
        dq_ms[key] = round((time.perf_counter() - t0) / iters * 1e3, 4)

    # -- e2e serving throughput, both paths ----------------------------
    kw = dict(max_batch=2, page_size=16 if on_tpu else 8, num_pages=64,
              max_pages_per_seq=16, chunk_block=8, chunk_budget=16,
              prefix_cache=False)
    v = model.config.vocab_size
    prompts = [p[:12] for p in quality.bundled_prompt_ids(v)[:2]]
    new_toks = 64 if on_tpu else 24

    q8e = LlamaServingEngine(mq, **kw)      # pre-quantized: honored
    q8_bytes = q8e.weight_bytes_per_param
    q8e.generate(prompts, max_new_tokens=q8e.decode_ticks + 2)
    t0 = time.perf_counter()
    outs_q8 = q8e.generate(prompts, max_new_tokens=new_toks)
    dt_q8 = time.perf_counter() - t0
    q8e.close()

    fpe = LlamaServingEngine(mfp, **kw)
    fpe.generate(prompts, max_new_tokens=fpe.decode_ticks + 2)
    t0 = time.perf_counter()
    outs_fp = fpe.generate(prompts, max_new_tokens=new_toks)
    dt_fp = time.perf_counter() - t0
    fpe.close()

    tok_match = sum(a == b for of, oq in zip(outs_fp, outs_q8)
                    for a, b in zip(of, oq))
    tok_total = max(sum(len(o) for o in outs_fp), 1)

    out = {
        "weight_int8_greedy_match": round(rep["greedy_match"], 4),
        "weight_int8_logits_max_err": round(rep["max_err"], 5),
        "weight_int8_parity_ok": bool(rep["passes"]),
        "weight_int8_capacity_x": round(capacity_x, 3),
        "weight_int8_capacity_ok": bool(capacity_x >= 1.4),
        "serving_weight_bytes_per_param": round(q8_bytes, 4),
        "weight_int8_token_match": round(tok_match / tok_total, 4),
        "weight_int8_tokens_per_sec": round(
            sum(len(o) for o in outs_q8) / dt_q8, 1),
        "weight_bf16_tokens_per_sec": round(
            sum(len(o) for o in outs_fp) / dt_fp, 1),
    }
    out.update(dq_ms)
    return out


def bench_restart_ttft(on_tpu=True):
    """Cold vs warm-cache restart-to-first-token for a SUBPROCESS
    serving replica (ROADMAP item 5 / PR 7): a worker process is
    started against an empty persistent compile cache (cold — it pays
    the full XLA compile bill before its self-probe's first token),
    SIGKILLed, and replaced by the supervisor; the replacement
    pre-warms the registry-recorded shape buckets against the now-warm
    cache. The delta is what makes kill-and-replace a non-event."""
    import shutil
    import tempfile

    from paddle_tpu.inference.cluster import ServingCluster

    root = tempfile.mkdtemp(prefix="paddle_tpu_restart_bench_")
    cfg = (dict(vocab_size=8192, hidden_size=512, intermediate_size=1408,
                num_hidden_layers=8, num_attention_heads=8,
                num_key_value_heads=4) if on_tpu else
           dict(vocab_size=512, hidden_size=256, intermediate_size=512,
                num_hidden_layers=4, num_attention_heads=4,
                num_key_value_heads=2))
    spec = {"model": {"kind": "tiny_llama", "seed": 0, "config": cfg},
            "engine": dict(max_batch=4 if on_tpu else 2,
                           page_size=16 if on_tpu else 8,
                           num_pages=128 if on_tpu else 48)}
    env = {"PADDLE_TPU_COMPILE_CACHE_DIR": os.path.join(root, "cache"),
           "PADDLE_TPU_SHAPE_REGISTRY": os.path.join(root, "shapes.json")}
    cluster = ServingCluster(
        engine_spec=spec, num_replicas=1,
        store_path=os.path.join(root, "members"), ttl=30.0,
        monitor_interval=0.05, restart_backoff=0.05,
        spawn_grace=900.0, subprocess_env=env).start()
    try:
        deadline = time.time() + 900
        rep = cluster.replicas()["replica-0"]
        while not rep.ready() and time.time() < deadline:
            time.sleep(0.2)
        cold = rep.restart_ttft
        # a little real load so decode lands in the shape registry via
        # actual dispatches, then SIGKILL: the supervised replacement
        # path IS the measured path
        cluster.submit([1, 2, 3], max_new_tokens=4).result(timeout=600)
        pid = rep._proc.pid
        rep.kill()
        deadline = time.time() + 900
        while time.time() < deadline:
            rep = cluster.replicas()["replica-0"]
            if rep.alive() and rep.ready() and rep._proc.pid != pid:
                break
            time.sleep(0.2)
        warm = rep.restart_ttft
        hits = (rep.cache_stats or {}).get("hits", 0)
    finally:
        cluster.stop()
        shutil.rmtree(root, ignore_errors=True)
    return {
        "serving_restart_cold_ttft_ms": round(cold * 1e3, 1),
        "serving_restart_ttft_ms": round(warm * 1e3, 1),
        "serving_restart_ttft_speedup": round(cold / max(warm, 1e-9), 3),
        "serving_restart_cache_hits": hits,
    }


def bench_store_failover(on_tpu=True):
    """Control-plane store cost (ROADMAP item 4a / PR 20): per-op
    latency of the membership surface on the shared-filesystem
    FileStore vs the TCP LeaseStore, and how long membership takes to
    RE-CONVERGE after the lease server is stopped and restarted on the
    same port (client reconnect + fresh registration + a scan that
    shows every host again) — the number the chaos drills bound."""
    import shutil
    import tempfile

    from paddle_tpu.distributed.net_store import (LeaseStore,
                                                  LeaseStoreServer)
    from paddle_tpu.distributed.watchdog import FileStore

    iters = 300 if on_tpu else 60
    root = tempfile.mkdtemp(prefix="paddle_tpu_store_bench_")

    def _ops_ms(store):
        # one warm-up round so neither backend pays its first-touch
        # cost (fs clock probe / TCP session handshake) in the loop
        store.register("h0")
        store.heartbeat("h0")
        store.hosts()
        t0 = time.perf_counter()
        for _ in range(iters):
            store.heartbeat("h0")
            store.hosts()
        return (time.perf_counter() - t0) / (2 * iters) * 1e3

    try:
        file_ms = _ops_ms(FileStore(os.path.join(root, "m"), ttl=30.0))
        srv = LeaseStoreServer()
        port = srv.port
        st = LeaseStore(f"127.0.0.1:{port}", ttl=30.0, retries=6)
        try:
            tcp_ms = _ops_ms(st)
            st.register("h1")
            srv.stop()
            t0 = time.perf_counter()
            srv = LeaseStoreServer(port=port)
            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    st.register("h0")
                    st.register("h1")
                    if st.hosts() == ["h0", "h1"]:
                        break
                except OSError:
                    pass
                time.sleep(0.005)
            reconverge_ms = (time.perf_counter() - t0) * 1e3
        finally:
            st.close()
            srv.stop()
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "store_file_op_ms": round(file_ms, 4),
        "store_tcp_op_ms": round(tcp_ms, 4),
        "store_reconverge_ms": round(reconverge_ms, 2),
    }


def bench_kv_tiering(model, on_tpu=True):
    """Host-DRAM KV tiering (ROADMAP item 5a): time-to-next-token of a
    RESUMED request (H2D page restore + one decode) vs the pre-tier
    evict fallback (full re-prefill + one decode) for the same prompt
    on the same warmed engine. The speedup is the pause rung's whole
    value proposition: preserving decoded K/V beats regenerating it,
    and the gap widens with context length."""
    from paddle_tpu.inference.serving import LlamaServingEngine, Request

    model.eval()
    prompt_len = 384 if on_tpu else 96
    prompt = [int(t) for t in (np.arange(prompt_len) % 251 + 1)]
    e = LlamaServingEngine(
        model, max_batch=2, page_size=16 if on_tpu else 8,
        num_pages=128 if on_tpu else 48, kv_tier=True,
        prefix_cache=False)
    try:
        def _next_token(req):
            """Steps until ``req`` emits one more token; seconds."""
            n0 = len(req.output_ids)
            t0 = time.perf_counter()
            while len(req.output_ids) <= n0 and not req.done:
                e.step()
            return time.perf_counter() - t0

        # warm every measured path (prefill, decode, D2H export, H2D
        # restore scatter) so neither arm pays a compile
        w = Request(prompt, max_new_tokens=8)
        e.add_request(w)
        while len(w.output_ids) < 2:
            e.step()
        with e._lock:
            e._pause(w)
        while not w.done:
            e.step()

        # arm 1: pause -> resume (restore restores the decoded pages)
        r = Request(prompt, max_new_tokens=8)
        e.add_request(r)
        while len(r.output_ids) < 2:
            e.step()
        with e._lock:
            e._pause(r)
        resumed = _next_token(r)
        while not r.done:
            e.step()

        # arm 2: the pre-tier fallback — evict resets to a from-scratch
        # re-prefill of the whole prompt
        r2 = Request(prompt, max_new_tokens=8, retry_budget=2)
        e.add_request(r2)
        while len(r2.output_ids) < 2:
            e.step()
        with e._lock:
            e._evict(r2)
        reprefill = _next_token(r2)
        while not r2.done:
            e.step()
        st = e.tier.stats()
    finally:
        e.close()
    return {
        "kv_tier_resumed_ttft_ms": round(resumed * 1e3, 2),
        "kv_tier_reprefill_ttft_ms": round(reprefill * 1e3, 2),
        "kv_tier_resume_speedup": round(
            reprefill / max(resumed, 1e-9), 3),
        "kv_tier_bench_exports": st["exports"],
        "kv_tier_bench_restores": st["restores"],
    }


# second MFU entry (~0.7-0.9B): best-first with HBM fallbacks
LARGE_CANDIDATES = [
    (dict(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
          num_hidden_layers=12, num_attention_heads=16,
          num_key_value_heads=8, max_position_embeddings=4096), 3, 2048),
    (dict(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
          num_hidden_layers=16, num_attention_heads=16,
          num_key_value_heads=8, max_position_embeddings=4096), 2, 2048),
    (dict(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
          num_hidden_layers=12, num_attention_heads=16,
          num_key_value_heads=8, max_position_embeddings=4096), 2, 2048),
]


def bench_frontend(model, on_tpu=True):
    """The HTTP front door under a replayed two-tenant trace: a
    batch-class tenant floods `/v1/completions` while a premium tenant
    trickles streaming requests. Reports per-tenant TTFT/TPOT p99
    (client-observed, through real sockets), shed counts, and
    ``frontend_stream_overhead_frac`` — how much of the in-process
    token rate the HTTP+SSE layer costs. The gate ``frontend_qos_ok``
    requires the flood to be shed while every premium request
    completes in full."""
    import socket
    import threading
    import urllib.error
    import urllib.request

    from paddle_tpu.inference.frontend import ServingFrontend
    from paddle_tpu.inference.qos import QosGate, Tenant
    from paddle_tpu.inference.serving import LlamaServingEngine

    model.eval()
    max_batch = 8 if on_tpu else 2
    new_tokens = 48 if on_tpu else 8
    n_prem = 8 if on_tpu else 3
    n_flood = 24 if on_tpu else 8
    engine = LlamaServingEngine(model, max_batch=max_batch,
                                page_size=64,
                                num_pages=max_batch * 8 + 8,
                                max_pages_per_seq=8, prefix_cache=False)
    rng = np.random.RandomState(0)
    v = model.config.vocab_size
    prompts = [rng.randint(0, v, (24,)).tolist()
               for _ in range(max(n_prem, 4))]

    # in-process baseline at the same geometry (warm first)
    engine.generate(prompts[:2], max_new_tokens=2)
    t0 = time.perf_counter()
    outs = engine.generate(prompts, max_new_tokens=new_tokens)
    inproc_tps = sum(len(o) for o in outs) / (time.perf_counter() - t0)

    # flood refills slowly enough that replaying the trace overruns
    # its share; premium is effectively unmetered
    gate = QosGate([
        Tenant("prem", tier="premium", rate=10 ** 6,
               ttft_slo=30.0 if not on_tpu else 2.0),
        Tenant("flood", tier="batch", rate=new_tokens * 2,
               burst=new_tokens * 2),
    ])
    fe = ServingFrontend(engine=engine, qos=gate)
    fe.start(port=0)

    def post(body, tenant):
        req = urllib.request.Request(
            f"http://127.0.0.1:{fe.port}/v1/completions",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     "X-Tenant": tenant})
        with urllib.request.urlopen(req, timeout=300) as r:
            return json.loads(r.read())

    def stream(body, tenant):
        """(ttft, n_tokens, wall) client-observed over a raw socket."""
        payload = json.dumps(dict(body, stream=True)).encode()
        sock = socket.create_connection(("127.0.0.1", fe.port),
                                        timeout=300)
        sock.sendall(
            f"POST /v1/completions HTTP/1.1\r\nHost: x\r\n"
            f"X-Tenant: {tenant}\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n".encode()
            + payload)
        rf = sock.makefile("rb")
        t0 = time.perf_counter()
        rf.readline()
        while rf.readline().strip():
            pass
        ttft, n = None, 0
        for line in rf:
            line = line.strip()
            if not line.startswith(b"data: ") or line == b"data: [DONE]":
                continue
            obj = json.loads(line[len(b"data: "):])
            toks = obj["choices"][0].get("token_ids") or []
            if toks and ttft is None:
                ttft = time.perf_counter() - t0
            n += len(toks)
        wall = time.perf_counter() - t0
        rf.close()
        sock.close()
        return ttft, n, wall

    # warm the door (and the engine's programs) through the real path
    stream({"prompt": prompts[0], "max_tokens": 4}, "prem")

    shed = {"n": 0}
    ok = {"n": 0}

    def flood_worker(k):
        r = np.random.RandomState(100 + k)
        for _ in range(n_flood // 2):
            try:
                post({"prompt": r.randint(0, v, (16,)).tolist(),
                      "max_tokens": new_tokens}, "flood")
                ok["n"] += 1
            except urllib.error.HTTPError:
                shed["n"] += 1

    prem_stats = []
    floods = [threading.Thread(target=flood_worker, args=(k,))
              for k in range(2)]
    t_trace = time.perf_counter()
    for th in floods:
        th.start()
    for i in range(n_prem):
        ttft, n, wall = stream(
            {"prompt": prompts[i % len(prompts)],
             "max_tokens": new_tokens}, "prem")
        prem_stats.append((ttft, n, wall))
    for th in floods:
        th.join()
    trace_wall = time.perf_counter() - t_trace
    fe.stop()
    engine.close()
    model.train()

    ttfts = [s[0] for s in prem_stats if s[0] is not None]
    tpots = [(s[2] - s[0]) / (s[1] - 1) for s in prem_stats
             if s[0] is not None and s[1] > 1]
    prem_tokens = sum(s[1] for s in prem_stats)
    # per-request streamed rate vs the in-process batch rate is not
    # apples to apples under concurrency; use aggregate trace tokens
    http_tokens = prem_tokens + ok["n"] * new_tokens
    http_tps = http_tokens / trace_wall
    prem_complete = all(s[1] == new_tokens for s in prem_stats)
    return {
        "frontend_prem_requests": n_prem,
        "frontend_prem_ttft_p50_ms": round(
            float(np.percentile(ttfts, 50)) * 1e3, 2),
        "frontend_prem_ttft_p99_ms": round(
            float(np.percentile(ttfts, 99)) * 1e3, 2),
        "frontend_prem_tpot_p99_ms": round(
            float(np.percentile(tpots, 99)) * 1e3, 2) if tpots else -1.0,
        "frontend_flood_shed": shed["n"],
        "frontend_flood_completed": ok["n"],
        "frontend_http_tokens_per_sec": round(http_tps, 1),
        "frontend_inproc_tokens_per_sec": round(inproc_tps, 1),
        "frontend_stream_overhead_frac": round(
            max(0.0, 1.0 - http_tps / max(inproc_tps, 1e-9)), 3),
        "frontend_qos_ok": bool(shed["n"] > 0 and prem_complete),
    }


def bench_trace_overhead(model, on_tpu=True):
    """Distributed-tracing tax at the cluster tier: tokens/sec through
    a ServingCluster with a per-request trace context active (route +
    admit + first-token spans mint and record) vs plain dispatch.
    ``trace_overhead_frac`` is the fractional rate loss; the gate
    ``trace_overhead_ok`` requires <= 3%."""
    from paddle_tpu.inference.cluster import ServingCluster
    from paddle_tpu.inference.serving import LlamaServingEngine
    from paddle_tpu.observability import tracing as _tracing

    model.eval()
    # each timed run must be long enough that per-span cost (~µs) is
    # resolvable above scheduler jitter — sub-second runs gate on noise
    max_batch = 8 if on_tpu else 2
    new_tokens = 48 if on_tpu else 64
    n_reqs = 24 if on_tpu else 12
    rounds = 3 if on_tpu else 4
    cluster = ServingCluster(
        engine_factory=lambda: LlamaServingEngine(
            model, max_batch=max_batch, page_size=64,
            num_pages=max_batch * 8 + 8, max_pages_per_seq=8,
            prefix_cache=False),
        num_replicas=1, max_backlog=n_reqs * 2)
    cluster.start()
    rng = np.random.RandomState(0)
    v = model.config.vocab_size
    prompts = [rng.randint(0, v, (24,)).tolist() for _ in range(n_reqs)]

    def run(traced):
        reqs = []
        t0 = time.perf_counter()
        for p in prompts:
            if traced:
                with _tracing.activate(_tracing.mint()):
                    reqs.append(cluster.submit(
                        p, max_new_tokens=new_tokens))
            else:
                reqs.append(cluster.submit(p, max_new_tokens=new_tokens))
        for r in reqs:
            r.wait(300.0)
        wall = time.perf_counter() - t0
        return sum(len(r.output_ids) for r in reqs) / wall

    run(False)                  # warm: compile the serving programs
    on, off = [], []
    for _ in range(rounds):     # interleave to share thermal/jit drift
        off.append(run(False))
        on.append(run(True))
    cluster.stop()
    model.train()
    # best-of per mode: external noise (scheduler preemption, a
    # neighbor's compile) only ever SLOWS a run, so the per-mode max is
    # the noise-robust estimate of true capability — a mean would gate
    # on whichever mode drew the unluckier rounds
    tps_on, tps_off = max(on), max(off)
    frac = round(max(0.0, 1.0 - tps_on / max(tps_off, 1e-9)), 3)
    return {
        "trace_tokens_per_sec_on": round(tps_on, 1),
        "trace_tokens_per_sec_off": round(tps_off, 1),
        "trace_overhead_frac": frac,
        "trace_overhead_ok": bool(frac <= 0.03),
    }


def bench_perf_overhead(model, on_tpu=True):
    """Perf-attribution tax at the cluster tier: tokens/sec through a
    ServingCluster with the roofline/sentinel layer active (host timer
    every dispatch, aggressive 50 ms fence throttle) vs
    ``PADDLE_TPU_PERF=0``. ``perf_overhead_frac`` is the fractional
    rate loss; the gate ``perf_overhead_ok`` requires <= 3% — the same
    bar as ``trace_overhead_ok``. Also reports the roofline readings
    attribution produced for the busiest serving callable during the
    run (the numbers an on-chip sweep publishes as
    ``paddle_tpu_perf_*`` gauges)."""
    from paddle_tpu.inference.cluster import ServingCluster
    from paddle_tpu.inference.serving import LlamaServingEngine
    from paddle_tpu.observability import perf as _perf

    model.eval()
    max_batch = 8 if on_tpu else 2
    new_tokens = 48 if on_tpu else 64
    n_reqs = 24 if on_tpu else 12
    rounds = 3 if on_tpu else 4
    cluster = ServingCluster(
        engine_factory=lambda: LlamaServingEngine(
            model, max_batch=max_batch, page_size=64,
            num_pages=max_batch * 8 + 8, max_pages_per_seq=8,
            prefix_cache=False),
        num_replicas=1, max_backlog=n_reqs * 2)
    cluster.start()
    rng = np.random.RandomState(0)
    v = model.config.vocab_size
    prompts = [rng.randint(0, v, (24,)).tolist() for _ in range(n_reqs)]

    saved = {k: os.environ.get(k) for k in
             ("PADDLE_TPU_PERF", "PADDLE_TPU_PERF_FENCE_INTERVAL")}

    def mode(attribution_on):
        if attribution_on:
            os.environ["PADDLE_TPU_PERF"] = "1"
            os.environ["PADDLE_TPU_PERF_FENCE_INTERVAL"] = "0.05"
        else:
            os.environ["PADDLE_TPU_PERF"] = "0"

    def run():
        reqs = []
        t0 = time.perf_counter()
        for p in prompts:
            reqs.append(cluster.submit(p, max_new_tokens=new_tokens))
        for r in reqs:
            r.wait(300.0)
        wall = time.perf_counter() - t0
        return sum(len(r.output_ids) for r in reqs) / wall

    try:
        mode(True)
        run()               # warm: compile + populate roofline gauges
        on, off = [], []
        for _ in range(rounds):  # interleave to share thermal/jit drift
            mode(False)
            off.append(run())
            mode(True)
            on.append(run())
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
    cluster.stop()
    model.train()
    # best-of per mode (see bench_trace_overhead): noise only slows
    tps_on, tps_off = max(on), max(off)
    frac = round(max(0.0, 1.0 - tps_on / max(tps_off, 1e-9)), 3)
    out = {
        "perf_tokens_per_sec_on": round(tps_on, 1),
        "perf_tokens_per_sec_off": round(tps_off, 1),
        "perf_overhead_frac": frac,
        "perf_overhead_ok": bool(frac <= 0.03),
    }
    serving = {n: s for n, s in _perf.recorders().items()
               if n.startswith("serving.")}
    if serving:
        name, st = max(serving.items(),
                       key=lambda kv: kv[1]["samples"])
        peak_flops, peak_bw, _ = _perf.device_peaks()
        out["perf_serving_callable"] = name
        if st["device_ewma_ms"]:
            dev_s = st["device_ewma_ms"] / 1e3
            out["perf_serving_device_ms"] = round(
                st["device_ewma_ms"], 3)
            if st["flops"]:
                out["perf_serving_flops_frac"] = round(
                    min(1.0, st["flops"] / (dev_s * peak_flops)), 5)
            if st["bytes_accessed"]:
                out["perf_serving_hbm_frac"] = round(
                    min(1.0, st["bytes_accessed"] / (dev_s * peak_bw)),
                    5)
    return out


def bench_fused_ce(on_tpu=True):
    """Chunked fused cross-entropy lm-head vs the materialized logits
    path at an 8k+ vocab config: fwd+bwd step time, static peak-memory
    delta (``memory_analysis`` temp bytes of the two compiled
    programs), and the ``fused_ce_parity_ok`` gate (loss + both grads
    match at tolerance). ``fused_ce_mem_ok`` (chunked temp bytes
    STRICTLY below materialized) is asserted on TPU; on CPU the same
    comparison is reported — XLA:CPU buffer assignment is a faithful
    proxy for the [N, V] elision."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.fused_linear_cross_entropy import (
        _loss_raw, default_chunk, supported)

    if on_tpu:
        n, d, v = 4096, 2048, 32000
        iters = 20
        chunk = min(default_chunk(), v)
    else:
        n, d, v = 256, 128, 8192
        iters = 3
        chunk = min(default_chunk(), 2048)   # real multi-chunk smoke
    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(n, d).astype(np.float32) * 0.02)
    w = jnp.asarray(rng.randn(d, v).astype(np.float32) * 0.02)
    lab = jnp.asarray(rng.randint(0, v, (n,)).astype(np.int32))

    def materialized(h, w, lab):
        lg = jnp.matmul(h.astype(jnp.float32), w.astype(jnp.float32))
        logp = jax.nn.log_softmax(lg, axis=-1)
        nll = -jnp.take_along_axis(logp, lab[:, None], axis=1)[:, 0]
        return jnp.mean(nll)

    def fused(h, w, lab):
        return _loss_raw(h, w, lab, chunk, -100, supported(h, w))

    out = {"fused_ce_vocab": v, "fused_ce_tokens": n,
           "fused_ce_chunk": chunk,
           "fused_ce_kernel": bool(supported(h, w))}

    results = {}
    for key, fn in (("fused", fused), ("materialized", materialized)):
        vg = jax.jit(jax.value_and_grad(fn, argnums=(0, 1)))
        compiled = vg.lower(h, w, lab).compile()
        try:
            ma = compiled.memory_analysis()
            out[f"{key}_ce_peak_temp_bytes"] = int(ma.temp_size_in_bytes)
        except Exception:
            pass
        (loss, grads) = compiled(h, w, lab)
        jax.block_until_ready(grads)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, grads = compiled(h, w, lab)
        jax.block_until_ready(grads)
        results[key] = (float(loss), grads)
        out[f"{key}_ce_step_ms"] = round(
            (time.perf_counter() - t0) / iters * 1e3, 3)

    lf, gf = results["fused"]
    lm, gm = results["materialized"]
    scale_h = float(jnp.max(jnp.abs(gm[0]))) or 1.0
    scale_w = float(jnp.max(jnp.abs(gm[1]))) or 1.0
    parity = (abs(lf - lm) < 1e-4 * max(abs(lm), 1.0)
              and float(jnp.max(jnp.abs(gf[0] - gm[0]))) < 1e-4 * scale_h
              and float(jnp.max(jnp.abs(gf[1] - gm[1]))) < 1e-4 * scale_w)
    out["fused_ce_parity_ok"] = bool(parity)
    out["fused_ce_speedup"] = round(
        out["materialized_ce_step_ms"] / max(out["fused_ce_step_ms"],
                                             1e-9), 3)
    if "fused_ce_peak_temp_bytes" in out \
            and "materialized_ce_peak_temp_bytes" in out:
        mem_ok = out["fused_ce_peak_temp_bytes"] \
            < out["materialized_ce_peak_temp_bytes"]
        out["fused_ce_mem_ok"] = bool(mem_ok)
        if on_tpu:
            assert mem_ok, (
                "chunked fused CE must beat the materialized path's "
                f"peak temp bytes: {out['fused_ce_peak_temp_bytes']} vs "
                f"{out['materialized_ce_peak_temp_bytes']}")
    return out


def bench_moe_train(on_tpu=True):
    """MoE pretraining scaling on ONE device: a compiled train step per
    expert count (same token budget — top-k work is constant, only the
    expert POOL grows), reporting step time per E and
    ``moe_train_scaling_frac`` = (t_max/t_min) / (E_max/E_min). A
    fraction well below 1.0 is the ROADMAP item-5 sublinear gate: step
    time must not grow proportionally with the expert pool. (The
    expert-PARALLEL `shard_llama(ep_axis=...)` path is exercised by
    tests/test_fused_ce.py on the CPU mesh, not by this bench.)"""
    import gc

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        counts = (8, 16, 32)
        cfg_kw = dict(vocab_size=8192, hidden_size=1024,
                      intermediate_size=2816, num_hidden_layers=4,
                      num_attention_heads=8, num_key_value_heads=4,
                      max_position_embeddings=2048)
        batch, seq, steps = 2, 1024, 6
    else:
        counts = (2, 4, 8)
        cfg_kw = dict(vocab_size=512, hidden_size=128,
                      intermediate_size=256, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=512)
        batch, seq, steps = 2, 64, 2

    out = {"moe_train_experts": list(counts)}
    rng = np.random.RandomState(0)
    times = []
    for e in counts:
        paddle.seed(0)
        cfg = LlamaConfig(**cfg_kw)
        cfg.moe_num_experts = e
        cfg.moe_top_k = 2
        model = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())

        def step(ids, labels):
            loss, _ = model(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        compiled = paddle.jit.to_static(step, state=[model, opt],
                                        warmup="once",
                                        donate_inputs=True)

        def batch_of():
            ids = rng.randint(0, cfg.vocab_size,
                              (batch, seq + 1)).astype(np.int64)
            return (paddle.to_tensor(ids[:, :-1]),
                    paddle.to_tensor(ids[:, 1:]))

        compiled(*batch_of())     # eager warmup
        compiled(*batch_of())     # compile
        compiled(*batch_of())     # steady state
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = compiled(*batch_of())
        float(loss)               # host sync
        ms = (time.perf_counter() - t0) / steps * 1e3
        times.append(ms)
        out[f"moe_train_step_ms_e{e}"] = round(ms, 3)
        del model, opt, compiled
        gc.collect()

    growth = times[-1] / max(times[0], 1e-9)
    pool_growth = counts[-1] / counts[0]
    out["moe_train_scaling_frac"] = round(growth / pool_growth, 3)
    out["moe_train_sublinear_ok"] = bool(growth < pool_growth)
    return out


def bench_train_large(steps=6):
    """Second MFU entry at the largest config that fits one chip
    (VERDICT r4 weak #2): ~1B-class Llama. Keys prefixed `large_`."""
    import gc

    # release the decode/serving model pinned by the earlier blocks —
    # its 2 GB of fp32 params would OOM the ~11 GB large config
    bench_train_step.last_model = None
    gc.collect()
    for cfg_kw, batch, seq in LARGE_CANDIDATES:
        try:
            r = bench_train_step(cfg_kw, batch, seq, steps=steps)
            bench_train_step.last_model = None
            import gc
            gc.collect()
            return {"large_" + k: v for k, v in r.items()
                    if k in ("model", "n_params", "batch", "seq",
                             "step_time_ms", "tokens_per_sec", "mfu",
                             "compile_s")}
        except Exception as e:  # OOM etc: next size down
            log(f"large config failed: {e!r:.200}")
    return {"large_error": "no large config fit"}


# (config kwargs, batch, seq) from largest to smallest; the first that
# completes on this chip wins (HBM-driven fallback)
CANDIDATES = [
    (dict(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
          num_hidden_layers=8, num_attention_heads=16, num_key_value_heads=8,
          max_position_embeddings=4096), 3, 2048),
    (dict(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
          num_hidden_layers=8, num_attention_heads=16, num_key_value_heads=8,
          max_position_embeddings=4096), 2, 2048),
    (dict(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
          num_hidden_layers=4, num_attention_heads=16, num_key_value_heads=8,
          max_position_embeddings=4096), 2, 2048),
    (dict(vocab_size=8192, hidden_size=1024, intermediate_size=2816,
          num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
          max_position_embeddings=2048), 2, 1024),
]


def _run_section(result, key, fn, label=None):
    """Run one bench section: merge its dict into ``result``, stamp
    ``<key>_wall_s`` with the section's wall time, and degrade to a
    ``<key>_error`` key on failure (one broken section must not sink
    the whole run — the historical contract of main()'s try blocks)."""
    label = label or key
    t0 = time.perf_counter()
    try:
        result.update(fn())
    except Exception as e:
        log(f"{label} bench failed: {e!r:.300}")
        result[f"{key}_error"] = repr(e)[:200]
    finally:
        result[f"{key}_wall_s"] = round(time.perf_counter() - t0, 3)


def main():
    import jax
    on_tpu = jax.default_backend() == "tpu"
    candidates = CANDIDATES if on_tpu else [
        (dict(vocab_size=512, hidden_size=128, intermediate_size=256,
              num_hidden_layers=2, num_attention_heads=4,
              num_key_value_heads=2, max_position_embeddings=512), 2, 128)]

    result, err = None, None
    for cfg_kw, batch, seq in candidates:
        try:
            result = bench_train_step(cfg_kw, batch, seq,
                                      steps=10 if on_tpu else 2)
            break
        except Exception as e:  # OOM etc.: fall back to the next size
            err = e
            log(f"config h{cfg_kw['hidden_size']}-"
                f"L{cfg_kw['num_hidden_layers']} failed: {e!r:.300}")
    if result is None:
        raise err

    # lambdas read bench_train_step.last_model at CALL time — no local
    # ref lingers to pin the serving model when the large config runs
    _model = lambda: bench_train_step.last_model  # noqa: E731

    if on_tpu:
        _run_section(result, "flash", bench_flash,
                     label="flash micro")
    else:
        _run_section(
            result, "flash",
            lambda: bench_flash(batch=1, seq=256, heads=4, kv_heads=2,
                                dim=64, iters=2),
            label="flash micro")
    _run_section(
        result, "paged",
        bench_paged if on_tpu else
        lambda: bench_paged(batch=2, heads=4, kv_heads=2, dim=32,
                            page=8, ctx=64, iters=2))
    _run_section(
        result, "ragged",
        bench_ragged if on_tpu else
        lambda: bench_ragged(rows=4, qb=8, heads=4, kv_heads=2,
                             dim=32, page=8, ctx=64, iters=2))
    _run_section(
        result, "decode",
        lambda: bench_decode(_model(), batch=16 if on_tpu else 1,
                             prompt=128 if on_tpu else 16,
                             new_tokens=64 if on_tpu else 4))
    _run_section(
        result, "distributed",
        lambda: bench_distributed_onchip(iters=10 if on_tpu else 1),
        label="distributed on-chip")
    _run_section(
        result, "serving",
        lambda: bench_serving(
            _model(), n_requests=24 if on_tpu else 2,
            new_tokens=48 if on_tpu else 4,
            max_batch=16 if on_tpu else 2,
            decode_ceiling=result.get("decode_tokens_per_sec"),
            on_tpu=on_tpu))
    _run_section(
        result, "fused_kv",
        (lambda: bench_fused_kv(_model(), on_tpu=True)) if on_tpu else
        lambda: bench_fused_kv(_model(), rows=4, qb=8, heads=4,
                               kv_heads=2, dim=32, page=8, ctx=64,
                               iters=2, on_tpu=False),
        label="fused-kv")
    _run_section(
        result, "fused_rope",
        (lambda: bench_fused_rope(_model(), on_tpu=True)) if on_tpu
        else lambda: bench_fused_rope(_model(), rows=4, qb=8, heads=4,
                                      kv_heads=2, dim=32, page=8,
                                      ctx=64, iters=2, on_tpu=False),
        label="fused-rope")
    _run_section(result, "cluster",
                 lambda: bench_prefix_cluster(_model(), on_tpu=on_tpu),
                 label="prefix/cluster")
    _run_section(result, "spec",
                 lambda: bench_speculative(_model(), on_tpu=on_tpu),
                 label="speculative")
    _run_section(result, "kv_int8",
                 lambda: bench_kv_int8(_model(), on_tpu=on_tpu),
                 label="kv-int8")
    _run_section(result, "weight_int8",
                 lambda: bench_weight_int8(_model(), on_tpu=on_tpu),
                 label="weight-int8")
    _run_section(result, "restart",
                 lambda: bench_restart_ttft(on_tpu=on_tpu),
                 label="restart-ttft")
    _run_section(result, "store_failover",
                 lambda: bench_store_failover(on_tpu=on_tpu),
                 label="store-failover")
    _run_section(result, "kv_tier",
                 lambda: bench_kv_tiering(_model(), on_tpu=on_tpu),
                 label="kv-tier")
    _run_section(result, "frontend",
                 lambda: bench_frontend(_model(), on_tpu=on_tpu))
    _run_section(result, "trace_overhead",
                 lambda: bench_trace_overhead(_model(), on_tpu=on_tpu),
                 label="trace-overhead")
    _run_section(result, "perf_overhead",
                 lambda: bench_perf_overhead(_model(), on_tpu=on_tpu),
                 label="perf-overhead")
    _run_section(result, "fused_ce",
                 lambda: bench_fused_ce(on_tpu=on_tpu),
                 label="fused-ce")
    _run_section(result, "moe_train",
                 lambda: bench_moe_train(on_tpu=on_tpu),
                 label="moe-train")
    if on_tpu:
        # ~11 GB large config: nothing above holds the serving model
        # now (only bench_train_step.last_model pins its params)
        _run_section(result, "large", bench_train_large,
                     label="large-model")

    prov = bench_provenance()
    result["device_kind"] = prov["device_kind"]
    result["jax_version"] = prov["jax_version"]
    result["git_commit"] = prov["git_commit"]

    mfu = result["mfu"]
    line = {"metric": "llama_train_mfu", "value": mfu,
            "unit": "fraction_of_peak",
            "vs_baseline": round(mfu / 0.40, 4)}
    line.update(result)
    print(json.dumps(line), flush=True)
    try:
        write_metrics_snapshot(line)
    except Exception as e:
        log(f"metrics snapshot failed: {e!r:.200}")


def write_metrics_snapshot(result,
                           path="BENCH_observability_snapshot.json"):
    """Publish the per-run bench numbers as observability gauges
    (``bench_<key>``) and write the registry snapshot through
    ``observability.export.json_snapshot`` next to the BENCH_*.json
    outputs — strict JSON (``allow_nan=False``), so downstream scrapers
    consume bench history with the exact parser they point at the
    serving /metrics.json endpoint.

    The document is versioned: ``{"schema_version":
    BENCH_SCHEMA_VERSION, "provenance": bench_provenance(), "metrics":
    [json_snapshot entries]}`` — the shape ``tools/bench_check.py``
    diffs against a committed baseline (it also still reads the
    pre-versioning bare-list snapshots). Returns the path, or None
    under ``PADDLE_TPU_METRICS=0`` (the kill switch writes no
    files)."""
    from paddle_tpu.observability import metrics as om
    from paddle_tpu.observability.export import json_snapshot

    if not om.enabled():
        return None
    reg = om.MetricsRegistry()      # private: bench numbers only
    for key, value in result.items():
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        reg.gauge(f"bench_{key}", "bench.py per-run number") \
            .set(float(value))
    doc = {"schema_version": BENCH_SCHEMA_VERSION,
           "provenance": bench_provenance(),
           "metrics": json_snapshot(reg)}
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, allow_nan=False)
    return path




def bench_distributed_onchip(iters=10):
    """Chip-validate the distributed kernels (VERDICT r4 weak #3): a
    degenerate 1-device mesh still exercises the real TPU lowering of
    the ring-attention block math, the compiled pipeline schedule
    (scan + dynamic indexing), and the MoE dispatch (sort + scatter /
    one-hot einsum) — the paths that previously ran only under the CPU
    test mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    out = {}
    rng = np.random.RandomState(0)

    # --- ring attention (CP ring of 1) vs naive attention ---------------
    from paddle_tpu.distributed.ring_attention import ring_attention
    from paddle_tpu.nn.functional.attention import _naive_attention

    mesh1 = Mesh(np.asarray(jax.devices()[:1]), ("sep",))
    B, S, H, Hk, D = 2, 2048, 8, 4, 128
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, Hk, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, Hk, D), jnp.float32)

    def ring(q, k, v):
        o = ring_attention(q, k, v, mesh1, causal=True)
        return jnp.asarray(getattr(o, "_data", o))

    o_ring = jax.block_until_ready(ring(q, k, v))
    t0 = time.perf_counter()
    for _ in range(iters):
        o_ring = ring(q, k, v)
    jax.block_until_ready(o_ring)
    out["ring_ms"] = round((time.perf_counter() - t0) / iters * 1e3, 3)
    kr = jnp.repeat(k, H // Hk, axis=2)
    vr = jnp.repeat(v, H // Hk, axis=2)
    o_ref = _naive_attention(q, kr, vr, None, 0.0, True, None)
    o_ref = jnp.asarray(getattr(o_ref, "_data", o_ref))
    err = float(jnp.max(jnp.abs(o_ring - o_ref)))
    scale = float(jnp.max(jnp.abs(o_ref)))
    out["ring_parity_ok"] = bool(err < 0.02 * max(scale, 1.0))

    # --- compiled pipeline schedule (P = 1) -----------------------------
    from paddle_tpu.distributed.pipeline import (pipeline_1f1b,
                                                 pipeline_spmd,
                                                 stack_stage_params)

    meshp = Mesh(np.asarray(jax.devices()[:1]), ("pp",))
    L, Dm, Bt = 4, 256, 32
    params = [{"w": jnp.asarray(rng.randn(Dm, Dm).astype(np.float32)
                                * 0.05)} for _ in range(L)]
    stacked = stack_stage_params(params)

    def stage_fn(p, h):
        def body(h, lp):
            return jnp.tanh(h @ lp["w"]), None
        return jax.lax.scan(body, h, p)[0]

    x = jnp.asarray(rng.randn(Bt, Dm).astype(np.float32))
    y = jnp.asarray(rng.randn(Bt, Dm).astype(np.float32))
    o_pp = pipeline_spmd(stage_fn, stacked, x, mesh=meshp,
                         num_microbatches=4)
    hh = x
    for l in range(L):
        hh = jnp.tanh(hh @ stacked["w"][l])
    err = float(jnp.max(jnp.abs(jnp.asarray(o_pp) - hh)))
    out["pipeline_parity_ok"] = bool(err < 1e-4)

    def loss_fn(h, yy):
        return jnp.mean((h - yy) ** 2)

    loss, grads = pipeline_1f1b(stage_fn, loss_fn, stacked, x, y,
                                mesh=meshp, num_microbatches=4)

    def ref_loss(st):
        hm = x.reshape(4, Bt // 4, Dm)
        ym = y.reshape(4, Bt // 4, Dm)
        ls = []
        for m in range(4):
            hh = hm[m]
            for l in range(L):
                hh = jnp.tanh(hh @ st["w"][l])
            ls.append(loss_fn(hh, ym[m]))
        return jnp.mean(jnp.asarray(ls))

    wl, wg = jax.value_and_grad(ref_loss)(stacked)
    ok = abs(float(loss) - float(wl)) < 1e-4 and bool(
        jnp.max(jnp.abs(grads["w"] - wg["w"])) < 1e-3)
    out["pipeline_1f1b_parity_ok"] = ok

    # --- MoE dispatch: grouped-GEMM vs dense at 64 experts --------------
    # The grouped path (dispatch_mode="ragged") is sort-based routing +
    # the Pallas grouped-GEMM megakernel (ops/grouped_gemm.py; XLA
    # grouped formulation off-TPU). Bar: moe_dispatch_speedup > 1.2 on
    # chip with moe_parity_ok vs the dense GShard formulation; the CPU
    # smoke gate is "not slower than dense". Both the switch (top-1)
    # and gshard (top-2) gates are measured.
    import paddle_tpu as paddle
    from paddle_tpu.incubate.moe import MoELayer

    E, Dm2, N = 64, 512, 4096
    xs = paddle.to_tensor(rng.randn(N, Dm2).astype(np.float32))

    def timed_moe(layer):
        # the layer's own compiled forward (public build_fn: the
        # compile-watched per-token-count program — eager per-op
        # dispatch would measure the host tunnel, not the dispatch
        # math)
        fn = layer.build_fn(N)
        args = (xs._data, layer.gate_weight._data, layer.w1._data,
                layer.b1._data, layer.w2._data, layer.b2._data)
        o, _, _ = fn(*args)
        jax.block_until_ready(o)
        t0 = time.perf_counter()
        for _ in range(iters):
            o, _, _ = fn(*args)
        jax.block_until_ready(o)
        return (time.perf_counter() - t0) / iters * 1e3, o

    out["moe_experts"] = E
    for gate, prefix in (("switch", "moe_"), ("gshard", "moe_gshard_")):
        paddle.seed(3)
        grouped = MoELayer(Dm2, Dm2 * 2, E, gate=gate,
                           dispatch_mode="ragged")
        paddle.seed(3)
        dense = MoELayer(Dm2, Dm2 * 2, E, gate=gate,
                         dispatch_mode="dense")
        grp_ms, o_grp = timed_moe(grouped)
        den_ms, o_den = timed_moe(dense)
        err = float(jnp.max(jnp.abs(o_grp - o_den)))
        scale = float(jnp.max(jnp.abs(o_den)))
        out[prefix + "parity_ok"] = bool(err < 0.02 * max(scale, 1.0))
        out[prefix + "grouped_ms"] = round(grp_ms, 3)
        out[prefix + "dense_ms"] = round(den_ms, 3)
        out[prefix + "dispatch_speedup"] = round(den_ms / grp_ms, 3)
    return out


if __name__ == "__main__":
    main()
