"""``paddle_tpu.native`` — the C++ runtime components.

The reference's runtime around the compute path is C++ (bootstrap store
`phi/core/distributed/store/tcp_store.h:121`, feed threads
`fluid/framework/data_feed.cc`). This package is its TPU-native
equivalent: small, sharp C++ pieces for the host-side control and data
planes, built on demand with g++ (see ``build.py``) and bound via
ctypes. Everything degrades gracefully — ``available()`` is False when
the toolchain is missing and callers fall back to Python paths.

Exports:
- :class:`TCPStore` — rendezvous KV store (master + clients) with
  blocking get/wait, atomic add, and a counter-based barrier.
- :class:`TokenFeed` — mmap'd fixed-size-sample corpus reader with a
  C++ prefetch thread, yielding numpy batches.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from . import build as _build

__all__ = ["available", "TCPStore", "TokenFeed"]


def available():
    return _build.load() is not None


def _lib():
    lib = _build.load()
    if lib is None:
        raise RuntimeError(
            f"paddle_tpu.native unavailable: {_build.load_error()}")
    return lib


class TCPStore:
    """Bootstrap/rendezvous store (reference ``TCPStore``).

    ``is_master=True`` starts the serving thread in this process (rank 0)
    and connects a client to it; workers connect to ``host:port``. All
    values are bytes; ``add`` keys hold a little-endian int64 counter.
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 timeout=30.0):
        lib = _lib()
        self._lib = lib
        self._server = None
        if is_master:
            self._server = lib.pts_store_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            port = lib.pts_store_server_port(self._server)
        self.host, self.port = host, port
        self.timeout = timeout
        self._client = lib.pts_store_connect(
            host.encode(), port, int(timeout * 1000))
        if not self._client:
            if self._server:
                srv, self._server = self._server, None
                lib.pts_store_server_stop(srv)
            raise TimeoutError(
                f"TCPStore: cannot reach master at {host}:{port}")

    @property
    def is_master(self):
        return self._server is not None

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value) \
            if value else None
        if self._lib.pts_store_set(self._client, key.encode(), buf,
                                   len(value)) != 0:
            raise self._unavailable("set")

    def get(self, key, timeout=None):
        t = self.timeout if timeout is None else timeout
        n = ctypes.c_uint64()
        p = self._lib.pts_store_get(self._client, key.encode(),
                                    ctypes.byref(n), int(t * 1000))
        if not p:
            raise TimeoutError(f"TCPStore.get({key!r}) timed out after {t}s")
        try:
            return ctypes.string_at(p, n.value)
        finally:
            self._lib.pts_buf_free(p)

    def add(self, key, delta=1):
        v = self._lib.pts_store_add(self._client, key.encode(), delta)
        if v == -(2 ** 63):
            raise self._unavailable("add")
        return v

    def _unavailable(self, op):
        # typed so no bare transport RuntimeError can reach a serving
        # dispatch path; lazy import avoids a module cycle (net_store
        # imports this package for the optional KV offload)
        from ..distributed.net_store import StoreUnavailableError
        return StoreUnavailableError(f"{self.host}:{self.port}", op,
                                     detail="connection lost")

    def wait(self, keys, timeout=None):
        t = self.timeout if timeout is None else timeout
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            if self._lib.pts_store_wait(self._client, k.encode(),
                                        int(t * 1000)) != 0:
                raise TimeoutError(
                    f"TCPStore.wait({k!r}) timed out after {t}s")

    def delete_key(self, key):
        return self._lib.pts_store_del(self._client, key.encode()) == 0

    def num_keys(self):
        return self._lib.pts_store_numkeys(self._client)

    def barrier(self, world_size, tag="barrier", timeout=None):
        """All ``world_size`` participants block until everyone arrived.
        ``tag`` must be fresh per barrier round (callers use an epoch
        counter)."""
        arrived = self.add(f"_{tag}/count", 1)
        if arrived == world_size:
            self.set(f"_{tag}/done", b"1")
        self.wait(f"_{tag}/done", timeout)

    def close(self):
        if getattr(self, "_client", None):
            self._lib.pts_store_disconnect(self._client)
            self._client = None
        if getattr(self, "_server", None):
            self._lib.pts_store_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class TokenFeed:
    """Prefetching reader over a flat binary corpus of fixed-size samples.

    Yields ``[batch, sample_elems]`` numpy arrays of ``dtype``. The C++
    producer thread stays one ``prefetch_depth`` of batches ahead of the
    training step; each epoch is a fresh (optionally shuffled)
    permutation of all full samples, last partial batch dropped.
    """

    def __init__(self, path, sample_elems, batch_size, dtype=np.int32,
                 shuffle=True, seed=0, prefetch_depth=4, epochs=-1):
        lib = _lib()
        self._lib = lib
        self.dtype = np.dtype(dtype)
        self.sample_elems = int(sample_elems)
        self.batch_size = int(batch_size)
        self._h = lib.pts_feed_open(
            os.fspath(path).encode(), self.sample_elems,
            self.dtype.itemsize, self.batch_size, int(bool(shuffle)),
            int(seed), int(prefetch_depth), int(epochs))
        if not self._h:
            raise ValueError(
                f"TokenFeed: cannot open {path!r} (too small for one "
                f"batch of {batch_size} x {sample_elems} {self.dtype})")

    @property
    def batches_per_epoch(self):
        return self._lib.pts_feed_batches_per_epoch(self._h)

    @property
    def num_samples(self):
        return self._lib.pts_feed_num_samples(self._h)

    def __iter__(self):
        return self

    def __next__(self):
        if not self._h:
            raise StopIteration
        out = np.empty((self.batch_size, self.sample_elems), self.dtype)
        rc = self._lib.pts_feed_next(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if rc != 0:
            raise StopIteration
        return out

    def close(self):
        if getattr(self, "_h", None):
            self._lib.pts_feed_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
