"""paddle_tpu: a TPU-native deep learning framework.

Capability target: PaddlePaddle (reference at `/root/reference`, see
SURVEY.md). Architecture: JAX/XLA/Pallas compute path, eager define-by-run
autograd on a jax.vjp tape, trace-compilation to XLA for performance, and
GSPMD mesh sharding for DP/FSDP/TP/SP/CP/EP parallelism.
"""

from __future__ import annotations

import os as _os

# Multi-host bootstrap MUST precede any jax call that initializes the XLA
# backend (importing the framework draws a PRNG key). The launch CLI
# (`python -m paddle_tpu.distributed.launch`) sets these env vars; plain
# single-process runs skip this entirely. Reference analog:
# parallel.py:943 init_parallel_env over TCPStore — here the JAX
# coordination service.
_distributed_bootstrapped = False
if "PADDLE_LOCAL_RANK" in _os.environ:
    # PADDLE_LOCAL_RANK marks an actual WORKER process (the launch CLI
    # sets it; set it manually when starting workers by hand). The guard
    # keeps the launcher parent — and any tool that merely imports the
    # package on a cluster with PADDLE_* pre-exported — from joining the
    # coordination service and colliding with the real rank.
    from ._bootstrap import bootstrap_distributed as _bd
    _distributed_bootstrapped = _bd()

from . import flags as _flags_mod
from .flags import set_flags, get_flags  # noqa: F401

from .framework import (  # noqa: F401
    Tensor, Parameter, to_tensor, no_grad, enable_grad,
    is_grad_enabled, set_grad_enabled, seed, get_rng_state, set_rng_state,
    in_dynamic_mode, in_pir_mode, in_dynamic_or_pir_mode,
)
from .framework.dtype import (  # noqa: F401
    dtype, float16, float32, float64, bfloat16,
    int8, int16, int32, int64, uint8, bool_ as bool8,
    complex64, complex128,
    get_default_dtype, set_default_dtype, iinfo, finfo,
)
from .framework.dtype import bool_  # noqa: F401

from .tensor import *  # noqa: F401,F403
from . import tensor  # noqa: F401
from .tensor import linalg  # noqa: F401  (paddle.linalg namespace)

from .framework import autograd_engine as _engine
grad = _engine.grad

from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import regularizer  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import observability  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import geometric  # noqa: F401,E402
from . import fft  # noqa: F401,E402
from . import audio  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import signal  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import version  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import hapi  # noqa: F401,E402
from .hapi import Model  # noqa: F401,E402
from .hapi.model_summary import summary, flops  # noqa: F401,E402
from .hapi import hub  # noqa: F401,E402
from .hapi import callbacks  # noqa: F401,E402  (paddle.callbacks)
from . import sysconfig  # noqa: F401,E402
from .distributed.parallel import DataParallel  # noqa: F401,E402
from .device import set_device, get_device, is_compiled_with_cuda  # noqa: F401,E402
from .framework.io import save, load  # noqa: F401,E402

__version__ = "0.1.0"


def disable_static(place=None):
    pass


def enable_static():
    raise NotImplementedError(
        "paddle_tpu is dynamic-first; use paddle_tpu.jit.to_static for "
        "trace-compilation (the XLA path).")


def disable_signal_handler():
    pass


def is_grad_enabled_():
    return is_grad_enabled()
