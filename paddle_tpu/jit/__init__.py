"""``paddle_tpu.jit`` — trace-compilation of imperative train steps to XLA.

Reference capability: `python/paddle/jit/api.py:136` (``to_static``) — the
reference captures Python bytecode (SOT) or rewrites ASTs (dy2static) to
turn eager code into a static program. The TPU-native design needs neither:
eager Tensors carry ``jax.Array`` payloads, so the same tape-recording ops
run unmodified under ``jax.jit`` tracing with tracer payloads. ``to_static``
therefore:

1. **warmup call** — runs the wrapped function eagerly once so lazy state
   (optimizer accumulators, RNG streams) materializes;
2. **trace** — swaps every state Tensor's payload for a jit tracer, replays
   the function (forward + ``loss.backward()`` + ``opt.step()`` all record
   through the same tape), and captures the whole step as ONE pure XLA
   computation ``(state, grads, inputs, lr, key) -> (state', grads',
   outputs, key')``;
3. **steady state** — each call dispatches a single compiled executable
   with donated state buffers (no per-op dispatch, no host round-trips).

The learning rate and PRNG key are scalar *inputs* of the compiled program,
so LR schedules and randomness never retrace.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..framework import random as frandom
from ..framework import amp_state

__all__ = ["to_static", "not_to_static", "ignore_module", "StaticFunction",
           "enable_to_static", "save", "load", "TranslatedLayer"]

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


def _discover_state(fn, extra):
    """Find Layers / Optimizers / Tensors the function closes over.

    The reference discovers program state by tracing variable usage
    (dy2static's ProgramTranslator); here state is the eager objects
    reachable from the function's closure cells, its ``__self__``, and
    anything passed explicitly via ``to_static(state=[...])``.
    """
    from ..nn import Layer
    from ..optimizer import Optimizer

    import types

    seen = set()
    layers, optimizers, tensors = [], [], []

    def visit(obj, depth=0):
        if id(obj) in seen:
            return
        seen.add(id(obj))
        if isinstance(obj, Layer):
            layers.append(obj)
        elif isinstance(obj, Optimizer):
            optimizers.append(obj)
        elif isinstance(obj, Tensor):
            tensors.append(obj)
        elif hasattr(obj, "__state_tensors__"):
            # stateful helpers (e.g. amp.GradScaler) expose their Tensors
            for t in obj.__state_tensors__():
                visit(t, depth)
        elif isinstance(obj, (list, tuple)):
            for e in obj:
                visit(e, depth)
        elif isinstance(obj, dict):
            for e in obj.values():
                visit(e, depth)
        elif depth < 2 and not isinstance(
                obj, (types.ModuleType, types.FunctionType,
                      types.MethodType, type, str, bytes, int, float,
                      bool, complex)) and hasattr(obj, "__dict__"):
            # plain container objects (a Trainer holding .model/.opt):
            # scan one attribute level so state reached through object
            # attributes is not silently missed (the stale-training trap)
            for e in vars(obj).values():
                visit(e, depth + 1)

    for obj in extra or ():
        visit(obj)
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            visit(cell.cell_contents)
        except ValueError:
            pass
    self_obj = getattr(fn, "__self__", None)
    if self_obj is not None:
        visit(self_obj)
    # module-level model/optimizer referenced as globals (the common script
    # pattern): only names the function actually loads, to keep this cheap.
    # visit() does the type filtering — including the holder-object
    # attribute scan, so a module-level Trainer is discovered too
    code = getattr(fn, "__code__", None)
    if code is not None:
        g = getattr(fn, "__globals__", {})
        for name in code.co_names:
            obj = g.get(name)
            if obj is None or isinstance(
                    obj, (types.ModuleType, types.FunctionType,
                          types.BuiltinFunctionType, type, str, bytes,
                          int, float, bool)):
                continue
            if isinstance(obj, (Layer, Optimizer, Tensor, list, tuple,
                                dict)):
                visit(obj)        # direct state / containers: full scan
                continue
            mod = type(obj).__module__ or ""
            if mod.split(".")[0] in ("numpy", "jax", "builtins"):
                continue  # library objects are never training state
            # co_names mixes globals with attribute names, so this scan
            # can over-approximate; start holder objects at depth 1 (their
            # DIRECT Layer/Optimizer/Tensor attrs only) to bound capture
            visit(obj, depth=1)
    return layers, optimizers, tensors


def _is_arraylike(x):
    return isinstance(x, (jax.Array, Tensor)) or hasattr(x, "__array__")


class StaticFunction:
    """The compiled wrapper returned by ``to_static``."""

    def __init__(self, function, input_spec=None, state=None, donate=True,
                 warmup="per-signature", donate_inputs=False, name=None):
        functools.update_wrapper(self, function)
        self._fn = function
        self._input_spec = input_spec
        self._extra_state = state
        # donate=True is for steps that UPDATE state (train steps): the
        # old param buffers are dead after the call and XLA reuses them.
        # Pass donate=False for read-only programs (serving, generate) —
        # pass-through state gains nothing from donation, and when many
        # state slots share an aval (e.g. int8 weights + scale sidecars)
        # XLA's aval-based alias matching can scramble the identity
        # outputs across the donated buffers.
        self._donate = donate
        # compile-watch identity: per-callable compile counters/gauges
        # are labeled with this name (see observability.compile_watch)
        if name:
            self._watch_name = name
        else:
            qn = getattr(function, "__qualname__", None)
            mod = getattr(function, "__module__", None)
            if qn:
                # module-qualified so two files' lambdas don't conflate
                self._watch_name = f"{mod}.{qn}" if mod else qn
            else:
                # no qualname (partial/bound callables): a stable,
                # address-free label — repr() would mint one permanent
                # labeled registry child per instance
                self._watch_name = type(function).__name__
        self._aot = {}          # signature -> compiled executable | None
        # donate_inputs additionally donates the INPUT arrays to XLA so
        # same-shaped outputs alias them in place (e.g. KV-cache buffers in
        # a decode loop). Only safe when the caller never reuses an input
        # after the call.
        self._donate_inputs = donate_inputs
        self._warmup = warmup   # "per-signature" | "once"
        self._warmed_any = False
        self._cache = {}        # signature -> (jitted fn, grad slots, out box)
        self._warm = set()      # signatures already run eagerly once
        self._layers = []
        self._optimizers = []
        self._state_tensors = None

    # -- state management ---------------------------------------------------
    def _collect_state(self):
        layers, optimizers, tensors = _discover_state(
            self._fn, self._extra_state)
        self._layers = layers
        self._optimizers = optimizers
        state, seen = [], set()

        def add(t):
            if t is not None and id(t) not in seen:
                seen.add(id(t))
                state.append(t)

        for l in layers:
            for p in l.parameters():
                add(p)
            for b in l.buffers():
                add(b)
        for o in optimizers:
            for p in o._parameter_list:
                add(p)
            for acc in o._accumulator_pytree():
                add(acc)
        for t in tensors:
            add(t)
        self._state_tensors = state

    def _signature(self, flat_in, in_treedef):
        training = tuple(l.training for l in self._layers)
        grads = tuple(t.grad is not None for t in self._state_tensors or ())
        shapes = tuple(
            (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape")
            else (type(a).__name__, a if isinstance(a, (int, float, bool, str,
                                                        type(None))) else None)
            for a in flat_in)
        # ambient autocast state is baked into the trace (casts become part
        # of the compiled program), so a program traced inside auto_cast
        # must not be reused outside it — key the cache on it
        amp = amp_state.current()
        amp_key = None if amp is None else (amp.dtype.name, amp.level,
                                            amp.white, amp.black)
        # the treedef distinguishes positional from keyword binding of the
        # same leaves — without it f(x, y) and f(y=y, x=x) would share a
        # compiled entry and silently mis-bind inputs
        return (shapes, repr(in_treedef), training, grads, amp_key)

    # -- the traced pure step ----------------------------------------------
    def _build(self, in_treedef):
        state_tensors = self._state_tensors
        optimizers = self._optimizers
        fn = self._fn
        grad_idx = [i for i, t in enumerate(state_tensors)
                    if t.grad is not None]
        out_box = {}

        def pure_step(state, grads, in_arrays, lrs, key):
            saved = [(t._data, t.grad, t._node) for t in state_tensors]
            overrides = [o._lr_override for o in optimizers]
            try:
                for t, a in zip(state_tensors, state):
                    t._data = a
                    t.grad = None
                    t._node = None
                for i, g in zip(grad_idx, grads):
                    state_tensors[i].grad = Tensor(g, stop_gradient=True)
                for o, lr in zip(optimizers, lrs):
                    o._lr_override = lr
                with frandom.rng_guard(key) as gen:
                    ins = [Tensor(a) if isinstance(a, jax.Array) else a
                           for a in in_arrays]
                    args, kwargs = jax.tree_util.tree_unflatten(in_treedef, ins)
                    out = fn(*args, **kwargs)
                    new_key = gen._key
                new_state = [t._data for t in state_tensors]
                new_grads = [
                    state_tensors[i].grad._data
                    if state_tensors[i].grad is not None
                    else jnp.zeros_like(new_state[i])
                    for i in grad_idx]
                flat_out, out_treedef = jax.tree_util.tree_flatten(
                    out, is_leaf=lambda x: isinstance(x, Tensor))
                flat_out = [o._data if isinstance(o, Tensor) else o
                            for o in flat_out]
                out_box["treedef"] = out_treedef
                return new_state, new_grads, flat_out, new_key
            finally:
                for t, (d, g, n) in zip(state_tensors, saved):
                    t._data, t.grad, t._node = d, g, n
                for o, ov in zip(optimizers, overrides):
                    o._lr_override = ov

        donate = (0, 1) if self._donate else ()
        if self._donate_inputs:
            donate = donate + (2,)
        return jax.jit(pure_step, donate_argnums=donate), grad_idx, out_box

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._fn(*args, **kwargs)
        flat_in, in_treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        in_arrays = [a._data if isinstance(a, Tensor)
                     else jnp.asarray(a) if _is_arraylike(a) else a
                     for a in flat_in]
        if self._state_tensors is None:
            self._collect_state()
        sig = self._signature(in_arrays, in_treedef)

        if sig not in self._warm and not (self._warmup == "once"
                                          and self._warmed_any):
            # warmup: eager run materializes accumulators / lazy buffers.
            # Bookkeeping only after success — a failed warmup (OOM, data
            # bug) must not mark the function warm, or a retry would trace
            # with never-materialized accumulators and leak tracers.
            out = self._fn(*args, **kwargs)
            self._warm.add(sig)
            self._warmed_any = True
            self._collect_state()  # re-collect: step() created accumulators
            # the grown state changes the signature; mark it warm so the
            # next same-shape call compiles instead of re-warming
            self._warm.add(self._signature(in_arrays, in_treedef))
            return out

        entry = self._cache.get(sig)
        if entry is None:
            entry = self._build(in_treedef)
            self._cache[sig] = entry
        jitted, grad_idx, out_box = entry

        state = [t._data for t in self._state_tensors]
        grads = [self._state_tensors[i].grad._data for i in grad_idx]
        lrs = [jnp.asarray(o.get_lr(), jnp.float32)
               for o in self._optimizers]
        key = frandom.next_key()
        step_args = (state, grads, in_arrays, lrs, key)
        if self._donate_inputs:
            # some inputs (e.g. prefill tokens) have no same-shaped output
            # to alias — the resulting JAX warning is expected, not a bug
            import warnings
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable")
                new_state, new_grads, flat_out, _ = self._dispatch(
                    sig, jitted, step_args)
        else:
            new_state, new_grads, flat_out, _ = self._dispatch(
                sig, jitted, step_args)
        for t, a in zip(self._state_tensors, new_state):
            t._data = a
            t._node = None
        for i, g in zip(grad_idx, new_grads):
            self._state_tensors[i].grad = Tensor(g, stop_gradient=True)
        outs = [Tensor(a, stop_gradient=True) if isinstance(a, jax.Array)
                else a for a in flat_out]
        return jax.tree_util.tree_unflatten(out_box["treedef"], outs)

    def _sig_desc(self, sig):
        """Compile-watch signature descriptor: the user-input shapes
        (the churn the storm diagnosis must name) plus the remaining
        cache-key components as labeled pseudo-args."""
        shapes, tree, training, grads, amp_key = sig
        desc = []
        for i, s in enumerate(shapes):
            if isinstance(s[0], tuple):
                desc.append(
                    (f"arg{i}",
                     f"{s[1]}[{','.join(str(d) for d in s[0])}]"))
            else:
                desc.append((f"arg{i}", f"{s[0]}={s[1]!r}"))
        desc.append(("training", str(training)))
        desc.append(("grads", str(grads)))
        desc.append(("amp", str(amp_key)))
        desc.append(("tree", tree))
        return tuple(desc)

    def _dispatch(self, sig, jitted, step_args):
        """Run the compiled step. With metrics enabled, the first call
        per signature compiles ahead-of-time through the compile watcher
        (exact compile count + duration + static cost/memory analysis)
        and later calls dispatch the cached executable; with
        ``PADDLE_TPU_METRICS=0`` this is exactly ``jitted(*step_args)``
        — the jit cache path untouched, byte-identical, sync-free."""
        from ..observability import compile_watch as _cw

        if not _cw.enabled():
            return jitted(*step_args)
        if _cw._in_outer_trace():
            # inside an outer trace only the plain jit path composes
            # (an AOT executable cannot take tracers)
            return jitted(*step_args)
        compiled = self._aot.get(sig)
        if compiled is None:
            if sig in self._aot:
                # AOT unsupported for this program: bail before touching
                # the watch lock or building the descriptor — this runs
                # per dispatch on the hot path
                return jitted(*step_args)
            w = _cw.watch(self._watch_name)
            desc = self._sig_desc(sig)
            compiled = w.aot_compile(jitted, step_args, desc=desc)
            self._aot[sig] = compiled
            if compiled is None:    # fall back, still count the compile
                return w.timed_first_dispatch(jitted, step_args,
                                              desc=desc)
        try:
            from ..observability import perf as _perf

            t0 = time.perf_counter()
            out = compiled(*step_args)
            _perf.note_dispatch(self._watch_name, compiled, out, t0)
            return out
        except _cw.AOT_MISMATCH_ERRORS:
            # the cache signature tracks user inputs, not state avals: a
            # state drift the signature can't see (the model cast to a
            # new dtype, a resharded parameter) mismatches the AOT
            # executable's fixed input types/shardings. jax.jit retraces
            # such drift transparently — stop AOT-ing this signature and
            # let the plain path own it
            self._aot[sig] = None
            return jitted(*step_args)

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._fn)

    def rollback(self):
        return self._fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, state=None, full_graph=True,
              warmup="per-signature", name=None, donate_inputs=False,
              **kwargs):
    """Decorator/wrapper: compile an imperative step into one XLA program.

    ``state`` optionally lists Layers/Optimizers/Tensors the function
    mutates (auto-discovered from the closure when omitted). Matches the
    reference's ``paddle.jit.to_static`` call shapes: bare decorator,
    decorator-with-args, and direct wrapping of a Layer.

    ``warmup="once"``: only the first call runs eagerly (to materialize
    optimizer accumulators); later unseen shapes compile directly. Use when
    the eager pass at full shape would exceed HBM (eager holds every
    intermediate; the compiled program lets XLA schedule memory).

    ``donate_inputs=True`` additionally donates the call's INPUT buffers
    to XLA (e.g. a train step's ids/labels: their HBM is reusable as
    workspace the moment the embedding gather read them). Only safe when
    every call gets fresh inputs — a caller re-feeding the same device
    batch would dispatch donated (invalidated) buffers.
    """
    def wrap(fn):
        from ..nn import Layer
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(layer.forward, input_spec=input_spec,
                                state=[layer] + list(state or ()),
                                warmup=warmup,
                                donate_inputs=donate_inputs,
                                name=name or type(layer).__name__)
            layer.forward = sf
            return layer
        return StaticFunction(fn, input_spec=input_spec, state=state,
                              warmup=warmup, donate_inputs=donate_inputs,
                              name=name)
    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


from .serialization import save, load, TranslatedLayer  # noqa: F401,E402
