"""``paddle.profiler`` — tracing + throughput benchmarking.

Reference: `python/paddle/profiler/profiler.py:346` (``Profiler`` state
machine with scheduler + on_trace_ready), ``RecordEvent`` host
instrumentation, chrome-trace export (`chrometracing_logger.cc`), and the
ips benchmark timer (`profiler/timer.py`).

TPU-native mechanics: the device tracer is the XLA/JAX profiler —
``start_trace`` collects host + device (TPU) timelines into an XPlane
protobuf AND a chrome ``trace.json.gz`` under
``<log_dir>/plugins/profile/<run>/`` (TensorBoard's profile plugin reads
the same directory). ``RecordEvent`` lowers to
``jax.profiler.TraceAnnotation`` so user ranges appear on the device
timeline, the analog of the reference's RecordEvent instrumentation.
"""

from __future__ import annotations

import glob
import os
import time

import jax

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget",
           "export_chrome_tracing", "make_scheduler", "benchmark",
           "Benchmark"]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"          # accepted for API parity; maps to the device
    CUSTOM_DEVICE = "custom_device"
    TPU = "tpu"


def export_chrome_tracing(dir_name, worker_name=None):
    """Returns an on_trace_ready handler that keeps traces under
    ``dir_name`` (reference profiler.py export_chrome_tracing). The JAX
    profiler already writes chrome json; the handler reports its paths —
    only from runs created by THIS profiler session. ``dir_name`` is a
    long-lived log directory, so a bare glob would resurrect every run
    any previous session ever wrote there; runs present at ``start()``
    (recorded in ``prof._preexisting_runs``) are excluded."""

    def handle(prof):
        stale = getattr(prof, "_preexisting_runs", set())
        prof._last_chrome_traces = sorted(
            trace
            for run in glob.glob(
                os.path.join(dir_name, "plugins", "profile", "*"))
            if run not in stale
            for trace in glob.glob(
                os.path.join(run, "*.trace.json.gz")))
        return prof._last_chrome_traces

    handle._log_dir = dir_name
    return handle


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0,
                   skip_first=0):
    """Step-state scheduler (reference profiler_utils make_scheduler):
    returns a callable step -> bool(record)."""
    cycle = closed + ready + record

    def schedule(step):
        if step < skip_first:
            return False
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return False
        return (s % cycle) >= (closed + ready)

    return schedule


class Profiler:
    """Reference profiler.py:346. Usage::

        p = Profiler(on_trace_ready=export_chrome_tracing('./log'))
        p.start()
        for ...: train(); p.step()
        p.stop()
        p.summary()
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self._on_trace_ready = on_trace_ready
        self._log_dir = getattr(on_trace_ready, "_log_dir", None) \
            or "./profiler_log"
        self._timer_only = timer_only
        self._scheduler = scheduler
        self._tracing = False
        self._steps = 0
        self._step_times = []
        self._t0 = None
        self._last_chrome_traces = []
        self._preexisting_runs = set()

    # -- lifecycle -----------------------------------------------------------
    def _want_trace(self, step):
        if self._timer_only:
            return False
        if self._scheduler is None:
            return True
        return bool(self._scheduler(step))

    def _set_tracing(self, want):
        if want and not self._tracing:
            os.makedirs(self._log_dir, exist_ok=True)
            jax.profiler.start_trace(self._log_dir)
            self._tracing = True
        elif not want and self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False

    def start(self):
        self._t0 = time.perf_counter()
        # snapshot the runs already under the log dir: on_trace_ready
        # handlers report only runs this session creates, not a previous
        # session's leftovers
        self._preexisting_runs = set(glob.glob(
            os.path.join(self._log_dir, "plugins", "profile", "*")))
        self._set_tracing(self._want_trace(self._steps))
        return self

    def stop(self):
        self._set_tracing(False)
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        return self

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t0 is not None:
            self._step_times.append((now - self._t0, num_samples))
        self._t0 = now
        self._steps += 1
        # scheduled tracing windows open/close on step boundaries
        self._set_tracing(self._want_trace(self._steps))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- results -------------------------------------------------------------
    def chrome_trace_paths(self):
        return list(self._last_chrome_traces)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Host-side step statistics (the full op table lives in the
        exported trace, viewable in TensorBoard / Perfetto)."""
        if not self._step_times:
            print("Profiler: no steps recorded")
            return {}
        times = [t for t, _ in self._step_times]
        counted = [(t, n) for t, n in self._step_times if n]
        mean = sum(times) / len(times)
        stats = {"steps": len(times),
                 "avg_step_ms": mean * 1e3,
                 "min_step_ms": min(times) * 1e3,
                 "max_step_ms": max(times) * 1e3}
        if counted:
            # pair each sample count with ITS step's time (a warmup step
            # without num_samples must not pollute ips)
            stats["ips"] = sum(n for _, n in counted) \
                / sum(t for t, _ in counted)
        print("Profiler summary: " + ", ".join(
            f"{k}={v:.3f}" if isinstance(v, float) else f"{k}={v}"
            for k, v in stats.items()))
        if self._last_chrome_traces:
            print("chrome traces: " + ", ".join(self._last_chrome_traces))
        return stats


class RecordEvent:
    """User-annotated range on the profiler timeline (reference
    profiler.py RecordEvent; lowers to jax.profiler.TraceAnnotation)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Benchmark:
    """ips/step-time tracker (reference `profiler/timer.py` Benchmark,
    the engine behind hapi's throughput logs)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t = None
        self._times = []
        self._samples = 0

    def begin(self):
        self._t = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t is not None:
            self._times.append(now - self._t)
        self._t = now
        if num_samples:
            self._samples += num_samples

    def end(self):
        self._t = None

    @property
    def ips(self):
        tot = sum(self._times)
        return self._samples / tot if tot and self._samples else 0.0

    def speed_average(self):
        return self.ips

    def report(self):
        return {"steps": len(self._times),
                "avg_step_s": (sum(self._times) / len(self._times))
                if self._times else 0.0,
                "ips": self.ips}


_global_benchmark = Benchmark()


def benchmark():
    """Reference timer.py ``benchmark()`` — the global Benchmark."""
    return _global_benchmark
