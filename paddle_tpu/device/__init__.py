"""Device management (reference: `python/paddle/device/__init__.py:265`
``set_device`` and the phi DeviceManager, `phi/backends/device_manager.h:134`).

TPU-native: devices are PJRT devices enumerated by JAX; there is no manual
stream/event surface because XLA schedules asynchronously — the stream-like
knobs are kept as no-op shims for API parity.
"""

from __future__ import annotations

import jax

__all__ = ["set_device", "get_device", "get_all_devices", "device_count",
           "is_compiled_with_cuda", "is_compiled_with_rocm",
           "is_compiled_with_xpu", "is_compiled_with_ipu",
           "is_compiled_with_custom_device", "synchronize", "Stream", "Event",
           "current_stream", "cuda"]

_current_device = None

_DEVICE_NAMES = ("cpu", "gpu", "tpu", "cuda", "axon")


def _platform():
    return jax.default_backend()


def _looks_like_device(spec) -> bool:
    """True if ``spec`` is a device string like 'tpu' / 'cpu:0' / 'cuda:1'."""
    if not isinstance(spec, str):
        return False
    return spec.lower().partition(":")[0] in _DEVICE_NAMES


def _resolve_device(spec: str):
    """Resolve a device string to a concrete JAX device (shared by
    ``set_device`` and ``Tensor.to``)."""
    name, _, idx = spec.lower().partition(":")
    if name == "cuda":
        name = "gpu"
    idx = int(idx) if idx else 0
    devs = [d for d in jax.devices()
            if d.platform == name
            or (name == "gpu" and d.platform in ("cuda", "rocm"))]
    if not devs and name == "cpu":
        # CPU devices exist even when an accelerator is the default backend;
        # ask the CPU backend explicitly.
        devs = jax.devices("cpu")
    if not devs:
        raise ValueError(
            f"no '{name}' device available; platforms present: "
            f"{sorted({d.platform for d in jax.devices()})}")
    if idx >= len(devs):
        raise ValueError(
            f"device index {idx} out of range: only {len(devs)} '{name}' "
            "device(s) present")
    return devs[idx]


def set_device(device: str):
    """Select default device: 'tpu', 'cpu', 'tpu:0' etc."""
    global _current_device
    _current_device = _resolve_device(device)
    jax.config.update("jax_default_device", _current_device)
    return _current_device


def get_device() -> str:
    d = _current_device or jax.devices()[0]
    return f"{d.platform}:{getattr(d, 'id', 0)}"


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count():
    return jax.device_count()


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_custom_device(name="tpu"):
    return True


def synchronize(device=None):
    """Block until all dispatched work completes (stream sync analog)."""
    try:
        (jax.device_put(0) + 0).block_until_ready()
    except Exception:
        pass


class Stream:
    """No-op shim: XLA owns scheduling; kept for API parity with
    ``paddle.device.Stream``."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


class _CudaShim:
    """``paddle.device.cuda`` compatibility namespace (no CUDA on TPU)."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        return 0

    @staticmethod
    def memory_allocated(device=None):
        return 0


cuda = _CudaShim()


# ---------------------------------------------------------------------------
# memory statistics (reference: `fluid/memory/stats.cc` — allocated/reserved
# current + peak per device; `paddle.device.cuda.max_memory_allocated`)
# ---------------------------------------------------------------------------
_peak_allocated: dict = {}


def _device_obj(device=None):
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    return device


def memory_stats(device=None, live_arrays=None):
    """Raw allocator statistics for a device. On real TPU/GPU backends
    this is the PJRT allocator report (``bytes_in_use``,
    ``peak_bytes_in_use``, ``bytes_limit``, ...); where the backend does
    not report (CPU, tunneled devices), live on-device arrays are summed
    instead and the dict carries ``{"bytes_in_use": ..., "source":
    "live_arrays"}``. ``live_arrays`` optionally supplies an already-
    fetched ``jax.live_arrays()`` list so callers that walk it anyway
    (the observability memory sampler) don't pay the enumeration
    twice."""
    d = _device_obj(device)
    stats = None
    try:
        stats = d.memory_stats()
    except Exception:
        stats = None
    if stats:
        out = dict(stats)
        # tag the provenance on BOTH paths so consumers (the
        # observability memory sampler, dashboards) can tell an
        # allocator-reported figure from a live-array estimate
        out.setdefault("source", "allocator")
        return out
    live = jax.live_arrays() if live_arrays is None else live_arrays
    in_use = sum(
        x.nbytes for x in live
        if any(dd == d for dd in x.devices()))
    return {"bytes_in_use": in_use, "source": "live_arrays"}


def memory_allocated(device=None):
    """Bytes currently allocated on the device (reference
    `paddle.device.cuda.memory_allocated`)."""
    n = int(memory_stats(device).get("bytes_in_use", 0))
    key = str(_device_obj(device))
    _peak_allocated[key] = max(_peak_allocated.get(key, 0), n)
    return n


def max_memory_allocated(device=None):
    """Peak allocated bytes: the allocator's own peak when reported,
    else the running max over this process's ``memory_allocated`` calls."""
    stats = memory_stats(device)
    if "peak_bytes_in_use" in stats:
        return int(stats["peak_bytes_in_use"])
    key = str(_device_obj(device))
    current = int(stats.get("bytes_in_use", 0))
    _peak_allocated[key] = max(_peak_allocated.get(key, 0), current)
    return _peak_allocated[key]


def memory_reserved(device=None):
    """Bytes reserved by the allocator (``bytes_limit`` when reported —
    XLA preallocates; else equals allocated)."""
    stats = memory_stats(device)
    return int(stats.get("bytes_limit", stats.get("bytes_in_use", 0)))


def reset_max_memory_allocated(device=None):
    _peak_allocated[str(_device_obj(device))] = 0


def empty_cache():
    """Reference `paddle.device.cuda.empty_cache`. XLA's BFC allocator
    serves frees internally; deleting dangling host references is the
    only lever, so this triggers a GC pass."""
    import gc
    gc.collect()


__all__ += ["memory_stats", "memory_allocated", "max_memory_allocated",
            "memory_reserved", "reset_max_memory_allocated", "empty_cache"]
