"""``paddle.metric`` — streaming evaluation metrics.

Reference: `python/paddle/metric/metrics.py` (``Metric`` base with
compute/update/reset/accumulate, ``Accuracy``, ``Precision``, ``Recall``,
``Auc``). Metrics accumulate on host in numpy — they sit outside the
compiled step, fed by its outputs, so they never force a retrace.
"""

from __future__ import annotations

import abc

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def _np(x):
    if isinstance(x, Tensor):
        return x.numpy()
    return np.asarray(x)


class Metric(abc.ABC):
    """Base metric (reference metrics.py Metric)."""

    def __init__(self, name=None):
        self._name = name or self.__class__.__name__.lower()

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    def name(self):
        return self._name

    def compute(self, *args):
        """Optional pre-processing of (pred, label) before ``update``;
        default passthrough (reference: Metric.compute)."""
        return args


class Accuracy(Metric):
    """Top-k accuracy (reference metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        super().__init__(name or "acc")
        self.reset()

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        maxk = max(self.topk)
        order = np.argsort(-pred, axis=-1)[..., :maxk]
        if label.ndim == pred.ndim:
            if label.shape[-1] == 1:       # paddle's [B, 1] index labels
                label = label[..., 0]
            else:                          # one-hot / soft labels
                label = label.argmax(-1)
        correct = (order == label[..., None]).astype(np.float32)
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        flat = correct.reshape(-1, correct.shape[-1])
        res = []
        for k in self.topk:
            hit = flat[:, :k].sum(-1).clip(max=1.0)
            self.total[self.topk.index(k)] += float(hit.sum())
            self.count[self.topk.index(k)] += hit.shape[0]
            res.append(float(hit.mean()))
        return res[0] if len(res) == 1 else res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision (reference metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def update(self, preds, labels):
        preds = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        labels = _np(labels).reshape(-1).astype(np.int64)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    """Binary recall (reference metrics.py Recall)."""

    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def update(self, preds, labels):
        preds = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        labels = _np(labels).reshape(-1).astype(np.int64)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    """Binned ROC-AUC (reference metrics.py Auc, trapezoid over
    ``num_thresholds`` bins)."""

    def __init__(self, num_thresholds=4095, name=None):
        self.num_thresholds = num_thresholds
        super().__init__(name or "auc")
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = _np(labels).reshape(-1).astype(np.int64)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        np.add.at(self._stat_pos, idx, labels == 1)
        np.add.at(self._stat_neg, idx, labels == 0)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.float64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.float64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # sweep thresholds high->low, trapezoid on the ROC curve
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy of a prediction batch (reference op `accuracy`,
    `phi/kernels/gpu/accuracy_kernel.cu`): input [N, C] scores, label
    [N, 1] or [N]; returns a 0-d fraction tensor."""
    import jax.numpy as jnp

    from ..framework.tensor import run_op

    kk = int(k)

    def fn(inp, lbl):
        topk = jnp.argsort(-inp, axis=1)[:, :kk]
        lbl = lbl.reshape(-1, 1)
        hit = jnp.any(topk == lbl, axis=1)
        return jnp.mean(hit.astype(jnp.float32))

    return run_op("accuracy", fn, (input, label), differentiable=False)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC (reference op `auc`, `phi/kernels/cpu/auc_kernel.cc`):
    histogram the positive-class scores into ``num_thresholds`` bins for
    positives and negatives, then trapezoid over the implied curve —
    ROC (TPR vs FPR) or PR (precision vs recall). Returns a 0-d
    tensor."""
    import jax.numpy as jnp

    from ..framework.tensor import run_op

    if curve not in ("ROC", "PR"):
        raise ValueError(f"curve must be 'ROC' or 'PR', got {curve!r}")
    nbins = int(num_thresholds)
    pr = curve == "PR"

    def fn(inp, lbl):
        score = inp[:, 1] if inp.ndim == 2 else inp.reshape(-1)
        y = lbl.reshape(-1).astype(jnp.float32)
        bins = jnp.clip((score * nbins).astype(jnp.int32), 0, nbins - 1)
        pos = jnp.zeros((nbins,)).at[bins].add(y)
        neg = jnp.zeros((nbins,)).at[bins].add(1.0 - y)
        # sweep thresholds high -> low: cumulative TP/FP
        tp = jnp.cumsum(pos[::-1])
        fp = jnp.cumsum(neg[::-1])
        tot_p = jnp.maximum(tp[-1], 1e-12)
        tot_n = jnp.maximum(fp[-1], 1e-12)
        recall = tp / tot_p
        if pr:
            precision = tp / jnp.maximum(tp + fp, 1e-12)
            rec = jnp.concatenate([jnp.zeros((1,)), recall])
            prec = jnp.concatenate([jnp.ones((1,)), precision])
            return jnp.trapezoid(prec, rec)
        tpr = jnp.concatenate([jnp.zeros((1,)), recall])
        fpr = jnp.concatenate([jnp.zeros((1,)), fp / tot_n])
        return jnp.trapezoid(tpr, fpr)

    return run_op("auc", fn, (input, label), differentiable=False)
