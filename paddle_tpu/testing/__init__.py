"""``paddle_tpu.testing`` — deterministic test harnesses.

Currently home to :mod:`paddle_tpu.testing.faults`, the fault-injection
plan that crash/recovery tests (checkpoint manager, elastic launch) use
to kill, hang, or corrupt a process at an exact instrumented point.
"""

from . import faults  # noqa: F401

__all__ = ["faults"]
