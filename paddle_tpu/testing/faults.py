"""Deterministic fault injection for crash/recovery tests.

Reference: the recovery model of `fleet/elastic/manager.py` (detect a
failure, relaunch, resume from checkpoint) is only provable if the
failure itself is reproducible. This module makes failures first-class
test inputs: production code calls :func:`fire` at named points
("ckpt.write", "rename", "train.step", ...) and a *fault plan* — a JSON
list of rules in the ``PADDLE_TPU_FAULTS`` environment variable —
decides, deterministically, what happens there: nothing (the default,
one dict lookup when no plan is set), a crash, a signal, a hang, a
slow-down, an injected ``OSError``, or a bit-flip of a file.

Because the plan travels through the environment, subprocess tests
activate it without patching any code: the launcher test sets
``PADDLE_TPU_FAULTS='[{"point": "rename", "step": 3, "action":
"sigkill"}]'`` and the worker under test dies mid-save of step 3,
exactly once, every run.

Rule fields (all optional except ``point`` and ``action``):

- ``point``: instrumented point name (exact match). Instrumented so
  far: the checkpoint commit path (``ckpt.write``,
  ``ckpt.before_marker``, ``rename``), the training loop
  (``train.step``), the serving request lifecycle
  (``serve.admit`` — fired per admission attempt, so a ``raise`` rule
  with ``exc: "MemoryError"`` simulates KV-pool pressure and drives
  the degradation ladder; ``serve.decode`` — fired before each
  step/burst dispatch, ``step`` = dispatch ordinal; ``serve.drain`` —
  fired as a graceful drain begins), and the multi-replica serving
  tier (``replica.dead`` — fired per replica worker-loop tick with
  ``step`` = tick ordinal and ``path`` = the replica id, so a
  ``raise``/``hang`` rule kills replica N at tick K and the router's
  membership TTL + failover path runs deterministically in CI;
  ``router.route`` — fired per routing decision with ``step`` = the
  route ordinal, so a ``raise`` rule injects routing errors;
  ``serve.spawn`` — fired in the SUPERVISOR before each replica
  spawn/restart attempt with ``path`` = the replica id and ``step`` =
  the spawn ordinal, so a ``raise`` rule deterministically fails
  process spawn — the supervisor's exponential backoff and crash-loop
  circuit breaker run without a single real process; ``replica
  .heartbeat`` — fired on the replica's heartbeat sidecar before each
  stamp refresh with ``path`` = the replica id and ``step`` = the beat
  ordinal, so a ``hang``/``sleep`` rule freezes heartbeats and the
  replica silently ages out of membership, driving TTL death detection
  and, repeated, the circuit breaker), and the host-DRAM KV page tier
  (``tier.d2h`` / ``tier.h2d`` — fired before each device↔host page
  copy via :func:`fire_copy`, with ``step`` = the engine's dispatch
  ordinal and ``path`` = ``"seq"`` for paused-sequence copies or
  ``"prefix"`` for demoted prefix-cache pages, so one plan can scope
  chaos to either flow; ``sleep`` = a slow copy, ``raise`` = a failed
  copy, ``bitflip`` = a torn copy — see :func:`fire_copy` for why the
  tear is performed by the caller). Cookbook — a slow-copy +
  torn-restore chaos plan that exercises both tier fallback paths::

      PADDLE_TPU_FAULTS='[
        {"point": "tier.d2h", "action": "sleep", "seconds": 0.05,
         "count": 2},
        {"point": "tier.h2d", "action": "bitflip", "count": 1}
      ]'

  (the first two D2H exports run slow; the first H2D restore is torn,
  the per-page CRC check catches it and the request falls back to the
  evict→requeue path — typed, never decoded into garbage).
- ``action``: one of ``crash`` (``os._exit``), ``sigkill``, ``sigterm``
  (signal self), ``hang`` (sleep ~forever), ``sleep`` (slow-down, then
  continue), ``raise`` (``OSError`` by default; see ``exc``),
  ``bitflip`` (corrupt the file at the point's ``path``).
- ``exc``: for ``raise`` — the exception type to inject, one of
  ``OSError`` (default), ``MemoryError``, ``TimeoutError``,
  ``RuntimeError``. Lets a plan exercise typed failure paths (e.g.
  admission pressure is a ``MemoryError`` contract).
- ``step``: only fire when the call site passes this step number.
- ``path``: fnmatch glob matched against the call site's path (full
  path or basename).
- ``env``: ``{name: value}`` — only fire when every named environment
  variable currently has that value (e.g. restrict a kill to elastic
  generation 0 via ``{"PADDLE_RESTART_COUNT": "0"}``).
- ``count``: fire at most this many times per process (default:
  unlimited).
- ``seconds``: duration for ``sleep`` / ``hang`` (defaults 0.1 / 3600).
- ``exit_code``: for ``crash`` (default 23).

Network rules (ISSUE 11): a rule whose ``action`` is one of ``drop`` /
``delay`` / ``duplicate`` / ``reorder`` / ``partition`` is a
:class:`NetworkRule` — it fires at MESSAGE points (``rpc.send``,
``rpc.reply``, ``store.heartbeat``) via :func:`fire_network`, matched
by the ``(src, dst)`` name pair (fnmatch globs), and returns a
:class:`NetworkVerdict` the transport interprets instead of performing
a process action:

- ``drop``: the message is lost — the caller sees a timeout and (with
  at-least-once rpc) retries.
- ``delay``: the message is held ``seconds`` before it is handed to
  the transport (in-flight latency).
- ``duplicate``: the message is delivered ``copies`` extra times — the
  receiver's dedup cache must make redelivery exactly-once-effective.
- ``reorder``: the message's mailbox slot is claimed, then held for a
  seeded-random fraction of ``seconds`` before the payload lands — in
  a sequential mailbox transport true reorder degenerates to
  head-of-line delay, which is what this injects.
- ``partition``: every matching message is dropped for a wall-clock
  window of ``seconds`` (default 1.0) measured from the rule's first
  match — a full network partition between the matched pair.

Extra network-rule fields: ``src`` / ``dst`` (fnmatch globs on the
endpoint names), ``p`` (per-message fire probability, drawn from a
rule-local ``random.Random(seed)`` so a seeded chaos schedule replays
identically), ``seed``, ``copies``.

Store rules (ISSUE 20): a rule whose ``point`` is a SOCKET point of
the TCP control-plane store (``store.connect`` — fired per connection
attempt of a :class:`~paddle_tpu.distributed.net_store.LeaseStore`
client, ``path`` = the server address; ``store.frame`` — fired per
request frame, ``path`` = the op name, ``step`` = the client's op
ordinal) is a :class:`StoreRule` — it fires via :func:`fire_store` and
returns a :class:`StoreVerdict` the CLIENT interprets (so seeded
chaos stays deterministic regardless of server threading):

- ``refuse``: the connection is refused (``ConnectionRefusedError``) —
  the server port is closed.
- ``reset``: the socket is reset mid-operation
  (``ConnectionResetError``) — the server died under the client.
- ``hang``: the operation blocks ``seconds`` (default 1.0), then times
  out — a black-holed route.
- ``slow``: the operation is delayed ``seconds`` (default 0.05), then
  proceeds — a congested link.
- ``torn``: the frame arrives truncated — the client must treat it as
  a transport failure, never decode garbage.

Store rules take the same ``p``/``seed``/``count``/``step``/``path``/
``env`` fields as network rules; every store-client failure they
induce surfaces as a typed ``StoreUnavailableError`` through the
normal retry/reconnect machinery.

Plans are VALIDATED at parse time: an unknown rule key, an unknown
action, or a point name that no instrumented call site registers
raises a clear ``ValueError`` — a typo'd chaos plan fails loudly
instead of silently never firing.
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import signal
import threading
import time

__all__ = ["PLAN_ENV", "FaultRule", "NetworkRule", "NetworkVerdict",
           "StoreRule", "StoreVerdict", "FaultPlan", "plan", "reset",
           "active", "fire", "fire_copy", "fire_network", "fire_store",
           "rename", "bitflip", "PROCESS_POINTS", "NETWORK_POINTS",
           "STORE_POINTS"]

#: environment variable holding the JSON fault plan
PLAN_ENV = "PADDLE_TPU_FAULTS"

_ACTIONS = ("crash", "sigkill", "sigterm", "hang", "sleep", "raise",
            "bitflip")

_NET_ACTIONS = ("drop", "delay", "duplicate", "reorder", "partition")

#: instrumented process points — :func:`fire` call sites. A plan naming
#: any other point is a typo and fails at parse time.
PROCESS_POINTS = frozenset({
    "ckpt.write", "ckpt.before_marker", "ckpt.save_begin",
    "ckpt.committed", "rename", "train.step", "serve.admit",
    "serve.decode", "serve.drain", "serve.spawn", "replica.dead",
    "replica.heartbeat", "router.route", "tier.d2h", "tier.h2d",
})

#: instrumented message points — :func:`fire_network` call sites
NETWORK_POINTS = frozenset({"rpc.send", "rpc.reply", "store.heartbeat"})

_STORE_ACTIONS = ("refuse", "reset", "hang", "slow", "torn")

#: instrumented socket points of the TCP control-plane store —
#: :func:`fire_store` call sites (client side, for determinism)
STORE_POINTS = frozenset({"store.connect", "store.frame"})

_RULE_KEYS = frozenset({"point", "action", "step", "path", "env",
                        "count", "seconds", "exit_code", "exc"})
_NET_RULE_KEYS = frozenset({"point", "action", "src", "dst", "p",
                            "seed", "count", "step", "seconds",
                            "copies", "env"})
_STORE_RULE_KEYS = frozenset({"point", "action", "step", "path", "p",
                              "seed", "count", "seconds", "env"})

#: injectable exception types for ``raise`` rules — a closed set, so a
#: plan can't name arbitrary symbols
_EXC_TYPES = {"OSError": OSError, "MemoryError": MemoryError,
              "TimeoutError": TimeoutError, "RuntimeError": RuntimeError}


class FaultRule:
    """One parsed plan entry. Matching is pure; firing performs the
    action (and may not return)."""

    def __init__(self, spec):
        unknown = set(spec) - _RULE_KEYS
        if unknown:
            raise ValueError(
                f"unknown fault rule key(s) {sorted(unknown)}; expected "
                f"a subset of {sorted(_RULE_KEYS)}")
        self.point = spec["point"]
        if self.point not in PROCESS_POINTS:
            raise ValueError(
                f"unregistered fault point {self.point!r}; instrumented "
                f"points are {sorted(PROCESS_POINTS)} (network points "
                f"{sorted(NETWORK_POINTS)} take network actions "
                f"{_NET_ACTIONS}; store points {sorted(STORE_POINTS)} "
                f"take store actions {_STORE_ACTIONS})")
        self.action = spec["action"]
        if self.action not in _ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r}; expected one of "
                f"{_ACTIONS}")
        self.step = spec.get("step")
        self.path = spec.get("path")
        self.env = spec.get("env") or {}
        self.count = spec.get("count")
        self.seconds = spec.get("seconds")
        self.exit_code = int(spec.get("exit_code", 23))
        self.exc = spec.get("exc", "OSError")
        if self.exc not in _EXC_TYPES:
            raise ValueError(
                f"unknown exc type {self.exc!r}; expected one of "
                f"{tuple(_EXC_TYPES)}")
        self.fired = 0

    def matches(self, point, step, path):
        if point != self.point:
            return False
        if self.count is not None and self.fired >= self.count:
            return False
        if self.step is not None and step != self.step:
            return False
        if self.path is not None:
            if path is None:
                return False
            if not (fnmatch.fnmatch(path, self.path)
                    or fnmatch.fnmatch(os.path.basename(path), self.path)):
                return False
        for k, v in self.env.items():
            if os.environ.get(k) != str(v):
                return False
        return True

    def perform(self, point, step, path):
        self.fired += 1
        if self.action == "crash":
            os._exit(self.exit_code)
        elif self.action == "sigkill":
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(30)          # SIGKILL needs no handler; just wait
        elif self.action == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        elif self.action == "hang":
            time.sleep(self.seconds if self.seconds is not None else 3600)
        elif self.action == "sleep":
            time.sleep(self.seconds if self.seconds is not None else 0.1)
        elif self.action == "raise":
            raise _EXC_TYPES[self.exc](
                f"fault injected at {point!r}"
                + (f" step={step}" if step is not None else "")
                + (f" path={path}" if path is not None else ""))
        elif self.action == "bitflip":
            if path is None:
                raise ValueError(
                    f"bitflip rule at {point!r} fired without a path")
            bitflip(path)


class NetworkVerdict:
    """What the matching network rules decided for ONE message. The
    transport interprets it: ``drop`` — never send (the caller times
    out); ``delay`` — sleep this long before handing the message to the
    transport; ``hold`` — claim the mailbox slot first, THEN sleep this
    long before the payload lands (reorder's head-of-line shape);
    ``copies`` — deliver this many extra copies."""

    __slots__ = ("drop", "delay", "hold", "copies")

    def __init__(self):
        self.drop = False
        self.delay = 0.0
        self.hold = 0.0
        self.copies = 0

    def __bool__(self):
        return self.drop or self.delay > 0 or self.hold > 0 \
            or self.copies > 0

    def __repr__(self):
        return (f"NetworkVerdict(drop={self.drop}, delay={self.delay}, "
                f"hold={self.hold}, copies={self.copies})")


#: shared falsy verdict returned when no rule matched (never mutated)
_NO_VERDICT = NetworkVerdict()


class NetworkRule:
    """One parsed network-plan entry. Matching is pure except for the
    rule-local seeded RNG draw (``p``) and the partition window clock;
    the verdict is applied by the transport, not here."""

    def __init__(self, spec):
        unknown = set(spec) - _NET_RULE_KEYS
        if unknown:
            raise ValueError(
                f"unknown network fault rule key(s) {sorted(unknown)}; "
                f"expected a subset of {sorted(_NET_RULE_KEYS)}")
        self.point = spec["point"]
        if self.point not in NETWORK_POINTS:
            raise ValueError(
                f"unregistered network fault point {self.point!r}; "
                f"instrumented message points are "
                f"{sorted(NETWORK_POINTS)}")
        self.action = spec["action"]
        if self.action not in _NET_ACTIONS:
            raise ValueError(
                f"unknown network fault action {self.action!r}; "
                f"expected one of {_NET_ACTIONS}")
        self.src = spec.get("src")
        self.dst = spec.get("dst")
        self.p = float(spec.get("p", 1.0))
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"network rule p={self.p} outside [0, 1]")
        self.seed = int(spec.get("seed", 0))
        self.count = spec.get("count")
        self.step = spec.get("step")
        self.seconds = spec.get("seconds")
        self.copies = int(spec.get("copies", 1))
        self.env = spec.get("env") or {}
        self._rng = random.Random(self.seed)
        self._window_start = None       # partition: first-match stamp
        self.fired = 0

    def _endpoint_match(self, pattern, name):
        if pattern is None:
            return True
        if name is None:
            return False
        return fnmatch.fnmatch(str(name), pattern)

    def matches(self, point, src, dst, step):
        if point != self.point:
            return False
        if not (self._endpoint_match(self.src, src)
                and self._endpoint_match(self.dst, dst)):
            return False
        if self.step is not None and step != self.step:
            return False
        for k, v in self.env.items():
            if os.environ.get(k) != str(v):
                return False
        if self.action == "partition":
            # window semantics: active for `seconds` of wall clock from
            # the FIRST match; p/count do not apply — a partition drops
            # everything it sees while it lasts
            now = time.monotonic()
            if self._window_start is None:
                self._window_start = now
            return now - self._window_start \
                < (self.seconds if self.seconds is not None else 1.0)
        if self.count is not None and self.fired >= self.count:
            return False
        if self.p < 1.0 and self._rng.random() >= self.p:
            return False
        return True

    def apply(self, verdict):
        self.fired += 1
        if self.action in ("drop", "partition"):
            verdict.drop = True
        elif self.action == "delay":
            verdict.delay += self.seconds if self.seconds is not None \
                else 0.05
        elif self.action == "duplicate":
            verdict.copies += self.copies
        elif self.action == "reorder":
            verdict.hold += self._rng.uniform(
                0.0, self.seconds if self.seconds is not None else 0.2)
        return verdict


class StoreVerdict:
    """What the matching store rules decided for ONE socket operation.
    The CLIENT interprets it (see the module docstring): ``slow`` /
    ``hang`` are seconds to sleep (hang then raises a timeout),
    ``refuse`` / ``reset`` / ``torn`` are the typed failure to
    simulate."""

    __slots__ = ("refuse", "reset", "hang", "slow", "torn")

    def __init__(self):
        self.refuse = False
        self.reset = False
        self.hang = 0.0
        self.slow = 0.0
        self.torn = False

    def __bool__(self):
        return self.refuse or self.reset or self.torn \
            or self.hang > 0 or self.slow > 0

    def __repr__(self):
        return (f"StoreVerdict(refuse={self.refuse}, "
                f"reset={self.reset}, hang={self.hang}, "
                f"slow={self.slow}, torn={self.torn})")


#: shared falsy verdict returned when no store rule matched
_NO_STORE_VERDICT = StoreVerdict()


class StoreRule:
    """One parsed store-socket plan entry (points ``store.connect`` /
    ``store.frame``). Matching mirrors :class:`NetworkRule`'s seeded
    determinism; the verdict is applied by the store client."""

    def __init__(self, spec):
        unknown = set(spec) - _STORE_RULE_KEYS
        if unknown:
            raise ValueError(
                f"unknown store fault rule key(s) {sorted(unknown)}; "
                f"expected a subset of {sorted(_STORE_RULE_KEYS)}")
        self.point = spec["point"]
        if self.point not in STORE_POINTS:
            raise ValueError(
                f"unregistered store fault point {self.point!r}; "
                f"instrumented socket points are "
                f"{sorted(STORE_POINTS)}")
        self.action = spec["action"]
        if self.action not in _STORE_ACTIONS:
            raise ValueError(
                f"unknown store fault action {self.action!r}; "
                f"expected one of {_STORE_ACTIONS}")
        self.step = spec.get("step")
        self.path = spec.get("path")
        self.p = float(spec.get("p", 1.0))
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"store rule p={self.p} outside [0, 1]")
        self.seed = int(spec.get("seed", 0))
        self.count = spec.get("count")
        self.seconds = spec.get("seconds")
        self.env = spec.get("env") or {}
        self._rng = random.Random(self.seed)
        self.fired = 0

    def matches(self, point, step, path):
        if point != self.point:
            return False
        if self.count is not None and self.fired >= self.count:
            return False
        if self.step is not None and step != self.step:
            return False
        if self.path is not None:
            if path is None:
                return False
            if not fnmatch.fnmatch(str(path), self.path):
                return False
        for k, v in self.env.items():
            if os.environ.get(k) != str(v):
                return False
        if self.p < 1.0 and self._rng.random() >= self.p:
            return False
        return True

    def apply(self, verdict):
        self.fired += 1
        if self.action == "refuse":
            verdict.refuse = True
        elif self.action == "reset":
            verdict.reset = True
        elif self.action == "hang":
            verdict.hang += self.seconds if self.seconds is not None \
                else 1.0
        elif self.action == "slow":
            verdict.slow += self.seconds if self.seconds is not None \
                else 0.05
        elif self.action == "torn":
            verdict.torn = True
        return verdict


class FaultPlan:
    def __init__(self, rules):
        self.rules = []
        self.net_rules = []
        self.store_rules = []
        # network matching mutates rule state (count, seeded rng,
        # partition window) and is called from concurrent rpc driver
        # threads and heartbeat sidecars: serialize it, or a count=1
        # rule fires twice and seeded replays stop being deterministic
        self._net_lock = threading.Lock()
        for r in rules:
            if isinstance(r, (FaultRule, NetworkRule, StoreRule)):
                rule = r
            elif r.get("point") in STORE_POINTS:
                # socket points take store actions only — routed by
                # point, since "hang" is also a process action
                rule = StoreRule(r)
            elif r.get("action") in _NET_ACTIONS:
                rule = NetworkRule(r)
            else:
                rule = FaultRule(r)
            if isinstance(rule, NetworkRule):
                self.net_rules.append(rule)
            elif isinstance(rule, StoreRule):
                self.store_rules.append(rule)
            else:
                self.rules.append(rule)

    def fire(self, point, step=None, path=None):
        for rule in self.rules:
            if rule.matches(point, step, path):
                rule.perform(point, step, path)

    def fire_copy(self, point, step=None, path=None):
        torn = False
        for rule in self.rules:
            if not rule.matches(point, step, path):
                continue
            if rule.action == "bitflip":
                # an in-memory copy has no file to flip: consume the
                # rule and report the tear back for the caller
                rule.fired += 1
                torn = True
            else:
                rule.perform(point, step, path)
        return torn

    def fire_network(self, point, src=None, dst=None, step=None):
        verdict = None
        with self._net_lock:
            for rule in self.net_rules:
                if rule.matches(point, src, dst, step):
                    verdict = rule.apply(verdict or NetworkVerdict())
        return verdict if verdict is not None else _NO_VERDICT

    def fire_store(self, point, step=None, path=None):
        verdict = None
        with self._net_lock:
            for rule in self.store_rules:
                if rule.matches(point, step, path):
                    verdict = rule.apply(verdict or StoreVerdict())
        return verdict if verdict is not None else _NO_STORE_VERDICT


_plan: "FaultPlan | None" = None
_parsed = False


def plan():
    """The process fault plan parsed (once) from ``PADDLE_TPU_FAULTS``,
    or None when the variable is unset/empty."""
    global _plan, _parsed
    if not _parsed:
        raw = os.environ.get(PLAN_ENV)
        _plan = FaultPlan(json.loads(raw)) if raw else None
        _parsed = True
    return _plan


def reset():
    """Forget the cached plan so the next :func:`fire` re-reads the
    environment (test hook; also clears per-rule fire counts)."""
    global _plan, _parsed
    _plan = None
    _parsed = False


def active():
    return plan() is not None


def fire(point, step=None, path=None):
    """Instrumented-point hook: no-op (one cached-None check) without a
    plan; otherwise every matching rule performs its action in plan
    order. ``raise`` rules propagate; crash-family rules never return."""
    p = plan()
    if p is not None:
        p.fire(point, step=step, path=path)


def fire_copy(point, step=None, path=None):
    """Copy-point hook (``tier.d2h`` / ``tier.h2d``): like :func:`fire`
    for every matching rule EXCEPT ``bitflip`` — an in-flight
    device↔host copy has no file to flip, so a matching bitflip rule is
    consumed and reported back (returns True) for the CALLER to tear
    the in-memory buffer it is copying. ``sleep`` rules model a slow
    copy, ``raise`` a failed one, ``bitflip`` a torn one."""
    p = plan()
    return p.fire_copy(point, step=step, path=path) \
        if p is not None else False


def fire_network(point, src=None, dst=None, step=None):
    """Message-point hook: returns the merged :class:`NetworkVerdict`
    of every matching network rule (a shared falsy verdict without a
    plan — one cached-None check on the hot path). The TRANSPORT
    applies the verdict; this function never sleeps or raises."""
    p = plan()
    if p is None:
        return _NO_VERDICT
    return p.fire_network(point, src=src, dst=dst, step=step)


def fire_store(point, step=None, path=None):
    """Socket-point hook (``store.connect`` / ``store.frame``):
    returns the merged :class:`StoreVerdict` of every matching store
    rule (a shared falsy verdict without a plan — one cached-None
    check on the hot path). The store CLIENT applies the verdict —
    sleeping for ``slow``/``hang`` and raising the typed connection
    failure — so every injected fault flows through the same
    retry/reconnect machinery a real one would."""
    p = plan()
    if p is None:
        return _NO_STORE_VERDICT
    return p.fire_store(point, step=step, path=path)


def rename(src, dst, step=None):
    """``os.rename`` with an injection point in front: a plan rule at
    point ``"rename"`` can delay (``sleep``), fail (``raise``), or kill
    the process (``sigkill``/``crash``) before the rename happens — the
    torn-commit cases an atomic checkpoint must survive."""
    fire("rename", step=step, path=dst)
    os.rename(src, dst)


def bitflip(path, offset=None, mask=0xFF):
    """Flip bits of one byte of ``path`` in place (default: the middle
    byte). The minimal storage corruption a checksum must catch."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot bitflip empty file {path}")
    if offset is None:
        offset = size // 2
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ mask]))
        f.flush()
        os.fsync(f.fileno())
