"""Deterministic fault injection for crash/recovery tests.

Reference: the recovery model of `fleet/elastic/manager.py` (detect a
failure, relaunch, resume from checkpoint) is only provable if the
failure itself is reproducible. This module makes failures first-class
test inputs: production code calls :func:`fire` at named points
("ckpt.write", "rename", "train.step", ...) and a *fault plan* — a JSON
list of rules in the ``PADDLE_TPU_FAULTS`` environment variable —
decides, deterministically, what happens there: nothing (the default,
one dict lookup when no plan is set), a crash, a signal, a hang, a
slow-down, an injected ``OSError``, or a bit-flip of a file.

Because the plan travels through the environment, subprocess tests
activate it without patching any code: the launcher test sets
``PADDLE_TPU_FAULTS='[{"point": "rename", "step": 3, "action":
"sigkill"}]'`` and the worker under test dies mid-save of step 3,
exactly once, every run.

Rule fields (all optional except ``point`` and ``action``):

- ``point``: instrumented point name (exact match). Instrumented so
  far: the checkpoint commit path (``ckpt.write``,
  ``ckpt.before_marker``, ``rename``), the training loop
  (``train.step``), the serving request lifecycle
  (``serve.admit`` — fired per admission attempt, so a ``raise`` rule
  with ``exc: "MemoryError"`` simulates KV-pool pressure and drives
  the degradation ladder; ``serve.decode`` — fired before each
  step/burst dispatch, ``step`` = dispatch ordinal; ``serve.drain`` —
  fired as a graceful drain begins), and the multi-replica serving
  tier (``replica.dead`` — fired per replica worker-loop tick with
  ``step`` = tick ordinal and ``path`` = the replica id, so a
  ``raise``/``hang`` rule kills replica N at tick K and the router's
  membership TTL + failover path runs deterministically in CI;
  ``router.route`` — fired per routing decision with ``step`` = the
  route ordinal, so a ``raise`` rule injects routing errors;
  ``serve.spawn`` — fired in the SUPERVISOR before each replica
  spawn/restart attempt with ``path`` = the replica id and ``step`` =
  the spawn ordinal, so a ``raise`` rule deterministically fails
  process spawn — the supervisor's exponential backoff and crash-loop
  circuit breaker run without a single real process; ``replica
  .heartbeat`` — fired on the replica's heartbeat sidecar before each
  stamp refresh with ``path`` = the replica id and ``step`` = the beat
  ordinal, so a ``hang``/``sleep`` rule freezes heartbeats and the
  replica silently ages out of membership, driving TTL death detection
  and, repeated, the circuit breaker).
- ``action``: one of ``crash`` (``os._exit``), ``sigkill``, ``sigterm``
  (signal self), ``hang`` (sleep ~forever), ``sleep`` (slow-down, then
  continue), ``raise`` (``OSError`` by default; see ``exc``),
  ``bitflip`` (corrupt the file at the point's ``path``).
- ``exc``: for ``raise`` — the exception type to inject, one of
  ``OSError`` (default), ``MemoryError``, ``TimeoutError``,
  ``RuntimeError``. Lets a plan exercise typed failure paths (e.g.
  admission pressure is a ``MemoryError`` contract).
- ``step``: only fire when the call site passes this step number.
- ``path``: fnmatch glob matched against the call site's path (full
  path or basename).
- ``env``: ``{name: value}`` — only fire when every named environment
  variable currently has that value (e.g. restrict a kill to elastic
  generation 0 via ``{"PADDLE_RESTART_COUNT": "0"}``).
- ``count``: fire at most this many times per process (default:
  unlimited).
- ``seconds``: duration for ``sleep`` / ``hang`` (defaults 0.1 / 3600).
- ``exit_code``: for ``crash`` (default 23).
"""

from __future__ import annotations

import fnmatch
import json
import os
import signal
import time

__all__ = ["PLAN_ENV", "FaultRule", "FaultPlan", "plan", "reset",
           "active", "fire", "rename", "bitflip"]

#: environment variable holding the JSON fault plan
PLAN_ENV = "PADDLE_TPU_FAULTS"

_ACTIONS = ("crash", "sigkill", "sigterm", "hang", "sleep", "raise",
            "bitflip")

#: injectable exception types for ``raise`` rules — a closed set, so a
#: plan can't name arbitrary symbols
_EXC_TYPES = {"OSError": OSError, "MemoryError": MemoryError,
              "TimeoutError": TimeoutError, "RuntimeError": RuntimeError}


class FaultRule:
    """One parsed plan entry. Matching is pure; firing performs the
    action (and may not return)."""

    def __init__(self, spec):
        self.point = spec["point"]
        self.action = spec["action"]
        if self.action not in _ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r}; expected one of "
                f"{_ACTIONS}")
        self.step = spec.get("step")
        self.path = spec.get("path")
        self.env = spec.get("env") or {}
        self.count = spec.get("count")
        self.seconds = spec.get("seconds")
        self.exit_code = int(spec.get("exit_code", 23))
        self.exc = spec.get("exc", "OSError")
        if self.exc not in _EXC_TYPES:
            raise ValueError(
                f"unknown exc type {self.exc!r}; expected one of "
                f"{tuple(_EXC_TYPES)}")
        self.fired = 0

    def matches(self, point, step, path):
        if point != self.point:
            return False
        if self.count is not None and self.fired >= self.count:
            return False
        if self.step is not None and step != self.step:
            return False
        if self.path is not None:
            if path is None:
                return False
            if not (fnmatch.fnmatch(path, self.path)
                    or fnmatch.fnmatch(os.path.basename(path), self.path)):
                return False
        for k, v in self.env.items():
            if os.environ.get(k) != str(v):
                return False
        return True

    def perform(self, point, step, path):
        self.fired += 1
        if self.action == "crash":
            os._exit(self.exit_code)
        elif self.action == "sigkill":
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(30)          # SIGKILL needs no handler; just wait
        elif self.action == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
        elif self.action == "hang":
            time.sleep(self.seconds if self.seconds is not None else 3600)
        elif self.action == "sleep":
            time.sleep(self.seconds if self.seconds is not None else 0.1)
        elif self.action == "raise":
            raise _EXC_TYPES[self.exc](
                f"fault injected at {point!r}"
                + (f" step={step}" if step is not None else "")
                + (f" path={path}" if path is not None else ""))
        elif self.action == "bitflip":
            if path is None:
                raise ValueError(
                    f"bitflip rule at {point!r} fired without a path")
            bitflip(path)


class FaultPlan:
    def __init__(self, rules):
        self.rules = [r if isinstance(r, FaultRule) else FaultRule(r)
                      for r in rules]

    def fire(self, point, step=None, path=None):
        for rule in self.rules:
            if rule.matches(point, step, path):
                rule.perform(point, step, path)


_plan: "FaultPlan | None" = None
_parsed = False


def plan():
    """The process fault plan parsed (once) from ``PADDLE_TPU_FAULTS``,
    or None when the variable is unset/empty."""
    global _plan, _parsed
    if not _parsed:
        raw = os.environ.get(PLAN_ENV)
        _plan = FaultPlan(json.loads(raw)) if raw else None
        _parsed = True
    return _plan


def reset():
    """Forget the cached plan so the next :func:`fire` re-reads the
    environment (test hook; also clears per-rule fire counts)."""
    global _plan, _parsed
    _plan = None
    _parsed = False


def active():
    return plan() is not None


def fire(point, step=None, path=None):
    """Instrumented-point hook: no-op (one cached-None check) without a
    plan; otherwise every matching rule performs its action in plan
    order. ``raise`` rules propagate; crash-family rules never return."""
    p = plan()
    if p is not None:
        p.fire(point, step=step, path=path)


def rename(src, dst, step=None):
    """``os.rename`` with an injection point in front: a plan rule at
    point ``"rename"`` can delay (``sleep``), fail (``raise``), or kill
    the process (``sigkill``/``crash``) before the rename happens — the
    torn-commit cases an atomic checkpoint must survive."""
    fire("rename", step=step, path=dst)
    os.rename(src, dst)


def bitflip(path, offset=None, mask=0xFF):
    """Flip bits of one byte of ``path`` in place (default: the middle
    byte). The minimal storage corruption a checksum must catch."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot bitflip empty file {path}")
    if offset is None:
        offset = size // 2
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ mask]))
        f.flush()
        os.fsync(f.fileno())
