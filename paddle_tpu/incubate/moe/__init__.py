"""Mixture-of-Experts with expert parallelism.

Reference: `python/paddle/incubate/distributed/models/moe/moe_layer.py:263`
(``MoELayer``), gates `moe/gate/{gshard,switch,naive}_gate.py`, capacity
utils `moe/utils.py:59`, and the CUDA dispatch collectives
`fluid/operators/collective/global_scatter_op.cu.cc` (+
`distributed/utils/moe_utils.py:20,153`).

TPU-native re-design (GShard formulation): instead of the reference's
index-based global_scatter/global_gather over NCCL, dispatch and combine
are DENSE einsums against one-hot capacity masks —

    dispatched[e, c, d] = sum_n dispatch[n, e, c] * x[n, d]
    out[n, d]           = sum_{e,c} combine[n, e, c] * expert_out[e, c, d]

with the expert dimension sharded over the mesh's ``ep`` axis. GSPMD
lowers the ``n -> e`` resharding to an all-to-all riding the ICI — the
same traffic pattern as the reference's global_scatter, but emitted by
the compiler and fused with the surrounding matmuls. Capacity is a static
shape (XLA needs it); overflow tokens are dropped exactly like the
reference's capacity limiting (`moe/utils.py:59`).

The scalable path (``dispatch_mode="ragged"``) replaces the dense
one-hot dispatch with sort-based routing plus the Pallas grouped-GEMM
megakernel (:mod:`paddle_tpu.ops.grouped_gemm`, the *MPK*/*Neptune*
operator-fusion direction): gather tokens once into expert-contiguous
rows, run grouped-GEMM(w1) + gelu + grouped-GEMM(w2) over the ragged
row blocks, gather back — dense-path parity preserved bit-for-bit,
capacity drops included.
"""

from __future__ import annotations

import collections
import math

import jax
import jax.numpy as jnp

from ... import nn
from ...framework.tensor import Parameter, Tensor, run_op
from ...framework import random as frandom
from ...observability import compile_watch as _cw
from ...observability import metrics as _om
from ...ops.grouped_gemm import _grouped as _grouped_gemm

__all__ = ["MoELayer", "top_k_gating", "top_k_routing", "NaiveGate",
           "GShardGate", "SwitchGate"]


def top_k_gating(logits, k, capacity, normalize=True):
    """Pure-jnp top-k gating with per-expert capacity.

    Returns (dispatch [N,E,C] one-hot, combine [N,E,C] weights, aux_loss).
    Reference: gshard_gate.py top2 routing + utils.py:59 capacity limit.
    """
    n, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, k)                 # [N, k]
    if normalize:
        topv = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-9)

    # load-balancing auxiliary loss (switch/gshard): E * mean_e(me * ce)
    me = jnp.mean(probs, axis=0)                          # mean gate prob
    ce = jnp.mean(jax.nn.one_hot(topi[:, 0], e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)

    dispatch = jnp.zeros((n, e, capacity), jnp.float32)
    combine = jnp.zeros((n, e, capacity), jnp.float32)
    counts = jnp.zeros((e,), jnp.int32)                   # filled slots
    for j in range(k):
        oh = jax.nn.one_hot(topi[:, j], e, dtype=jnp.int32)   # [N, E]
        pos = jnp.cumsum(oh, axis=0) - 1 + counts[None, :]    # slot index
        counts = counts + jnp.sum(oh, axis=0)
        pos_tok = jnp.sum(pos * oh, axis=1)                   # [N]
        keep = (pos_tok < capacity).astype(jnp.float32)
        slot = jax.nn.one_hot(jnp.clip(pos_tok, 0, capacity - 1),
                              capacity, dtype=jnp.float32)    # [N, C]
        mask = oh.astype(jnp.float32)[:, :, None] * slot[:, None, :] \
            * keep[:, None, None]
        dispatch = dispatch + mask
        combine = combine + topv[:, j][:, None, None] * mask
    return dispatch, combine, aux


def top_k_routing(logits, k, capacity, normalize=True):
    """Sort-based (ragged) routing — the scalable replacement for the
    dense one-hot masks (reference semantics:
    `fluid/operators/collective/global_scatter_op.cu.cc` — index-based
    dispatch). Cost is O(Nk log Nk) sort + O(E*C) scatter instead of the
    dense O(N*E*C) mask build, so it survives DeepSeekMoE-class expert
    counts.

    Slot assignment mirrors the dense path bit-for-bit: entries are laid
    out k-major (all first choices, then all second choices, token order
    within each), and the stable sort by expert preserves that order, so
    capacity overflow drops the same tokens.

    Returns (slot_token [E*C] int32 (-1 = empty slot),
             expert_of [N, k], pos_of [N, k], keep [N, k],
             weights [N, k], aux_loss).
    """
    n, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, k)                 # [N, k]
    if normalize:
        topv = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-9)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(topi[:, 0], e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)

    nk = n * k
    flat_expert = topi.T.reshape(-1)                     # k-major [nk]
    flat_token = jnp.tile(jnp.arange(n, dtype=jnp.int32), k)
    order = jnp.argsort(flat_expert, stable=True)
    se = flat_expert[order]
    st = flat_token[order]
    # position within each expert's contiguous group
    group_start = jnp.searchsorted(se, jnp.arange(e), side="left")
    pos_sorted = jnp.arange(nk, dtype=jnp.int32) - group_start[se]
    keep_sorted = pos_sorted < capacity
    buf_idx = se * capacity + jnp.clip(pos_sorted, 0, capacity - 1)
    buf_idx = jnp.where(keep_sorted, buf_idx, e * capacity)  # OOB -> drop
    slot_token = jnp.full((e * capacity,), -1, jnp.int32) \
        .at[buf_idx].set(st, mode="drop")
    # un-sort pos/keep back to [N, k] for the combine gather
    pos_flat = jnp.zeros((nk,), jnp.int32).at[order].set(pos_sorted)
    keep_flat = jnp.zeros((nk,), bool).at[order].set(keep_sorted)
    pos_of = pos_flat.reshape(k, n).T
    keep = keep_flat.reshape(k, n).T
    return slot_token, topi, pos_of, keep, topv, aux


def _watched_fn_cache(cache, n_tokens, build, name, limit):
    """Bounded-LRU lookup of a compile-watched per-token-count forward
    — the one mechanism behind ``MoELayer.build_fn`` and
    ``LlamaMoEMLP.build_fn``: each new token count builds + wraps with
    :func:`~paddle_tpu.observability.compile_watch.watched_jit` (so
    recompiles are counted under ``name``), and the oldest entries are
    evicted past ``limit``."""
    fn = cache.get(n_tokens)
    if fn is None:
        fn = _cw.watched_jit(build(n_tokens), name=name)
        cache[n_tokens] = fn
        while len(cache) > limit:
            cache.popitem(last=False)
    else:
        cache.move_to_end(n_tokens)
    return fn


class _Gate:
    top_k = 2
    normalize = True

    def __init__(self, top_k=None):
        if top_k is not None:
            self.top_k = top_k


class NaiveGate(_Gate):
    """Top-k softmax, no balancing pressure (reference naive_gate.py)."""
    normalize = True


class GShardGate(_Gate):
    """Top-2 with load-balancing aux loss (reference gshard_gate.py)."""
    top_k = 2


class SwitchGate(_Gate):
    """Top-1 switch routing (reference switch_gate.py)."""
    top_k = 1
    normalize = False


_GATES = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}


class MoELayer(nn.Layer):
    """Expert-parallel MoE FFN block (reference moe_layer.py:263).

    ``forward(x)`` routes each token to its top-k experts (gelu MLPs with
    stacked weights ``[E, ...]``); with ``mesh`` given, expert weights are
    sharded over ``ep_axis`` and the dispatch einsum becomes the
    all-to-all. The load-balancing loss of the last forward is in
    ``self.l_aux`` — add ``moe.l_aux * coeff`` to the training loss, as
    the reference's examples do.

    ``dispatch_mode="ragged"`` (the default) is the grouped-GEMM path:
    routing sorts token-choices by expert, ONE gather lays tokens out
    expert-contiguous, and two Pallas grouped GEMMs
    (:mod:`paddle_tpu.ops.grouped_gemm`) walk the ragged per-expert row
    blocks — empty experts skipped, tails masked — before one gather
    combines back. ``"dense"`` keeps the one-hot capacity-mask einsum
    formulation (the GShard reference bar both paths must match).
    """

    #: bound on the per-token-count compiled-forward cache (LRU):
    #: ragged serving token counts must not grow the jit cache (and
    #: its executables) without bound
    FN_CACHE_SIZE = 8

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 top_k=None, capacity_factor=1.25, mesh=None, ep_axis="ep",
                 dispatch_mode="ragged", name=None):
        super().__init__()
        if dispatch_mode not in ("ragged", "dense"):
            raise ValueError("dispatch_mode must be 'ragged' or 'dense'")
        self.dispatch_mode = dispatch_mode
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        if isinstance(gate, str):
            gate = _GATES[gate](top_k)
        elif isinstance(gate, type):
            gate = gate(top_k)
        elif top_k is not None and top_k != gate.top_k:
            # never mutate a caller-owned gate instance
            fresh = type(gate)(top_k)
            fresh.normalize = gate.normalize
            gate = fresh
        self.gate = gate
        self.mesh = mesh
        self.ep_axis = ep_axis

        def init(shape, scale):
            return Parameter(jax.random.normal(
                frandom.next_key(), shape, jnp.float32) * scale)

        e = num_experts
        self.gate_weight = init((d_model, e), 1.0 / math.sqrt(d_model))
        self.w1 = init((e, d_model, d_hidden), 1.0 / math.sqrt(d_model))
        self.b1 = Parameter(jnp.zeros((e, d_hidden), jnp.float32))
        self.w2 = init((e, d_hidden, d_model), 1.0 / math.sqrt(d_hidden))
        self.b2 = Parameter(jnp.zeros((e, d_model), jnp.float32))
        if mesh is not None:
            from ...distributed import shard_tensor, Shard, Replicate
            place = [Replicate()] * mesh.ndim
            place[mesh.dim_names.index(ep_axis)] = Shard(0)
            for attr in ("w1", "b1", "w2", "b2"):
                setattr(self, attr,
                        shard_tensor(getattr(self, attr), mesh, place))
        self.l_aux = None
        # token-count -> watched-jit forward; bounded LRU — serving
        # traffic with ragged token counts must not grow this (and its
        # compiled executables) without bound
        self._fns: "collections.OrderedDict[int, object]" = \
            collections.OrderedDict()

    def _expert_sharding(self, ndim):
        from jax.sharding import NamedSharding, PartitionSpec
        spec = [None] * ndim
        spec[0] = self.ep_axis
        return NamedSharding(self.mesh.to_jax_mesh(), PartitionSpec(*spec))

    def _build_fn(self, n_tokens):
        k = self.gate.top_k
        cap = self.capacity(n_tokens)
        e = self.num_experts
        normalize = self.gate.normalize
        constrain = self.mesh is not None
        if constrain:
            disp_sharding = self._expert_sharding(3)
            # [E*cap, D] rows are expert-major, so sharding dim 0 over
            # ``ep`` splits whole expert row-blocks across the mesh
            row_sharding = self._expert_sharding(2)
        ragged = self.dispatch_mode == "ragged"

        def expert_ffn(dispatched, w1, b1, w2, b2):
            h = jax.nn.gelu(
                jnp.einsum("ecd,edh->ech", dispatched, w1) + b1[:, None, :])
            eo = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
            if constrain:
                eo = jax.lax.with_sharding_constraint(eo, disp_sharding)
            return eo

        def fn_dense(x2d, wg, w1, b1, w2, b2):
            n = x2d.shape[0]
            logits = jnp.matmul(x2d.astype(jnp.float32), wg)
            dispatch, combine, aux = top_k_gating(logits, k, cap, normalize)
            dispatch = dispatch.astype(x2d.dtype)
            combine = combine.astype(x2d.dtype)
            # n -> (e, c): GSPMD turns this resharding into the all-to-all
            dispatched = jnp.einsum("nec,nd->ecd", dispatch, x2d)
            if constrain:
                dispatched = jax.lax.with_sharding_constraint(
                    dispatched, disp_sharding)
            eo = expert_ffn(dispatched, w1, b1, w2, b2)
            out = jnp.einsum("nec,ecd->nd", combine, eo)
            dropped = jnp.round(n * k - jnp.sum(dispatch
                                                .astype(jnp.float32))) \
                .astype(jnp.int32)
            return out, aux, dropped

        def fn_ragged(x2d, wg, w1, b1, w2, b2):
            n = x2d.shape[0]
            logits = jnp.matmul(x2d.astype(jnp.float32), wg)
            slot_token, expert_of, pos_of, keep, weights, aux = \
                top_k_routing(logits, k, cap, normalize)
            # grouped-GEMM dispatch (ROADMAP item 4): ONE gather lays
            # tokens out expert-contiguous (expert e owns rows
            # [e*cap, e*cap + gs[e])); the two grouped GEMMs walk those
            # ragged row blocks in one kernel each — no [E, C, D]
            # zero-padded dispatch einsum, no per-expert loop. Rows
            # past gs[e] (empty slots) are masked inside the kernel,
            # so the gather needs no zeroing multiply.
            gs = jnp.zeros((e,), jnp.int32).at[expert_of.reshape(-1)] \
                .add(keep.reshape(-1).astype(jnp.int32))
            gathered = x2d[jnp.maximum(slot_token, 0)]      # [E*cap, D]
            if constrain:
                gathered = jax.lax.with_sharding_constraint(
                    gathered, row_sharding)
            # under SPMD the XLA formulation is forced: GSPMD partitions
            # the batched dot and emits the dispatch collectives; a
            # Pallas custom call would pin everything to one replica
            uk = False if constrain else None
            y1 = _grouped_gemm(gathered, w1, gs, use_kernel=uk)
            h = jax.nn.gelu(y1.reshape(e, cap, -1) + b1[:, None, :]) \
                .reshape(e * cap, -1)
            eo = _grouped_gemm(h, w2, gs, use_kernel=uk) \
                .reshape(e, cap, -1) + b2[:, None, :]
            if constrain:
                eo = jax.lax.with_sharding_constraint(eo, disp_sharding)
            # combine = one gather back: token n reads its k slots
            flat_eo = eo.reshape(e * cap, -1)
            idx = expert_of * cap + jnp.clip(pos_of, 0, cap - 1)  # [N, k]
            picked = flat_eo[idx]                                 # [N,k,D]
            w = (weights * keep).astype(x2d.dtype)
            out = jnp.einsum("nk,nkd->nd", w, picked)
            dropped = (n * k
                       - jnp.sum(keep.astype(jnp.int32))).astype(jnp.int32)
            return out, aux, dropped

        return fn_ragged if ragged else fn_dense

    def build_fn(self, n_tokens):
        """The compiled-forward function for ``n_tokens`` (public:
        bench and serving integrations call it instead of reaching into
        the private cache). Signature
        ``fn(x2d, gate_weight, w1, b1, w2, b2) -> (out, aux, dropped)``
        on raw arrays; compiled through the PR-2 compile watcher under
        the ``moe_layer`` name, so per-token-count recompiles are
        visible in ``paddle_tpu_xla_compile_total`` and the
        recompile-storm detector. The cache keeps the most recent
        :attr:`FN_CACHE_SIZE` token counts (LRU)."""
        return _watched_fn_cache(self._fns, int(n_tokens),
                                 self._build_fn, "moe_layer",
                                 self.FN_CACHE_SIZE)

    def forward(self, x):
        shape = x.shape
        d = shape[-1]
        n = 1
        for s in shape[:-1]:
            n *= s
        x2d = x.reshape([n, d])
        fn = self.build_fn(n)
        out, aux, dropped = run_op(
            "moe_layer", fn, (x2d, self.gate_weight, self.w1, self.b1,
                              self.w2, self.b2))
        self.l_aux = aux
        # capacity-overflow observability: tokens top_k_routing /
        # top_k_gating silently dropped past capacity this forward.
        # Metrics-off (or inside an outer trace, where the count is
        # abstract) this is zero-cost — no D2H sync.
        if _om.enabled() and not isinstance(dropped._data,
                                            jax.core.Tracer):
            nd = int(dropped._data)
            _om.counter("moe_dropped_tokens_total",
                        "token-choice slots dropped past expert "
                        "capacity").inc(nd)
            _om.gauge("moe_drop_fraction",
                      "dropped fraction of token-choice slots in the "
                      "last MoE forward").set(nd / float(n
                                                         * self.gate.top_k))
        return out.reshape(shape)

    def capacity(self, n_tokens):
        return max(1, int(math.ceil(
            n_tokens * self.capacity_factor * self.gate.top_k
            / self.num_experts)))
