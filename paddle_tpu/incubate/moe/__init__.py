"""Mixture-of-Experts with expert parallelism.

Reference: `python/paddle/incubate/distributed/models/moe/moe_layer.py:263`
(``MoELayer``), gates `moe/gate/{gshard,switch,naive}_gate.py`, capacity
utils `moe/utils.py:59`, and the CUDA dispatch collectives
`fluid/operators/collective/global_scatter_op.cu.cc` (+
`distributed/utils/moe_utils.py:20,153`).

TPU-native re-design (GShard formulation): instead of the reference's
index-based global_scatter/global_gather over NCCL, dispatch and combine
are DENSE einsums against one-hot capacity masks —

    dispatched[e, c, d] = sum_n dispatch[n, e, c] * x[n, d]
    out[n, d]           = sum_{e,c} combine[n, e, c] * expert_out[e, c, d]

with the expert dimension sharded over the mesh's ``ep`` axis. GSPMD
lowers the ``n -> e`` resharding to an all-to-all riding the ICI — the
same traffic pattern as the reference's global_scatter, but emitted by
the compiler and fused with the surrounding matmuls. Capacity is a static
shape (XLA needs it); overflow tokens are dropped exactly like the
reference's capacity limiting (`moe/utils.py:59`).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ... import nn
from ...framework.tensor import Parameter, Tensor, run_op
from ...framework import random as frandom

__all__ = ["MoELayer", "top_k_gating", "NaiveGate", "GShardGate",
           "SwitchGate"]


def top_k_gating(logits, k, capacity, normalize=True):
    """Pure-jnp top-k gating with per-expert capacity.

    Returns (dispatch [N,E,C] one-hot, combine [N,E,C] weights, aux_loss).
    Reference: gshard_gate.py top2 routing + utils.py:59 capacity limit.
    """
    n, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, k)                 # [N, k]
    if normalize:
        topv = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-9)

    # load-balancing auxiliary loss (switch/gshard): E * mean_e(me * ce)
    me = jnp.mean(probs, axis=0)                          # mean gate prob
    ce = jnp.mean(jax.nn.one_hot(topi[:, 0], e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)

    dispatch = jnp.zeros((n, e, capacity), jnp.float32)
    combine = jnp.zeros((n, e, capacity), jnp.float32)
    counts = jnp.zeros((e,), jnp.int32)                   # filled slots
    for j in range(k):
        oh = jax.nn.one_hot(topi[:, j], e, dtype=jnp.int32)   # [N, E]
        pos = jnp.cumsum(oh, axis=0) - 1 + counts[None, :]    # slot index
        counts = counts + jnp.sum(oh, axis=0)
        pos_tok = jnp.sum(pos * oh, axis=1)                   # [N]
        keep = (pos_tok < capacity).astype(jnp.float32)
        slot = jax.nn.one_hot(jnp.clip(pos_tok, 0, capacity - 1),
                              capacity, dtype=jnp.float32)    # [N, C]
        mask = oh.astype(jnp.float32)[:, :, None] * slot[:, None, :] \
            * keep[:, None, None]
        dispatch = dispatch + mask
        combine = combine + topv[:, j][:, None, None] * mask
    return dispatch, combine, aux


class _Gate:
    top_k = 2
    normalize = True

    def __init__(self, top_k=None):
        if top_k is not None:
            self.top_k = top_k


class NaiveGate(_Gate):
    """Top-k softmax, no balancing pressure (reference naive_gate.py)."""
    normalize = True


class GShardGate(_Gate):
    """Top-2 with load-balancing aux loss (reference gshard_gate.py)."""
    top_k = 2


class SwitchGate(_Gate):
    """Top-1 switch routing (reference switch_gate.py)."""
    top_k = 1
    normalize = False


_GATES = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}


class MoELayer(nn.Layer):
    """Expert-parallel MoE FFN block (reference moe_layer.py:263).

    ``forward(x)`` routes each token to its top-k experts (gelu MLPs with
    stacked weights ``[E, ...]``); with ``mesh`` given, expert weights are
    sharded over ``ep_axis`` and the dispatch einsum becomes the
    all-to-all. The load-balancing loss of the last forward is in
    ``self.l_aux`` — add ``moe.l_aux * coeff`` to the training loss, as
    the reference's examples do.
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 top_k=None, capacity_factor=1.25, mesh=None, ep_axis="ep",
                 name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        if isinstance(gate, str):
            gate = _GATES[gate](top_k)
        elif isinstance(gate, type):
            gate = gate(top_k)
        elif top_k is not None and top_k != gate.top_k:
            # never mutate a caller-owned gate instance
            fresh = type(gate)(top_k)
            fresh.normalize = gate.normalize
            gate = fresh
        self.gate = gate
        self.mesh = mesh
        self.ep_axis = ep_axis

        def init(shape, scale):
            return Parameter(jax.random.normal(
                frandom.next_key(), shape, jnp.float32) * scale)

        e = num_experts
        self.gate_weight = init((d_model, e), 1.0 / math.sqrt(d_model))
        self.w1 = init((e, d_model, d_hidden), 1.0 / math.sqrt(d_model))
        self.b1 = Parameter(jnp.zeros((e, d_hidden), jnp.float32))
        self.w2 = init((e, d_hidden, d_model), 1.0 / math.sqrt(d_hidden))
        self.b2 = Parameter(jnp.zeros((e, d_model), jnp.float32))
        if mesh is not None:
            from ...distributed import shard_tensor, Shard, Replicate
            place = [Replicate()] * mesh.ndim
            place[mesh.dim_names.index(ep_axis)] = Shard(0)
            for attr in ("w1", "b1", "w2", "b2"):
                setattr(self, attr,
                        shard_tensor(getattr(self, attr), mesh, place))
        self.l_aux = None
        self._fns = {}

    def _expert_sharding(self, ndim):
        from jax.sharding import NamedSharding, PartitionSpec
        spec = [None] * ndim
        spec[0] = self.ep_axis
        return NamedSharding(self.mesh.to_jax_mesh(), PartitionSpec(*spec))

    def _build_fn(self, n_tokens):
        k = self.gate.top_k
        cap = self.capacity(n_tokens)
        normalize = self.gate.normalize
        constrain = self.mesh is not None
        if constrain:
            disp_sharding = self._expert_sharding(3)

        def fn(x2d, wg, w1, b1, w2, b2):
            logits = jnp.matmul(x2d.astype(jnp.float32), wg)
            dispatch, combine, aux = top_k_gating(logits, k, cap, normalize)
            dispatch = dispatch.astype(x2d.dtype)
            combine = combine.astype(x2d.dtype)
            # n -> (e, c): GSPMD turns this resharding into the all-to-all
            dispatched = jnp.einsum("nec,nd->ecd", dispatch, x2d)
            if constrain:
                dispatched = jax.lax.with_sharding_constraint(
                    dispatched, disp_sharding)
            h = jax.nn.gelu(
                jnp.einsum("ecd,edh->ech", dispatched, w1) + b1[:, None, :])
            eo = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
            if constrain:
                eo = jax.lax.with_sharding_constraint(eo, disp_sharding)
            out = jnp.einsum("nec,ecd->nd", combine, eo)
            return out, aux

        return fn

    def forward(self, x):
        shape = x.shape
        d = shape[-1]
        n = 1
        for s in shape[:-1]:
            n *= s
        x2d = x.reshape([n, d])
        fn = self._fns.get(n)
        if fn is None:
            fn = self._fns[n] = self._build_fn(n)
        out, aux = run_op("moe_layer", fn,
                          (x2d, self.gate_weight, self.w1, self.b1,
                           self.w2, self.b2))
        self.l_aux = aux
        return out.reshape(shape)

    def capacity(self, n_tokens):
        return max(1, int(math.ceil(
            n_tokens * self.capacity_factor * self.gate.top_k
            / self.num_experts)))
