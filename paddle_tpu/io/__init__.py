"""``paddle_tpu.io`` — datasets, samplers, DataLoader.

Reference: `python/paddle/io/__init__.py`.
"""

from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    SubsetRandomSampler, BatchSampler, DistributedBatchSampler,
)
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .token_feed import TokenFeed, PyTokenFeed  # noqa: F401

__all__ = [
    "TokenFeed", "PyTokenFeed",
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "SubsetRandomSampler", "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "default_collate_fn",
]
