"""``paddle_tpu.io`` — datasets, samplers, DataLoader.

Reference: `python/paddle/io/__init__.py`.
"""

from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    SubsetRandomSampler, BatchSampler, DistributedBatchSampler,
)
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .token_feed import (  # noqa: F401
    TokenFeed, PyTokenFeed, DevicePrefetcher,
)


class WorkerInfo:
    """Reference `io/dataloader/worker.py:WorkerInfo`. The thread-pool
    loader has no per-worker dataset copies, so a single-worker view is
    always reported (id 0 of num_workers)."""

    def __init__(self, id=0, num_workers=1, dataset=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def get_worker_info():
    """Reference `paddle.io.get_worker_info`: None in the main process
    (always, here — workers are threads sharing the dataset object)."""
    return None

__all__ = [
    "TokenFeed", "PyTokenFeed", "DevicePrefetcher",
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "SubsetRandomSampler", "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "default_collate_fn", "get_worker_info", "WorkerInfo",
]
