"""Token-corpus feed: native C++ prefetcher with a numpy fallback.

``TokenFeed(path, sample_elems, batch_size)`` iterates ``[batch,
sample_elems]`` numpy batches over a flat binary corpus of fixed-size
samples — the host-side input path for pretraining recipes
(`examples/llama_pretrain.py`). When the native library is available
(`paddle_tpu/native/src/data_feed.cc` — the analog of the reference's
C++ feed threads, `fluid/framework/data_feed.cc`), batches are filled by
a C++ prefetch thread over an mmap; otherwise :class:`PyTokenFeed`
serves the same contract from ``np.memmap`` synchronously.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .. import native as _native

__all__ = ["TokenFeed", "PyTokenFeed", "DevicePrefetcher"]


class PyTokenFeed:
    """Pure-numpy fallback with identical iteration semantics to
    :class:`paddle_tpu.native.TokenFeed` (same per-epoch permutation is
    NOT guaranteed — the native feed shuffles with C++ mt19937 — but the
    visit-each-sample-once / drop-last contract is)."""

    def __init__(self, path, sample_elems, batch_size, dtype=np.int32,
                 shuffle=True, seed=0, prefetch_depth=4, epochs=-1):
        self.dtype = np.dtype(dtype)
        self.sample_elems = int(sample_elems)
        self.batch_size = int(batch_size)
        data = np.memmap(path, dtype=self.dtype, mode="r")
        n = data.size // self.sample_elems
        if n < self.batch_size:
            raise ValueError(
                f"TokenFeed: cannot open {path!r} (too small for one "
                f"batch of {batch_size} x {sample_elems} {self.dtype})")
        self._data = data[:n * self.sample_elems].reshape(
            n, self.sample_elems)
        self.shuffle, self.seed = shuffle, seed
        self.epochs = epochs
        self._epoch = 0
        self._step = 0
        self._order = self._epoch_order()

    @property
    def num_samples(self):
        return self._data.shape[0]

    @property
    def batches_per_epoch(self):
        return self.num_samples // self.batch_size

    def _epoch_order(self):
        if not self.shuffle:
            return np.arange(self.num_samples)
        return np.random.RandomState(
            self.seed + self._epoch).permutation(self.num_samples)

    def __iter__(self):
        return self

    def __next__(self):
        if self._step >= self.batches_per_epoch:
            self._epoch += 1
            if self.epochs > 0 and self._epoch >= self.epochs:
                raise StopIteration
            self._step = 0
            self._order = self._epoch_order()
        idx = self._order[self._step * self.batch_size:
                          (self._step + 1) * self.batch_size]
        self._step += 1
        return np.ascontiguousarray(self._data[idx])

    def close(self):
        pass


class DevicePrefetcher:
    """Double-buffered async host->device prefetch over any host-batch
    iterator.

    A background thread pulls the next host batch from ``source``,
    applies ``transform`` (e.g. split ``[B, S+1]`` ids into the train
    step's ``(ids, labels)`` views), and ``put``s every array leaf onto
    the device — so the NEXT batch's host work and H2D copy overlap the
    CURRENT step's device compute. Combined with
    ``jit.to_static(donate_inputs=True)`` this is the input half of the
    training hot loop: the step consumes a fresh donated device batch
    while the prefetcher is already copying the following one.

    ``depth`` bounds the queue (default 2: one batch in flight on
    device, one being filled — classic double buffering). Iteration
    ends when ``source`` does; a source exception re-raises in the
    consumer.

    Stall accounting: :meth:`mark` returns ``(stall_seconds,
    wall_seconds)`` since the previous mark — time the CONSUMER spent
    blocked waiting for a batch vs wall time — and publishes the ratio
    as the ``train_input_stall_frac`` gauge. A fraction near 0 means
    the input pipeline hides behind compute; anything above a few
    percent is headroom the accelerator is not getting.
    """

    def __init__(self, source, transform=None, depth=2, put=None):
        if put is None:
            import jax
            put = jax.device_put
        self._put = put
        self._transform = transform
        self._src = iter(source)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._stall = 0.0
        self._mark_stall = 0.0
        self._mark_t = time.perf_counter()
        self._terminal = None   # sticky: StopIteration / source error
        self.batches = 0
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="device-prefetch")
        self._thread.start()

    def _device_put_tree(self, item):
        import jax
        return jax.tree_util.tree_map(
            lambda leaf: self._put(np.ascontiguousarray(leaf))
            if isinstance(leaf, np.ndarray) else leaf, item)

    def _enqueue(self, entry):
        """put with a stop-aware timeout so close() never deadlocks on a
        full queue with no consumer."""
        while not self._stop.is_set():
            try:
                self._q.put(entry, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            while not self._stop.is_set():
                try:
                    item = next(self._src)
                except StopIteration:
                    self._enqueue(("end", None))
                    return
                if self._transform is not None:
                    item = self._transform(item)
                if not self._enqueue(("ok", self._device_put_tree(item))):
                    return
        except Exception as e:  # surface in the consumer, not the log
            self._enqueue(("err", e))

    def __iter__(self):
        return self

    def __next__(self):
        if self._terminal is not None:
            raise self._terminal
        if self._stop.is_set():
            raise StopIteration
        t0 = time.perf_counter()
        kind, payload = self._q.get()
        self._stall += time.perf_counter() - t0
        if kind == "end":
            # sticky: later next() calls re-raise instead of blocking
            # on a queue the worker will never fill again
            self._terminal = StopIteration()
            raise self._terminal
        if kind == "err":
            self._terminal = payload
            raise payload
        self.batches += 1
        return payload

    @property
    def stall_seconds(self):
        """Total consumer time spent blocked waiting for a batch."""
        return self._stall

    def mark(self):
        """(stall_seconds, wall_seconds) since the previous mark; also
        sets the ``train_input_stall_frac`` gauge to their ratio."""
        now = time.perf_counter()
        stall = self._stall - self._mark_stall
        wall = max(now - self._mark_t, 1e-9)
        self._mark_stall = self._stall
        self._mark_t = now
        try:
            from ..observability import metrics as om
            if om.enabled():
                om.gauge("train_input_stall_frac",
                         "fraction of the window the train loop spent "
                         "blocked on input prefetch").set(
                    min(1.0, stall / wall))
        except Exception:
            pass
        return stall, wall

    def close(self):
        self._stop.set()
        # drain so a worker blocked on put can observe the stop
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        src_close = getattr(self._src, "close", None)
        if callable(src_close):
            src_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def TokenFeed(path, sample_elems, batch_size, dtype=np.int32, shuffle=True,
              seed=0, prefetch_depth=4, epochs=-1):
    """Factory: the native prefetching feed when buildable, else the
    numpy fallback. Both yield ``[batch_size, sample_elems]`` arrays."""
    cls = _native.TokenFeed if _native.available() else PyTokenFeed
    return cls(path, sample_elems, batch_size, dtype=dtype, shuffle=shuffle,
               seed=seed, prefetch_depth=prefetch_depth, epochs=epochs)
