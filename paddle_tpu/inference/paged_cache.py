"""Paged KV-cache management for continuous-batching decode.

Reference capability: the paged/block KV cache behind
`paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu`
(block tables, per-sequence lengths, block reuse across requests). Host
side this is pure bookkeeping — :class:`PageAllocator` keeps a free list
of page ids and a block table per live sequence — while the device side
is two functional updates: scatter new K/V into the page pool
(`.at[...]` — XLA lowers to dynamic-update-slice / scatter on TPU), and
the Pallas `paged_attention` kernel reading through the table.

A transformer with L layers shares ONE allocator (the page structure is
identical per layer) across L per-layer pools — see
`paddle_tpu/inference/serving.py`. :class:`PagedKVCache` bundles an
allocator with a single pool for the one-layer case.
"""

from __future__ import annotations

import math
import threading
import warnings

import jax.numpy as jnp
import numpy as np

from ..observability import metrics as _om
from ..ops.paged_attention import paged_attention, paged_attention_xla

__all__ = ["PageAllocator", "PagedKVCache", "quantize_kv_int8"]


def quantize_kv_int8(x):
    """Symmetric per-head int8 quantization of K/V tokens over the
    last (head_dim) axis.

    ``x`` is ``[..., D]`` float K/V; returns ``(q, scale)`` where ``q``
    is int8 with the same shape and ``scale`` is ``x.shape[:-1]`` f32 —
    one scale per head per token slot, so every page slot's
    ``(int8, scale)`` pair is written exactly once by its own token
    write and later writes to OTHER slots of the page can never skew
    it. Dequantization is ``q.astype(f32) * scale[..., None]`` — done
    inside the paged kernels' kv loop, so pages live in HBM at half
    (bf16) / a quarter (f32) of their float bytes.

    Pure jnp — safe under jit/trace (the serving mixed program calls
    it per page write).
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    # multiply by the f32 reciprocal instead of dividing by 127: XLA
    # strength-reduces constant divides to reciprocal multiplies under
    # jit, so an eager divide and a compiled one differ by 1 ulp —
    # writing the multiply keeps the scale bitwise identical across
    # eager, jit and the fused kernel's in-Pallas quantizer
    scale = jnp.maximum(amax, 1e-8) * jnp.float32(1.0 / 127.0)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127) \
        .astype(jnp.int8)
    return q, scale


class PageAllocator:
    """Free-list page allocator + per-sequence block tables.

    Pages are **refcounted** so a page can be shared by several owners:
    a live sequence whose prompt prefix was already prefilled can
    reference the cached prefix pages (see
    :mod:`paddle_tpu.inference.prefix_cache`) instead of re-prefilling
    them, and a prefix cache can keep pages alive after the sequence
    that wrote them retired. A page returns to the free list only when
    its last reference drops. Writing into a shared page goes through
    :meth:`ensure_writable` — copy-on-write: the writer gets a private
    copy and the shared original stays immutable for its other owners.
    """

    def __init__(self, num_pages, page_size, max_pages_per_seq=None):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq or num_pages
        self._free = list(range(num_pages - 1, -1, -1))
        self._free_set = set(self._free)
        self._refs: dict[int, int] = {}     # page -> refcount (allocated)
        self._tables: dict[int, list[int]] = {}
        self._lens: dict[int, int] = {}
        # copy-on-write accounting: ensure_writable() copies are counted
        # so the page-aligned prefix-cache design (which should never
        # trigger one in the natural flow) stays observable
        self.cow_count = 0
        self._m_cow = _om.counter(
            "kv_page_cow_total",
            "copy-on-write page copies triggered by a write into a "
            "shared page")
        # double-free accounting: release() is idempotent (cancellation
        # racing a natural completion must not corrupt the free list),
        # but every ignored release is counted — a growing count means
        # a caller's lifecycle bookkeeping is wrong
        self.double_free_count = 0
        self._m_double_free = _om.counter(
            "kv_page_double_free_total",
            "release() calls ignored because the sequence or page was "
            "already free")
        # free-list mutations are check-then-pop; the serving engine's
        # admission backoff explicitly supports a second thread driving
        # step()/burst, so allocate/free must be atomic or a race leaks
        # popped pages (and escapes the MemoryError contract)
        self._lock = threading.Lock()

    @property
    def free_pages(self):
        return len(self._free)

    def live_sequences(self):
        return sorted(self._tables)

    def admit(self, seq_id, n_tokens, shared_pages=None):
        """Reserve pages for a new sequence of ``n_tokens`` (prefill).

        ``shared_pages`` (optional) is a list of already-allocated pages
        holding the sequence's prefix K/V — typically a prefix-cache
        match. They become the leading entries of the block table with
        their refcount bumped (shared, not owned), and only the
        remaining ``need - len(shared_pages)`` pages are drawn from the
        free list."""
        shared = list(shared_pages or ())
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id} already admitted")
            need = max(1, math.ceil(n_tokens / self.page_size))
            if need > self.max_pages_per_seq:
                raise ValueError(
                    f"{n_tokens} tokens needs {need} pages > "
                    f"max_pages_per_seq ({self.max_pages_per_seq})")
            if len(shared) > need:
                raise ValueError(
                    f"{len(shared)} shared prefix pages exceed the "
                    f"{need} pages {n_tokens} tokens need")
            for p in shared:
                if p in self._free_set or p not in self._refs:
                    raise ValueError(
                        f"shared page {p} is not allocated; a prefix "
                        f"match must hold a live reference")
            if need - len(shared) > len(self._free):
                raise MemoryError(
                    f"paged cache exhausted: need {need - len(shared)} "
                    f"pages, {len(self._free)} free")
            for p in shared:
                self._refs[p] += 1
            self._tables[seq_id] = shared + [
                self._pop_free() for _ in range(need - len(shared))]
            self._lens[seq_id] = n_tokens
            return list(self._tables[seq_id])

    def _pop_free(self):
        # caller holds self._lock
        p = self._free.pop()
        self._free_set.discard(p)
        self._refs[p] = 1
        return p

    def extend(self, seq_id, n_tokens=1):
        """Grow a sequence by ``n_tokens`` (decode), allocating pages as
        page boundaries are crossed. Returns the previous length (the
        write offset of the first new token)."""
        with self._lock:
            table, ln = self._tables[seq_id], self._lens[seq_id]
            new_len = ln + n_tokens
            need = max(1, math.ceil(new_len / self.page_size))
            if need > self.max_pages_per_seq:
                raise ValueError(
                    f"sequence {seq_id} exceeds max_pages_per_seq")
            while len(table) < need:
                if not self._free:
                    raise MemoryError("paged cache exhausted on extend")
                table.append(self._pop_free())
            self._lens[seq_id] = new_len
            return ln

    def rollback(self, seq_id, n_tokens):
        """Shrink a live sequence by its LAST ``n_tokens`` — the
        speculative-decoding rejection path: draft tokens were
        tentatively written past the committed length, verification
        rejected a suffix of them, and the pages that existed only for
        that suffix must return to the pool before the next step.

        The length cursor moves back and table-tail pages wholly past
        the new length drop one reference (``decref`` semantics: a
        page another owner still holds — impossible for natural draft
        tails, but the contract stays refcount-correct — survives for
        them). Rejected K/V left in a *kept* page is invisible: reads
        mask by the rolled-back ``kv_len``, and the next extend()
        overwrites those slots. Returns pages freed to the pool."""
        n_tokens = int(n_tokens)
        if n_tokens <= 0:
            return 0
        with self._lock:
            ln = self._lens[seq_id]
            if n_tokens > ln:
                raise ValueError(
                    f"cannot roll back {n_tokens} tokens of sequence "
                    f"{seq_id} (length {ln})")
            table = self._tables[seq_id]
            new_len = ln - n_tokens
            need = max(1, math.ceil(new_len / self.page_size))
            freed = 0
            while len(table) > need:
                p = table.pop()
                if p in self._free_set or p not in self._refs:
                    self.double_free_count += 1
                    self._m_double_free.inc()
                    warnings.warn(
                        f"rollback of sequence {seq_id} found page {p} "
                        f"already free; skipping", RuntimeWarning,
                        stacklevel=2)
                    continue
                if self._decref_locked(p):
                    freed += 1
            self._lens[seq_id] = new_len
            return freed

    def release(self, seq_id):
        """Drop a finished sequence's references; pages whose LAST
        reference this was return to the free list (shared prefix pages
        a cache or another sequence still holds stay allocated).

        Idempotent: releasing an unknown / already-released sequence —
        or a table entry that somehow already sits in the free list —
        is a no-op counted by ``double_free_count`` (and the
        ``kv_page_double_free_total`` metric) with a
        :class:`RuntimeWarning`, so a cancellation racing a natural
        completion can never corrupt the free list by double-inserting
        page ids."""
        with self._lock:
            table = self._tables.pop(seq_id, None)
            if table is None:
                self.double_free_count += 1
                self._m_double_free.inc()
                warnings.warn(
                    f"release of unknown or already-released sequence "
                    f"{seq_id} ignored", RuntimeWarning, stacklevel=2)
                return
            self._lens.pop(seq_id, None)
            for p in table:
                if p in self._free_set or p not in self._refs:
                    self.double_free_count += 1
                    self._m_double_free.inc()
                    warnings.warn(
                        f"page {p} of sequence {seq_id} already free; "
                        f"skipping double insert", RuntimeWarning,
                        stacklevel=2)
                    continue
                self._decref_locked(p)

    def _decref_locked(self, p):
        # caller holds self._lock and proved p is allocated
        self._refs[p] -= 1
        if self._refs[p] <= 0:
            del self._refs[p]
            self._free.append(p)
            self._free_set.add(p)
            return True
        return False

    def incref(self, page):
        """Take an extra reference on an allocated page (a prefix cache
        pinning a freshly prefilled page)."""
        with self._lock:
            if page in self._free_set or page not in self._refs:
                raise ValueError(f"cannot incref free page {page}")
            self._refs[page] += 1

    def decref(self, page):
        """Drop one reference; frees the page at zero. Returns True if
        the page went back to the free list. Decref of an already-free
        page is the same counted no-op as a double release."""
        with self._lock:
            if page in self._free_set or page not in self._refs:
                self.double_free_count += 1
                self._m_double_free.inc()
                warnings.warn(
                    f"decref of free page {page} ignored",
                    RuntimeWarning, stacklevel=2)
                return False
            return self._decref_locked(p=page)

    def page_ref(self, page):
        """Current refcount of a page (0 = free)."""
        with self._lock:
            return self._refs.get(page, 0)

    def export_table(self, seq_id):
        """Host-tier export snapshot: ``(pages, n_tokens)`` of a live
        sequence, copied under the allocator lock. The snapshot is only
        as stable as the caller's own serialization — the serving
        engine exports while holding its engine lock, so no extend /
        release can race the D2H copy that follows. Raises
        :class:`KeyError` for unknown sequences."""
        with self._lock:
            if seq_id not in self._tables:
                raise KeyError(seq_id)
            return list(self._tables[seq_id]), self._lens[seq_id]

    def import_table(self, seq_id, n_tokens):
        """Admit a RESUMED sequence against freshly drawn, exclusively
        owned pages — never prefix-shared ones: the H2D restore scatter
        overwrites every slot of every page, and a shared page must
        stay immutable for its other owners (the restore path does not
        go through :meth:`ensure_writable`). Same refcount/double-free
        contract as :meth:`admit`: each page starts at refcount 1 and
        :meth:`release` is the idempotent inverse."""
        return self.admit(seq_id, n_tokens)

    def take_pages(self, n):
        """Draw ``n`` standalone pages, refcount 1 each, owned by the
        caller (the host-tier prefix-promotion path; hand them to a
        prefix cache or give them back with :meth:`decref`). Raises
        :class:`MemoryError` when the free list is short — atomically:
        either all ``n`` pages are drawn or none are."""
        with self._lock:
            if n > len(self._free):
                raise MemoryError(
                    f"paged cache exhausted: need {n} standalone "
                    f"pages, {len(self._free)} free")
            return [self._pop_free() for _ in range(n)]

    def ensure_writable(self, seq_id, pos):
        """Copy-on-write guard for a K/V write at token position
        ``pos``: if the page holding ``pos`` is shared (refcount > 1),
        allocate a private replacement, swap it into this sequence's
        block table and drop one reference on the original. Returns
        ``(old_page, new_page)`` when a copy is needed — the caller
        must copy the page's device content old -> new before writing —
        or ``None`` when the page is already exclusively owned.

        With page-aligned prefix caching this never fires in the
        natural flow (a sequence's own writes always land past its
        shared prefix, in pages it owns), but the contract keeps a
        shared page immutable no matter what the caller does."""
        with self._lock:
            table = self._tables[seq_id]
            idx = pos // self.page_size
            p = table[idx]
            if self._refs.get(p, 0) <= 1:
                return None
            if not self._free:
                raise MemoryError(
                    "paged cache exhausted on copy-on-write")
            new = self._pop_free()
            table[idx] = new
            self._refs[p] -= 1
            self.cow_count += 1
            self._m_cow.inc()
            return (p, new)

    def context_len(self, seq_id):
        return self._lens[seq_id]

    def page_positions(self, seq_id, start, count):
        """(page_ids, offsets) numpy arrays for token positions
        ``start .. start+count`` of a sequence — the scatter target for a
        K/V write."""
        table = self._tables[seq_id]
        pos = np.arange(start, start + count)
        page_ids = np.asarray([table[p] for p in pos // self.page_size])
        return page_ids, pos % self.page_size

    def batch_views(self, seq_ids, width=None, fill_page=0):
        """(block_tables [B, width], context_lens [B]) for a batch — the
        kernel inputs. Unused tail entries point at ``fill_page``."""
        width = width or max(len(self._tables[s]) for s in seq_ids)
        tables = np.full((len(seq_ids), width), fill_page, np.int32)
        lens = np.zeros((len(seq_ids),), np.int32)
        for i, s in enumerate(seq_ids):
            t = self._tables[s]
            tables[i, :len(t)] = t
            lens[i] = self._lens[s]
        return jnp.asarray(tables), jnp.asarray(lens)


class PagedKVCache(PageAllocator):
    """One layer's K/V pool bundled with its own allocator."""

    def __init__(self, num_pages, page_size, num_kv_heads, head_dim,
                 dtype=jnp.bfloat16, max_pages_per_seq=None):
        super().__init__(num_pages, page_size, max_pages_per_seq)
        # head-major [P, Hk, page, D]: the layout the Pallas kernel tiles
        shape = (num_pages, num_kv_heads, page_size, head_dim)
        self.k_pages = jnp.zeros(shape, dtype)
        self.v_pages = jnp.zeros(shape, dtype)

    def write(self, seq_id, k, v, start=None):
        """Scatter ``[S, Hk, D]`` new K/V at position ``start`` (default:
        end of already-written context minus the new tokens — i.e. the
        tokens just accounted by admit/extend)."""
        k = jnp.asarray(getattr(k, "_data", k), self.k_pages.dtype)
        v = jnp.asarray(getattr(v, "_data", v), self.v_pages.dtype)
        s = k.shape[0]
        if start is None:
            start = self._lens[seq_id] - s
        page_ids, offs = self.page_positions(seq_id, start, s)
        # k is [S, Hk, D]; target (page_ids[s], h, offs[s], :) — the
        # [S,1]/[1,Hk] index arrays broadcast to [S, Hk] scatter sites
        hidx = np.arange(self.k_pages.shape[1])[None, :]
        self.k_pages = self.k_pages.at[
            page_ids[:, None], hidx, offs[:, None]].set(k)
        self.v_pages = self.v_pages.at[
            page_ids[:, None], hidx, offs[:, None]].set(v)

    def attend(self, seq_ids, q, scale=None, use_pallas=True):
        """Decode-step attention for ``q [B, H, D]`` over the batch's
        pages; rows of ``q`` correspond to ``seq_ids``."""
        tables, lens = self.batch_views(seq_ids)
        fn = paged_attention if use_pallas else paged_attention_xla
        return fn(q, self.k_pages, self.v_pages, tables, lens, scale=scale)
