"""Subprocess serving replica: ``replica_main()``.

The PR-6 :class:`~paddle_tpu.inference.cluster.EngineReplica` worker
loop was designed to map 1:1 onto a process main loop — this module IS
that process. ``python -m paddle_tpu.inference.replica_worker`` (the
supervisor's spawn command) reads its configuration from the
environment, builds the engine from a JSON spec, and runs the exact
same ``EngineReplica`` the in-process cluster uses, with three
process-native twists:

- **Crash containment.** The engine, its compiled programs, and every
  dispatch live in THIS process. A segfault, OOM, or wedged dispatch
  takes down one replica; the supervisor sees the exit code (or the
  heartbeat stamp aging out of the FileStore) and spawns a
  replacement. A worker whose loop dies uncleanly exits ``17`` without
  deregistering — a crashed host never says goodbye; membership TTL is
  the detector.
- **Warm restart.** The engine construction enables JAX's persistent
  compilation cache and pre-warms the shape buckets recorded by
  previous engines of identical geometry
  (``PADDLE_TPU_SERVING_PREWARM=1`` is the supervisor's default for
  workers), then runs a one-token self-probe — so registration in
  membership means "compiled and serving", and the reported
  ``restart_ttft`` (process start to first emitted token) is seconds,
  not the ~19 s compile bill (ROADMAP item 5).
- **Transport.** Requests arrive over the
  :class:`~paddle_tpu.distributed.rpc.RpcEndpoint` dynamic mesh: the
  router hosts the master TCPStore; this worker joins as
  ``PADDLE_TPU_REPLICA_ID`` with no barrier and serves the module-level
  ``_worker_*`` handlers below (pickled by reference, so both sides
  import this module). Typed errors — :class:`AdmissionError` with
  ``retry_after``, :class:`DeadlineExceeded` with its carried fields —
  travel pickled in the rpc error reply, intact.

Environment contract (set by :class:`SubprocessReplica`):

- ``PADDLE_TPU_REPLICA_ID`` — replica name (rpc address + membership id)
- ``PADDLE_TPU_REPLICA_STORE`` — FileStore membership directory
- ``PADDLE_TPU_REPLICA_STORE_ADDR`` — ``host:port`` of a
  :class:`~paddle_tpu.distributed.net_store.LeaseStoreServer`;
  replaces ``PADDLE_TPU_REPLICA_STORE`` in TCP-only deployments
  (membership AND the rpc mailbox ride the lease server — no shared
  filesystem is touched)
- ``PADDLE_TPU_REPLICA_RPC`` — ``host:port`` of the router's TCPStore
- ``PADDLE_TPU_REPLICA_SPEC`` — JSON engine spec (below)
- ``PADDLE_TPU_REPLICA_TTL`` — membership TTL seconds (optional)
- ``PADDLE_TPU_REPLICA_T0`` — supervisor's spawn wall-clock stamp; the
  base of the reported ``restart_ttft``
- ``PADDLE_TPU_REPLICA_BACKLOG`` / ``PADDLE_TPU_REPLICA_BURST`` —
  worker-loop knobs (optional)
- ``PADDLE_TPU_REPLICA_HEALTH_PORT`` — serve ``/metrics`` +
  ``/healthz`` + ``/readyz`` on this port (optional; the actual port is
  written to ``<store>/.http.<id>`` so ``port=0`` works)
- ``PADDLE_TPU_REPLICA_LOG_DIR`` — the cluster log dir (optional).
  When set, the worker (a) installs the crash flight recorder with its
  bundles under ``<log_dir>/<id>/postmortem/`` (the supervisor's death
  path harvests them), and (b) flushes its span ring to a bounded
  trace shard ``<log_dir>/trace_shards/<id>.trace.json`` every
  ``PADDLE_TPU_TRACE_FLUSH`` seconds (default 0.5) for the cluster's
  merged-trace collector. Both are no-ops under
  ``PADDLE_TPU_METRICS=0``.

Spec format::

    {"model": {"kind": "tiny_llama", "seed": 0, "config": {...}},
     "engine": {"max_batch": 8, "page_size": 16, ...}}

``kind`` is ``tiny_llama`` / ``llama`` (config kwargs into
:func:`tiny_llama_config` / :class:`LlamaConfig`), or ``{"model":
{"factory": "my_pkg.serving:build_model"}}`` imports a zero-arg model
builder. Fault plans (``PADDLE_TPU_FAULTS``) ride the inherited
environment, so ``replica.dead`` / ``replica.heartbeat`` rules fire
inside the worker process exactly as they do in-process.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

__all__ = ["replica_main"]

#: the live worker state in a replica process (None in the router)
_WORKER = None


#: seconds a TERMINAL request waits to be polled before the worker
#: forgets it — a submit whose rpc reply was lost leaves an entry the
#: router never learned the id of (it re-routed on timeout), and those
#: must not accumulate for the life of the process
_UNCLAIMED_TTL = 60.0


class _WorkerState:
    def __init__(self, replica_id, rep):
        self.replica_id = replica_id
        self.rep = rep
        self.restart_ttft = None
        self._reqs = {}                   # req_id -> ClusterRequest
        self._done_at = {}                # req_id -> monotonic stamp
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self.stop = threading.Event()

    def _reap_unclaimed(self, polled_ids):
        """Forget terminal entries nobody has polled for
        ``_UNCLAIMED_TTL`` seconds (caller holds the lock). Entries the
        router knows are deleted on first poll; what lands here is the
        lost-submit-reply orphan the router already failed over."""
        now = time.monotonic()
        for req_id, creq in list(self._reqs.items()):
            if req_id in polled_ids or not creq.done:
                continue
            t0 = self._done_at.setdefault(req_id, now)
            if now - t0 > _UNCLAIMED_TTL:
                del self._reqs[req_id]
                self._done_at.pop(req_id, None)


def _require():
    if _WORKER is None:
        raise RuntimeError(
            "not a replica worker process (replica_main() not running)")
    return _WORKER


# ---------------------------------------------------------------------
# rpc handlers — module-level so they pickle by reference; they run on
# the worker's rpc dispatcher thread
# ---------------------------------------------------------------------
def _worker_submit(spec):
    """Admit one request spec into the replica's backlog. Returns a
    request id the router polls; raises a typed (picklable)
    AdmissionError when the replica is draining or its backlog is
    full — the rpc error reply carries it back intact. A spec stamped
    with an ``epoch`` other than this incarnation's membership epoch
    is rejected with a typed StaleEpochError: a submission addressed
    to the replacement must never be served by a partitioned old
    incarnation consuming the same name-keyed mailbox (and vice
    versa). Retried submits (at-least-once rpc) are deduped by the
    dispatcher's reply cache, so admission stays exactly-once."""
    from .cluster import ClusterRequest
    from .sampling import SamplingParams

    w = _require()
    creq = ClusterRequest(
        spec["prompt_ids"], spec["max_new_tokens"],
        spec.get("eos_token_id"), spec.get("deadline"),
        spec.get("token_budget"), spec.get("priority", 0),
        spec.get("retry_budget", 1),
        sampling=SamplingParams.from_spec(spec.get("sampling")),
        stop=spec.get("stop") or ())
    creq._t_submit = time.perf_counter()
    w.rep.submit(creq, epoch=spec.get("epoch"))
    req_id = f"{w.replica_id}:{next(w._seq)}"
    with w._lock:
        w._reqs[req_id] = creq
    return req_id


def _worker_poll(req_ids):
    """Batched status poll: per-request state (terminal entries are
    handed over once, then forgotten) plus the replica-level snapshot
    the router routes on (ready, load, restart TTFT, compile-cache
    hit/miss)."""
    from ..observability import compile_watch as _cw

    w = _require()
    reqs = {}
    with w._lock:
        for req_id in req_ids:
            c = w._reqs.get(req_id)
            if c is None:
                reqs[req_id] = None       # unknown: router fails over
            elif c.done:
                reqs[req_id] = {"done": True, "status": c.status,
                                "output_ids": list(c.output_ids),
                                "error": c.error}
                del w._reqs[req_id]
                w._done_at.pop(req_id, None)
            else:
                reqs[req_id] = {"done": False, "status": c.status,
                                "output_ids": list(c.output_ids),
                                "error": None}
        w._reap_unclaimed(set(req_ids))
    # ready only once the self-probe finished: "compiled AND proven
    # serving", not merely "registered" — the router must never route
    # to a replica whose restart_ttft (and first real dispatch) is
    # still in flight
    return {"ready": w.rep.ready() and w.restart_ttft is not None,
            "load": w.rep.load(), "restart_ttft": w.restart_ttft,
            "epoch": w.rep.epoch,
            "cache": _cw.persistent_cache_stats(), "requests": reqs}


def _worker_cancel(req_id):
    w = _require()
    with w._lock:
        creq = w._reqs.get(req_id)
    if creq is None:
        return False
    req = creq.cancel()
    if req is not None and w.rep.engine is not None:
        w.rep.engine.cancel(req)
    return True


def _worker_begin_drain():
    w = _require()
    w.rep.begin_drain()
    return True


def _worker_take_backlog():
    """Hand queued-but-unadmitted requests back to the router (their
    ids); the router re-routes its own handles to peer replicas."""
    w = _require()
    backlog = w.rep.take_backlog()
    taken = []
    with w._lock:
        ids = {c: i for i, c in w._reqs.items()}
        for c in backlog:
            req_id = ids.get(c)
            if req_id is not None:
                del w._reqs[req_id]
                taken.append(req_id)
    return taken


def _worker_drain(grace=30.0):
    """Stop the worker loop and drain the engine (PR-4 semantics):
    in-flight requests finish or expire typed inside the grace."""
    w = _require()
    w.rep.stop_worker()
    return w.rep.drain(grace)


def _worker_scrape():
    """This replica's full registry snapshot (the one-pane metrics
    feed): the supervisor's ``ServingCluster.scrape()`` pulls these
    over the existing rpc path and merges them under a ``replica``
    label. Returns an empty snapshot under ``PADDLE_TPU_METRICS=0``."""
    from ..observability import metrics as _om
    from ..observability import perf as _perf
    from ..observability.export import json_snapshot

    w = _require()
    _perf.ensure_build_info()   # identity labels ride every scrape
    snapshot = json_snapshot() if _om.enabled() else []
    return {"replica": w.replica_id, "pid": os.getpid(),
            "snapshot": snapshot}


def _worker_capture_profile(seconds=1.0):
    """One on-demand profiler window in this replica process (the
    fan-out target of ``ServingCluster.capture_profile()``): runs on
    the rpc dispatcher thread while the engine keeps serving, returns
    this process's span shard + device-trace events for the
    supervisor's merge. Empty-events shard under
    ``PADDLE_TPU_METRICS=0``."""
    from ..observability import perf as _perf

    w = _require()
    return _perf.capture_local(seconds, worker_name=w.replica_id)


def _worker_exit():
    """Clean shutdown: the main loop deregisters from membership and
    exits 0 (the reply is published before the dispatcher yields)."""
    w = _require()
    w.stop.set()
    return True


# ---------------------------------------------------------------------
# process entrypoint
# ---------------------------------------------------------------------
def _build_model(model_spec):
    import paddle_tpu as paddle
    from ..models import LlamaForCausalLM, tiny_llama_config
    from ..models.llama import LlamaConfig

    factory = model_spec.get("factory")
    if factory:
        mod, _, attr = factory.partition(":")
        import importlib

        fn = getattr(importlib.import_module(mod), attr)
        return fn()
    seed = model_spec.get("seed")
    if seed is not None:
        paddle.seed(int(seed))
    kind = model_spec.get("kind", "tiny_llama")
    cfg_kw = model_spec.get("config", {})
    if kind == "tiny_llama":
        cfg = tiny_llama_config(**cfg_kw)
    elif kind == "llama":
        cfg = LlamaConfig(**cfg_kw)
    else:
        raise ValueError(f"unknown model kind {kind!r}")
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def replica_main():
    """Run one subprocess serving replica until a clean ``_worker_exit``
    (exit 0, deregistered) or an unclean worker-loop death (exit 17, no
    goodbye — membership TTL detects it)."""
    global _WORKER

    t0 = float(os.environ.get("PADDLE_TPU_REPLICA_T0") or time.time())
    replica_id = os.environ["PADDLE_TPU_REPLICA_ID"]
    store_path = os.environ.get("PADDLE_TPU_REPLICA_STORE")
    store_addr = os.environ.get("PADDLE_TPU_REPLICA_STORE_ADDR")
    if store_path is None and store_addr is None:
        raise RuntimeError(
            "replica worker needs PADDLE_TPU_REPLICA_STORE (FileStore "
            "dir) or PADDLE_TPU_REPLICA_STORE_ADDR (LeaseStore "
            "host:port)")
    rpc_addr = os.environ["PADDLE_TPU_REPLICA_RPC"]
    spec = json.loads(os.environ["PADDLE_TPU_REPLICA_SPEC"])
    ttl_env = os.environ.get("PADDLE_TPU_REPLICA_TTL")
    ttl = float(ttl_env) if ttl_env else None
    backlog = os.environ.get("PADDLE_TPU_REPLICA_BACKLOG")
    burst = os.environ.get("PADDLE_TPU_REPLICA_BURST")

    from ..distributed.rpc import RpcEndpoint
    from ..distributed.watchdog import FileStore
    from ..observability import flight_recorder as _fr
    from ..observability import tracing as _tracing
    from .cluster import ClusterRequest, EngineReplica
    from .serving import LlamaServingEngine

    log_dir = os.environ.get("PADDLE_TPU_REPLICA_LOG_DIR")
    if log_dir:
        # install BEFORE the engine builds: a crash mid-compile leaves
        # a postmortem bundle too. Per-replica subdir, so the
        # supervisor's death path knows exactly whose bundle it found.
        _fr.install(log_dir=os.path.join(log_dir, replica_id))

    model = _build_model(spec.get("model", {}))
    engine_kw = dict(spec.get("engine", {}))

    def factory():
        # prewarm rides the engine default (PADDLE_TPU_SERVING_PREWARM,
        # which the supervisor sets to 1 for workers): registry-recorded
        # mixed-program shapes / decode-scan ticks compile here, against
        # the persistent cache — BEFORE this replica enters membership
        return LlamaServingEngine(model, **engine_kw)

    if store_addr is not None:
        # TCP-only control plane: membership leases live on the
        # LeaseStoreServer — nothing in this process touches a shared
        # filesystem (replica and router may be on different hosts)
        from ..distributed.net_store import LeaseStore

        store = LeaseStore(store_addr, ttl=ttl)
    else:
        store = FileStore(store_path, ttl=ttl)
    rep = EngineReplica(
        replica_id, factory, store=store, ttl=ttl,
        max_backlog=int(backlog) if backlog else None,
        burst=int(burst) if burst else None,
        spawn_fault=False)      # the supervisor's Popen was the spawn
    state = _WorkerState(replica_id, rep)
    _WORKER = state

    # rpc FIRST, membership second: the dispatcher resumes this name's
    # mailbox at the store's current seq counter, so every seq claimed
    # after this point IS served — and because a caller only trusts a
    # replica it has seen in membership (or polled ready), nothing it
    # sends to a registered replica can fall into the resume gap.
    # Pre-engine polls simply report ready=False while compiles run.
    if store_addr is not None:
        # mailbox on the SAME lease server as membership (its own
        # session): outage tolerance + post-restart seq resync come
        # from the LeaseStore client, not the native TCPStore
        endpoint = RpcEndpoint(replica_id, store=store.clone())
    else:
        endpoint_host, _, endpoint_port = rpc_addr.rpartition(":")
        endpoint = RpcEndpoint(replica_id, host=endpoint_host,
                               port=int(endpoint_port))

    # start() builds the engine (compiles included), registers in
    # membership, then starts the worker loop + heartbeat sidecar —
    # registration IS the readiness signal the supervisor waits on
    rep.start()

    # monotonic<->epoch clock-offset handshake AT registration: the
    # collector needs this process's span-clock base to align its
    # shard with the other processes' timelines (dot-prefixed file:
    # membership hosts() scans ignore it). No file under METRICS=0,
    # and no file at all in TCP-only mode (no shared dir to put it in)
    if store_path is not None:
        _tracing.record_clock_handshake(store_path, replica_id)

    # restart -> serving self-probe: one trivial request through the
    # real admission + prefill + decode path proves every serving
    # program compiles and works — so a COLD worker pays exactly the
    # program set a warm worker pre-warms from the registry, and the
    # stamped restart_ttft numbers (what the warm-restart bench/e2e
    # compare) measure cache hit vs full compile, not differing work
    probe = ClusterRequest([1], max_new_tokens=2)
    probe._t_submit = time.perf_counter()
    rep.submit(probe)
    probe.wait(timeout=600)
    state.restart_ttft = time.time() - t0

    srv = None
    health_port = os.environ.get("PADDLE_TPU_REPLICA_HEALTH_PORT")
    if health_port:
        from ..observability.export import start_http_server

        def _health_info():
            # /healthz names the membership epoch + heartbeat age so
            # an operator can spot a fenced-out stale incarnation from
            # the probe alone (ISSUE 11 satellite)
            try:
                hb_age = store.heartbeat_age(replica_id)
            except OSError:
                hb_age = None   # store outage: age unknown — the
                # probe itself must keep answering
            return {"replica_id": replica_id, "epoch": rep.epoch,
                    "fenced": rep._fenced,
                    "membership_heartbeat_age_seconds": hb_age}

        srv = start_http_server(port=int(health_port), ready=rep.ready,
                                health_info=_health_info)
        # port=0 picks a free port; publish it next to the membership
        # stamps (dot-prefixed: hosts() ignores it). TCP-only mode has
        # no shared dir — publish through the lease store's KV instead
        if store_path is not None:
            with open(os.path.join(store_path, f".http.{replica_id}"),
                      "w") as f:
                f.write(str(srv.port))
        else:
            store.set(f"http/{replica_id}", str(srv.port).encode())

    flush_every = float(os.environ.get("PADDLE_TPU_TRACE_FLUSH")
                        or 0.5)
    last_flush = 0.0

    def _flush_shard():
        if log_dir:
            try:
                _tracing.write_span_shard(log_dir, replica_id)
            except Exception:
                pass    # telemetry must never kill a serving worker

    try:
        while not state.stop.wait(0.1):
            now = time.monotonic()
            if now - last_flush >= flush_every:
                last_flush = now
                _flush_shard()
            if rep._dead:
                # the worker loop DIED (fault injection, a crash the
                # fatal-guard re-raised) — as opposed to a deliberate
                # stop_worker() during a drain, which keeps this
                # process serving rpc until _worker_exit. Exit unclean
                # WITHOUT deregistering: a crashed host never says
                # goodbye; membership TTL is the detector. The final
                # shard flush below still happens: the dying worker's
                # spans are exactly the ones worth merging.
                _flush_shard()
                os._exit(17)
            if rep._fenced:
                # fenced out by a replacement incarnation (stale-epoch
                # heartbeat rejection): stop serving immediately and —
                # critically — do NOT deregister: the stamp belongs to
                # the replacement now, and removing it would knock the
                # HEALTHY successor out of membership
                os._exit(19)
    finally:
        # clean exit: give the dispatcher a beat to flush the
        # _worker_exit reply, then say goodbye properly
        time.sleep(0.3)
        _flush_shard()
        rep.stop()
        endpoint.stop()
        if srv is not None:
            srv.stop()
    return 0


if __name__ == "__main__":
    # run the CANONICAL module's replica_main, not __main__'s copy:
    # ``python -m`` loads this file as __main__, but the rpc dispatcher
    # unpickles handlers against ``paddle_tpu.inference.replica_worker``
    # — two module objects, two _WORKER globals, and the handlers would
    # see None forever
    from paddle_tpu.inference.replica_worker import replica_main as _rm

    raise SystemExit(_rm() or 0)
