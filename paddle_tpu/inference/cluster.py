"""Multi-replica serving tier: load-aware routing, membership, rolling
restart.

One :class:`~paddle_tpu.inference.serving.LlamaServingEngine` is a
single continuous batch on a single chip; this module is the layer that
makes N of them look like one service (ROADMAP item 2 — the
millions-of-users story, cf. the Gemma-on-TPU serving comparison in
PAPERS.md):

- :class:`EngineReplica` — one engine driven by its own worker thread,
  registered in the shared :class:`~paddle_tpu.distributed.watchdog
  .FileStore` membership store with TTL heartbeats (the elastic
  launcher's liveness mechanism, reused for serving). A replica that
  dies — fault-injected via the ``replica.dead`` point, or a simulated
  SIGKILL via :meth:`EngineReplica.kill` — simply stops heartbeating
  and ages out of membership.
- :class:`ClusterRequest` — the router-level request handle. It
  survives its replica: if the replica dies before the request
  finishes, the router re-submits it elsewhere (bounded by
  ``failover_budget``), and a cluster-level ``deadline`` keeps ticking
  across attempts — a request always ends terminal (completed or a
  typed error), never lost.
- :class:`ServingCluster` — the routing frontend. ``submit()`` picks
  the least-loaded ready replica from the engines' own queue-depth /
  KV-page-utilization gauges; when every replica sheds, the typed
  :class:`~paddle_tpu.inference.serving.AdmissionError` propagates with
  the smallest ``retry_after`` hint (backpressure, not a drop). A
  monitor thread watches membership through an
  :class:`~paddle_tpu.distributed.watchdog.ElasticManager`, fails over
  the requests of dead replicas and (``auto_replace=True``) rebuilds
  them. :meth:`ServingCluster.rolling_restart` cycles replicas through
  ``drain()`` one at a time — the router stops routing to a draining
  replica, its backlog is re-routed, in-flight requests finish or
  expire typed inside the grace window, and a fresh engine takes over.

Each replica's engine keeps its own shared-prefix KV cache, so a hot
system prompt is prefilled once per replica. In tests replicas are
in-process engines; a subprocess deployment drives the same surface
(the worker loop maps 1:1 onto a process main loop with the store on a
shared filesystem).

Fault points: ``router.route`` fires per routing decision and
``replica.dead`` fires per worker-loop tick, so a ``PADDLE_TPU_FAULTS``
plan can inject routing errors or kill replica N at tick K
deterministically in CI. Network rules at ``store.heartbeat`` /
``rpc.send`` / ``rpc.reply`` drop, delay, duplicate, or partition the
control-plane messages themselves.

Partition tolerance (ISSUE 11): every replica incarnation registers
under a fresh monotonic EPOCH from the store; heartbeats and request
submissions stamped with a fenced-out epoch raise a typed
:class:`~paddle_tpu.distributed.watchdog.StaleEpochError`, so a
partitioned-but-alive old incarnation can never race its supervisor-
spawned replacement — and a request that completes on both emits
exactly once (first terminal report wins, token-exact;
``cluster_duplicate_completions_suppressed_total``).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import random
import tempfile
import threading
import time

import numpy as np

from ..distributed.net_store import LeaseStore, StoreUnavailableError
from ..distributed.watchdog import (ElasticManager, FileStore,
                                    StaleEpochError)
from ..observability import metrics as _om
from ..observability import slo as _slo
from ..observability import tracing as _tracing
from ..observability.export import (aggregate_snapshot, json_snapshot,
                                    merge_snapshots)
from ..observability.trace import span as _span
from ..testing import faults as _faults
from .sampling import SamplingParams
from .serving import (AdmissionError, DeadlineExceeded,
                      LlamaServingEngine, Request)

__all__ = ["ClusterRequest", "EngineReplica", "SubprocessReplica",
           "ServingCluster", "ReplicaLostError", "StaleEpochError"]


def _m_stale():
    return _om.counter(
        "cluster_stale_epoch_rejections_total",
        "membership/submission actions rejected because their epoch "
        "was fenced out by a newer incarnation")


def _m_dup_completions():
    return _om.counter(
        "cluster_duplicate_completions_suppressed_total",
        "terminal reports for an already-finished cluster request "
        "(split-brain / failover double completion) suppressed — the "
        "first terminal state won, token-exact")


class ReplicaLostError(RuntimeError):
    """Terminal cluster-level failure: the request's replica died and
    its failover budget is spent. Carries enough to alert on."""

    def __init__(self, msg, replica_id=None, failovers=0):
        super().__init__(msg)
        self.replica_id = replica_id
        self.failovers = failovers

    def __reduce__(self):
        # survives the rpc error-reply round trip with its typed fields
        # (default exception pickling keeps __dict__, but rebuilding
        # from fields is the explicit contract the tests pin down)
        return (type(self), (self.args[0] if self.args else "",
                             self.replica_id, self.failovers))


def _router_metrics():
    return {
        "routed": _om.counter(
            "router_requests_routed_total",
            "requests routed to a replica", labelnames=("replica",)),
        "backpressure": _om.counter(
            "router_backpressure_total",
            "submissions rejected because every replica shed"),
        "failover": _om.counter(
            "router_failovers_total",
            "requests re-submitted after their replica died"),
        "lost": _om.counter(
            "router_requests_lost_total",
            "requests that exhausted their failover budget"),
        "replaced": _om.counter(
            "router_replicas_replaced_total",
            "dead replicas rebuilt by the monitor"),
        "restarts": _om.counter(
            "router_rolling_restarts_total",
            "replicas cycled through a rolling restart"),
        "ready": _om.gauge(
            "router_replicas_ready",
            "replicas currently routable (alive, registered, not "
            "draining)"),
        "quarantined": _om.counter(
            "cluster_replica_quarantined_total",
            "replicas quarantined by the crash-loop circuit breaker"),
        "quarantined_now": _om.gauge(
            "cluster_replicas_quarantined",
            "replicas currently held out by the circuit breaker"),
        "affinity_hits": _om.counter(
            "serving_prefix_affinity_hits_total",
            "requests routed to a replica advertising their prompt's "
            "prefix in its hot-prefix set"),
        "scrape_failures": _om.counter(
            "cluster_scrape_failures_total",
            "per-replica metric-scrape rpcs that failed",
            labelnames=("replica",)),
    }


class ClusterRequest:
    """One generation request at the routing tier.

    Holds the *intent* (prompt, budgets, priority); each submission to
    a replica materializes a fresh engine-level
    :class:`~paddle_tpu.inference.serving.Request` so a failover
    restarts cleanly. ``deadline`` is a cluster-level wall-clock TTL
    measured from the first ``submit()`` — it keeps ticking across
    failovers, so a request bouncing between dying replicas still ends
    in a typed :class:`DeadlineExceeded` rather than living forever.
    """

    def __init__(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
                 deadline=None, token_budget=None, priority=0,
                 retry_budget=1, failover_budget=3, sampling=None,
                 stop=(), on_token=None):
        self.prompt_ids = np.asarray(prompt_ids, np.int64).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.deadline = None if deadline is None else float(deadline)
        self.token_budget = token_budget
        self.priority = int(priority)
        self.retry_budget = int(retry_budget)
        self.failover_budget = int(failover_budget)
        if sampling is not None and sampling.seed is None \
                and not sampling.is_greedy:
            # pin the auto-seed at the CLUSTER request level: engine
            # auto-seeds are per-attempt, so a failover's fresh engine
            # Request would otherwise resample a DIFFERENT sequence —
            # a streaming client could receive a spliced output the
            # stream's shrink check cannot detect
            sampling = SamplingParams(
                temperature=sampling.temperature,
                top_p=sampling.top_p, top_k=sampling.top_k,
                seed=int.from_bytes(os.urandom(4), "little") % (2**31),
                stop=sampling.stop, logit_bias=sampling.logit_bias,
                constraint=sampling.constraint)
        self.sampling = sampling
        self.stop = tuple(int(t) for t in (stop or ()))
        #: optional streaming hook ``fn(token)`` — fired per appended
        #: token by an IN-PROCESS engine attempt (subprocess replicas
        #: surface partials through :meth:`partial_output` instead)
        self.on_token = on_token
        #: the distributed trace node this request belongs to, captured
        #: from the ambient context at construction (the frontend's
        #: request span, or the rpc.handle span in a subprocess worker)
        #: so replica-side spans can chain to it from other threads
        self._trace = _tracing.current()
        self.failovers = 0
        self.request: Request | None = None   # current engine attempt
        self.replica_id = None
        self.status = "pending"
        self.error = None
        self.output_ids: list[int] = []
        self._partial: list[int] = []   # poller-mirrored live output
        self._t_submit = None
        self._finished = threading.Event()
        self._lock = threading.Lock()
        # constructing the engine request up front validates the args
        # at submit() time, not on a replica's worker thread
        Request(self.prompt_ids, self.max_new_tokens, eos_token_id,
                deadline, token_budget, priority, retry_budget,
                sampling=sampling, stop=self.stop)

    # ------------------------------------------------------------------
    @property
    def done(self):
        return self._finished.is_set()

    def wait(self, timeout=None):
        """Block until terminal; True if it finished in time."""
        return self._finished.wait(timeout)

    def result(self, timeout=None):
        """Output ids, or raises the typed terminal error (or
        :class:`TimeoutError` if still running past ``timeout``)."""
        if not self._finished.wait(timeout):
            raise TimeoutError(
                f"request not finished within {timeout}s "
                f"(status={self.status})")
        if self.error is not None:
            raise self.error
        return self.output_ids

    # -- replica-side hooks --------------------------------------------
    def _remaining_ttl(self, now=None):
        if self.deadline is None:
            return None
        now = time.perf_counter() if now is None else now
        return self.deadline - (now - self._t_submit)

    def _new_attempt(self, replica_id):
        """Engine-level request for one submission attempt, or None if
        the cluster deadline already lapsed (the request is finished
        typed here — never silently dropped)."""
        with self._lock:
            if self._finished.is_set():
                return None
            ttl = self._remaining_ttl()
            if ttl is not None and ttl <= 0:
                self._finish_locked(
                    "deadline_exceeded",
                    DeadlineExceeded(
                        f"cluster deadline of {self.deadline}s lapsed "
                        f"before the request reached a live replica",
                        tokens_emitted=len(self.output_ids),
                        reason="cluster deadline"))
                return None
            r = Request(self.prompt_ids, self.max_new_tokens,
                        self.eos_token_id, ttl, self.token_budget,
                        self.priority, self.retry_budget,
                        sampling=self.sampling, stop=self.stop,
                        on_token=self._attempt_token)
            self.request = r
            self.replica_id = replica_id
            self.status = "live"
            return r

    def _finish_locked(self, status, error):
        self.status = status
        self.error = error
        self._finished.set()

    def _attempt_spec(self, replica_id):
        """JSON-able engine-request spec for one submission attempt to a
        SUBPROCESS replica (deadline already reduced to the remaining
        cluster TTL), or None when the request finished typed first."""
        req = self._new_attempt(replica_id)
        if req is None:
            return None
        return {"prompt_ids": [int(t) for t in self.prompt_ids],
                "max_new_tokens": self.max_new_tokens,
                "eos_token_id": self.eos_token_id,
                "deadline": req.deadline,
                "token_budget": self.token_budget,
                "priority": self.priority,
                "retry_budget": self.retry_budget,
                "sampling": None if self.sampling is None
                else self.sampling.to_spec(),
                "stop": list(self.stop)}

    # -- streaming hooks -----------------------------------------------
    def _attempt_token(self, req, token):
        """Engine-side per-token hook of the CURRENT in-process
        attempt; forwards to the caller's ``on_token``."""
        cb = self.on_token
        if cb is not None:
            try:
                cb(int(token))
            except Exception:
                pass        # streaming hooks must never kill a dispatch

    def _mirror_partial(self, output_ids):
        """Adopt a subprocess replica's non-terminal output snapshot
        (poller thread). Terminal adoption still goes through
        :meth:`_finish_remote` exactly once."""
        with self._lock:
            if not self._finished.is_set():
                self._partial = list(output_ids or [])

    def partial_output(self):
        """Best-effort live output snapshot for streaming: the current
        in-process attempt's tokens, the poller's last mirror for a
        subprocess attempt, or the terminal output once finished. May
        SHRINK across a failover (the replacement attempt restarts
        generation) — streaming frontends treat a shrink as a stream
        error."""
        with self._lock:
            if self._finished.is_set():
                return list(self.output_ids)
            r = self.request
            partial = list(self._partial)
        if r is not None and r.status != "pending" \
                and len(r.output_ids) >= len(partial):
            # in-process live attempt: the engine request IS the truth
            return list(r.output_ids)
        return partial

    def _finish_from(self, req):
        """Adopt an engine request's terminal state. Exactly-once: a
        second terminal report (the request completed on BOTH an
        orphaned incarnation and its failover target) is suppressed —
        the first emission won, token-exact — and counted. Returns
        whether the report was adopted."""
        with self._lock:
            if self._finished.is_set():
                _m_dup_completions().inc()
                return False
            self.output_ids = list(req.output_ids)
            self._finish_locked(req.status, req.error)
            return True

    def _finish_remote(self, status, output_ids, error):
        """Adopt a terminal state reported by a subprocess replica over
        rpc (the error arrives pickled — typed, fields intact). Same
        exactly-once contract as :meth:`_finish_from`."""
        with self._lock:
            if self._finished.is_set():
                _m_dup_completions().inc()
                return False
            self.output_ids = list(output_ids or [])
            self._finish_locked(status, error)
            return True

    def _fail(self, status, error):
        with self._lock:
            if not self._finished.is_set():
                self._finish_locked(status, error)

    def cancel(self):
        """Best-effort cancel: marks the handle terminal and cancels
        the current engine attempt if one is live."""
        with self._lock:
            req = self.request
            if not self._finished.is_set():
                self._finish_locked("cancelled", None)
        return req


class EngineReplica:
    """One serving replica: an engine plus the worker thread that
    drives it (admission from a backlog queue, decode steps, completion
    reaping, membership heartbeats). The worker thread is the ONLY
    thread that touches the engine's dispatch path; the router merely
    appends to the backlog, so replica-internal state never races.

    ``kill()`` simulates a SIGKILL: the worker stops mid-loop without
    draining or deregistering — exactly what a preempted host looks
    like to the membership store (its stamp ages out after ``ttl``).
    """

    def __init__(self, replica_id, engine_factory, store=None,
                 ttl=None, heartbeat_interval=None, max_backlog=None,
                 idle_sleep=0.002, burst=None, spawn_fault=True):
        self.replica_id = str(replica_id)
        # replica_main() passes False: for a subprocess worker the
        # SUPERVISOR's Popen is the spawn — the inherited fault plan
        # must not fire the same serve.spawn rule a second time inside
        # the worker it already allowed to spawn
        self._spawn_fault = bool(spawn_fault)
        self._factory = engine_factory
        self.engine: LlamaServingEngine | None = None
        self.store = store
        self.ttl = ttl
        self._hb_interval = heartbeat_interval or (
            ttl / 3.0 if ttl else 0.5)
        self.max_backlog = max_backlog
        self.idle_sleep = float(idle_sleep)
        self.burst = burst                  # decode chunk per loop tick
        self._backlog: collections.deque[ClusterRequest] = \
            collections.deque()
        self._tracked: dict[Request, ClusterRequest] = {}
        # requests popped from the backlog but not yet admitted: the
        # worker can die (fault injection) mid-admission, and a
        # request in that window must still be found by failover
        self._pending_admit: list[ClusterRequest] = []
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread = None
        self._hb_thread = None
        self._draining = False
        self._dead = False
        self._fenced = False
        self._death_reason = None
        self._last_beat = 0.0
        self._ticks = 0
        self._beats = 0
        self._spawns = 0
        #: membership fencing token of the CURRENT incarnation (bumped
        #: by every start/restart through the store's epoch counter)
        self.epoch = 0
        self._m_dead = _om.counter(
            "replica_deaths_total",
            "replica worker loops that died uncleanly")

    # ------------------------------------------------------------------
    def start(self):
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
        # retire the previous incarnation's threads: each incarnation
        # owns its stop event + epoch (closure args), so a straggler
        # that outlives the bounded join below — a sidecar stuck in a
        # slow/faulted heartbeat — is HARMLESS: its next stamp attempt
        # carries the old epoch and the store fences it out with a
        # typed StaleEpochError instead of resurrecting a ghost. The
        # join is hygiene, not correctness, so it must not block a
        # replacement behind a wedged old thread for long.
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        t = self._hb_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=1.0)
        # deterministic spawn failure for chaos plans: a raise rule at
        # serve.spawn (path = replica id, step = spawn ordinal) fails
        # this start/restart the way a full host or a bad image fails a
        # process spawn — the supervisor's backoff + breaker take over.
        # The ordinal advances even when the fault raises, so a
        # step-keyed rule fails only the attempt it names.
        spawn = self._spawns
        self._spawns += 1
        if self._spawn_fault:
            _faults.fire("serve.spawn", step=spawn,
                         path=self.replica_id)
        with self._lock:
            # fresh per-incarnation stop event: a straggler thread of
            # the old incarnation keeps ITS event (closure arg) and can
            # never be resurrected by this clear
            stop = self._stop = threading.Event()
            self._draining = False
            self._dead = False
            self._fenced = False
            self._death_reason = None
        if self.engine is None:
            self.engine = self._factory()
        if self.max_backlog is None:
            self.max_backlog = self.engine.max_batch * 4
        self._register()
        epoch = self.epoch
        self._thread = threading.Thread(
            target=self._run, args=(stop,), daemon=True,
            name=f"replica-{self.replica_id}")
        self._thread.start()
        if self.store is not None:
            # heartbeats ride a sidecar thread: a worker mid-compile
            # (multi-second XLA trace) must not age out of membership;
            # a DEAD worker stops the sidecar, so death still surfaces
            # as TTL expiry
            self._hb_thread = threading.Thread(
                target=self._hb_loop, args=(stop, epoch), daemon=True,
                name=f"replica-{self.replica_id}-hb")
            self._hb_thread.start()
        return self

    def _register(self):
        if self.store is not None:
            # registration carries a FRESH epoch from the store: the
            # supervisor's kill-and-replace and rolling_restart() both
            # come through here, so every replacement incarnation
            # fences out its predecessor by construction
            self.epoch = self.store.next_epoch(self.replica_id)
            self.store.register(self.replica_id, epoch=self.epoch)
            self._last_beat = time.monotonic()

    def _hb_loop(self, stop, epoch):
        gen = getattr(self.store, "restarts", None)
        seen_gen = gen() if gen is not None else 0
        while not stop.wait(self._hb_interval):
            if self._dead or not self.alive():
                return      # a crashed host never says goodbye
            # chaos hook: a hang/sleep rule at replica.heartbeat (path =
            # replica id, step = beat ordinal) freezes this sidecar so
            # the replica silently ages out of membership — the TTL
            # detection + circuit-breaker path, driven deterministically
            _faults.fire("replica.heartbeat", step=self._beats,
                         path=self.replica_id)
            self._beats += 1
            try:
                self.store.heartbeat(self.replica_id, epoch=epoch)
            except StaleEpochError:
                # fenced out: a replacement incarnation owns this name
                # now. If WE are still the current incarnation (an
                # external same-named replica replaced us), stop
                # serving; an old straggler sidecar just exits.
                if self.epoch == epoch:
                    self._fenced = True
                return
            except StoreUnavailableError:
                continue    # store outage, not OUR death: keep
                # beating — the client reconnects by itself and the
                # router's outage credit suppresses the age-out
            except OSError:
                pass
            if gen is not None and gen() != seen_gen:
                # the store came back from a RESTART: its leases and
                # epoch counters are gone, so re-register under a
                # FRESH epoch (the server's adopt-max fence heals at
                # it; _worker_poll mirrors the bump to the router).
                # Only the current incarnation may — a straggler
                # sidecar minting epochs would fence out its OWN
                # replacement.
                if self.epoch != epoch:
                    seen_gen = gen()    # straggler: nothing to mint
                else:
                    try:
                        epoch = self.epoch = \
                            self.store.next_epoch(self.replica_id)
                        self.store.register(self.replica_id,
                                            epoch=epoch)
                        seen_gen = gen()
                    except OSError:
                        pass    # still flapping: next beat retries

    # -- router-facing surface -----------------------------------------
    def alive(self):
        t = self._thread
        return (not self._dead) and t is not None and t.is_alive()

    def is_dead(self, registered):
        """Supervisor's death verdict given this sweep's membership
        observation: a dead worker thread, or a live thread whose stamp
        aged out (frozen heartbeats — as good as dead for routing)."""
        return (not self.alive()) or (not registered
                                      and not self._draining)

    def cancel_attempt(self, creq):
        """Cancel the engine-level attempt of a cluster request."""
        req = creq.request
        if req is not None and self.engine is not None:
            self.engine.cancel(req)

    def ready(self):
        return (self.alive() and not self._draining
                and not self._fenced
                and self.engine is not None and self.engine.is_ready())

    def load(self):
        """Load score from the engine's own admission gauges: live
        batch occupancy + backlog depth (normalized to max_batch) +
        KV-page utilization + pending prefill work. Lower is better.

        The prefill-backlog term (prompt tokens admitted but not yet
        chunk-prefilled, normalized to the engine's per-step
        ``chunk_budget``) makes a replica chewing through a long prompt
        look busier than its live count alone suggests — its decode
        budget is partly spoken for over the next
        ``backlog / chunk_budget`` steps.

        The advertised hot-prefix set (``prefix_keys``: hex chain keys
        of the engine's most recently used cached prefix pages, plus
        the ``page_size`` they were hashed at) piggybacks on this same
        gauge snapshot so the router's prefix-affinity scoring costs no
        extra rpc — a subprocess replica's poll reply carries it the
        same way."""
        e = self.engine
        with self._lock:
            backlog = len(self._backlog)
        if e is None:
            return {"score": float("inf"), "live": 0, "backlog": backlog,
                    "kv_util": 1.0, "prefill_backlog": 0}
        live = len(e._live)
        kv_util = 1.0 - e.alloc.free_pages / e.alloc.num_pages
        pb = e.prefill_backlog()
        score = (live + backlog) / max(1, e.max_batch) + kv_util \
            + pb / max(1, e.chunk_budget)
        out = {"score": score, "live": live, "backlog": backlog,
               "kv_util": kv_util, "prefill_backlog": pb}
        if e.prefix is not None:
            out["prefix_keys"] = e.prefix.hot_keys()
            out["page_size"] = e.page_size
        return out

    def submit(self, creq, epoch=None):
        """Queue a request for this replica's worker. Raises a typed
        :class:`AdmissionError` (with the engine's ``retry_after``
        estimate) when the replica is not accepting or its backlog is
        full — the router's cue to pick another replica. A submission
        stamped with an ``epoch`` other than this incarnation's is
        rejected with a typed :class:`StaleEpochError`: neither a
        stale router view nor a fenced-out old incarnation may accept
        work addressed to its successor."""
        if epoch is not None and int(epoch) != self.epoch:
            _m_stale().inc()
            raise StaleEpochError(self.replica_id, int(epoch),
                                  self.epoch)
        e = self.engine
        with self._lock:
            if self._dead or self._draining or e is None:
                raise AdmissionError(
                    f"replica {self.replica_id} not accepting "
                    f"({'dead' if self._dead else 'draining'})",
                    live=0 if e is None else len(e._live),
                    max_batch=0 if e is None else e.max_batch,
                    free_pages=0 if e is None else e.alloc.free_pages,
                    num_pages=0 if e is None else e.alloc.num_pages,
                    retries=0)
            if len(self._backlog) >= self.max_backlog:
                raise AdmissionError(
                    f"replica {self.replica_id} backlog full",
                    live=len(e._live), max_batch=e.max_batch,
                    free_pages=e.alloc.free_pages,
                    num_pages=e.alloc.num_pages, retries=0,
                    retry_after=e._retry_after())
            self._backlog.append(creq)

    # -- worker loop ----------------------------------------------------
    def _run(self, stop):
        try:
            while not stop.is_set():
                # deterministic kill switch for CI plans: a rule at
                # replica.dead (action raise/hang) takes this worker
                # down as a crash, not a drain
                _faults.fire("replica.dead", step=self._ticks,
                             path=self.replica_id)
                self._ticks += 1
                self._admit_from_backlog()
                served = 0
                e = self.engine
                if e is not None \
                        and any(not r.done for r in e._live.values()):
                    served = e.decode_many(self.burst) if self.burst \
                        else e.step()
                self._reap_completed()
                with self._lock:
                    idle = not served and not self._backlog
                if idle:
                    time.sleep(self.idle_sleep)
        except BaseException as exc:     # noqa: BLE001 — death IS the event
            with self._lock:
                self._dead = True
                self._death_reason = exc
            self._m_dead.inc()
            # no deregister: a crashed host never says goodbye — the
            # membership TTL is what detects it

    def _admit_from_backlog(self):
        e = self.engine
        admitted = []
        while True:
            with self._lock:
                if (self._draining or not self._backlog
                        or len(e._live) >= e.max_batch):
                    break
                creq = self._backlog.popleft()
                self._pending_admit.append(creq)
            # removal from _pending_admit happens ONLY on the normal
            # paths below: a crash anywhere in between leaves the
            # request discoverable by take_unfinished()
            if creq.done:
                self._unpend(creq)
                continue
            req = creq._new_attempt(self.replica_id)
            if req is None:
                self._unpend(creq)
                continue        # finished typed (cluster deadline)
            # thread the request's trace context onto the worker
            # thread: the admit span chains to the submitter's span
            # tree, and the engine request carries the context so the
            # first-token emit can tag itself too
            req._trace = creq._trace
            try:
                if creq._trace is not None:
                    with _tracing.activate(creq._trace), \
                            _span("serving.admit",
                                  replica=self.replica_id,
                                  prompt_len=len(creq.prompt_ids)):
                        e._admit(req)
                else:
                    e._admit(req)
            except AdmissionError:
                with self._lock:
                    self._backlog.appendleft(creq)
                    self._pending_admit.remove(creq)
                break
            except ValueError as exc:
                # never-fitting prompt: typed terminal, not a retry
                creq._fail("evicted", exc)
                self._unpend(creq)
                continue
            with self._lock:
                self._tracked[req] = creq
                self._pending_admit.remove(creq)
            admitted.append(req)
        # no explicit prefill here: admitted prompts chunk-prefill
        # inside the worker tick's very next mixed dispatch
        # (engine.step()/decode_many), interleaved with live decodes
        return admitted

    def _unpend(self, creq):
        with self._lock:
            if creq in self._pending_admit:
                self._pending_admit.remove(creq)

    def _reap_completed(self):
        with self._lock:
            finished = [(r, c) for r, c in self._tracked.items()
                        if r.done]
            for r, _ in finished:
                del self._tracked[r]
        for r, c in finished:
            c._finish_from(r)

    # -- lifecycle ------------------------------------------------------
    def begin_drain(self):
        """Stop accepting routes; the worker finishes what's admitted."""
        with self._lock:
            self._draining = True

    def take_backlog(self):
        """Pull every queued-but-unadmitted request (the router
        re-routes them before a drain or after a death)."""
        with self._lock:
            out = list(self._backlog)
            self._backlog.clear()
        return out

    def take_unfinished(self):
        """Backlog + mid-admission + tracked in-flight requests that
        are not terminal — the failover set after this replica died."""
        with self._lock:
            out = [c for c in self._backlog if not c.done]
            self._backlog.clear()
            out += [c for c in self._pending_admit if not c.done]
            self._pending_admit.clear()
            out += [c for r, c in self._tracked.items() if not c.done]
            self._tracked.clear()
        return out

    def stop_worker(self, timeout=10.0):
        """Ask the worker loop to exit and join it — the heartbeat
        sidecar too, so a stopped incarnation can never keep stamping
        membership (the ghost a later restart would resurrect). The
        engine itself stays usable — rolling restart drains it next."""
        self._stop.set()
        for t in (self._thread, self._hb_thread):
            if t is not None and t is not threading.current_thread():
                t.join(timeout)

    def drain(self, grace=30.0):
        """Drain the engine (worker must be stopped first so only one
        thread drives dispatches), then reap terminal requests."""
        stats = self.engine.drain(grace) if self.engine is not None \
            else {"seconds": 0.0, "completed": 0, "expired": 0}
        self._reap_completed()
        return stats

    def restart(self):
        """Replace the engine via the factory and rejoin the cluster —
        the second half of a rolling restart (or a kill-and-replace).
        Unfinished requests are NOT carried over; the caller fails
        them over first."""
        old = self.engine
        if old is not None:
            try:
                old.close()
            except Exception:
                pass
        self.engine = self._factory()
        with self._lock:
            self._tracked.clear()
            self._backlog.clear()
            self._pending_admit.clear()
        return self.start()

    def kill(self):
        """Simulate a SIGKILL: stop the worker abruptly, no drain, no
        deregistration — detected only by membership TTL expiry (or
        the monitor noticing the dead thread)."""
        with self._lock:
            self._dead = True
            self._death_reason = RuntimeError("killed")
        self._m_dead.inc()
        self._stop.set()

    def stop(self, timeout=10.0):
        """Clean shutdown: stop the worker and leave membership."""
        self.stop_worker(timeout)
        if self.store is not None:
            try:
                self.store.deregister(self.replica_id)
            except OSError:
                pass
        if self.engine is not None:
            self.engine.close()


class SubprocessReplica:
    """One serving replica in its OWN process — the crash-containment
    unit. A segfault, OOM, or wedged XLA dispatch inside the worker
    kills that process and nothing else; the supervisor sees the exit
    code (or the heartbeat stamp aging out) and replaces it, warm via
    the persistent compile cache.

    The process runs :func:`paddle_tpu.inference.replica_worker
    .replica_main`: it builds its engine from a JSON ``spec``,
    registers in the shared :class:`FileStore` with TTL heartbeats once
    the engine is ready (pre-warm included — registration IS the
    readiness signal), and serves requests over the
    :class:`~paddle_tpu.distributed.rpc.RpcEndpoint` transport. On this
    side, a poller thread mirrors request state back into the router's
    :class:`ClusterRequest` handles and keeps the last-seen load/ready
    snapshot for routing — no rpc on the routing hot path.

    Fault points: ``serve.spawn`` fires before each process spawn
    (path = replica id, step = spawn ordinal) so a chaos plan can fail
    spawns deterministically and drive the supervisor's circuit
    breaker.
    """

    def __init__(self, replica_id, spec, endpoint, store, store_path,
                 ttl=None, max_backlog=None, burst=None,
                 spawn_grace=180.0, poll_interval=0.05,
                 submit_timeout=15.0, env=None, on_orphan=None,
                 prewarm=True, log_dir=None, store_addr=None):
        self.replica_id = str(replica_id)
        self.spec = spec
        self.endpoint = endpoint
        self.store = store
        self.store_path = store_path
        self.store_addr = store_addr
        self.ttl = ttl
        self.max_backlog = max_backlog
        self.burst = burst
        self.spawn_grace = float(spawn_grace)
        self.poll_interval = float(poll_interval)
        self.submit_timeout = float(submit_timeout)
        self.on_orphan = on_orphan
        self.log_dir = log_dir
        self._prewarm = prewarm
        self._extra_env = dict(env or {})
        self.engine = None            # interface parity: never local
        self._proc = None
        self._log_file = None
        self._tracked: dict[str, ClusterRequest] = {}
        self._ids: dict[ClusterRequest, str] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._poller = None
        self._load = None             # last load dict seen by the poller
        self._remote_ready = False
        self._registered_seen = False
        #: the worker's membership epoch, mirrored from its poll reply;
        #: stamped onto submissions so a fenced-out old incarnation
        #: sharing the rpc mailbox name can never accept them
        self.epoch = None
        self._spawn_t = None
        self._draining = False
        self._dead = False
        self.exit_code = None
        self.restart_ttft = None      # worker-reported restart -> token
        self.cache_stats = None       # worker-reported compile cache
        self._spawns = 0
        self._m_dead = _om.counter(
            "replica_deaths_total",
            "replica worker loops that died uncleanly")

    # ------------------------------------------------------------------
    def start(self):
        import subprocess
        import sys

        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return self
        self._retire_poller()
        # the chaos hook a crash-loop plan drives: raising here IS the
        # failed spawn (bad image, full host); the supervisor backs
        # off. The ordinal advances even when the fault raises, so a
        # step-keyed rule fails exactly the attempt it names and the
        # supervisor's NEXT retry can succeed (the recovery path).
        spawn = self._spawns
        self._spawns += 1
        _faults.fire("serve.spawn", step=spawn, path=self.replica_id)
        env = dict(os.environ)
        env.update(self._extra_env)
        # the worker must import THIS paddle_tpu, wherever the router
        # imported it from (repo checkout, wheel, editable install) —
        # python -m resolves via PYTHONPATH, not the router's sys.path
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        env["PADDLE_TPU_REPLICA_ID"] = self.replica_id
        if self.store_addr is not None:
            # TCP-only control plane: the worker joins membership AND
            # its rpc mailbox through the lease server — no shared
            # filesystem path travels to it at all
            env["PADDLE_TPU_REPLICA_STORE_ADDR"] = str(self.store_addr)
            env.pop("PADDLE_TPU_REPLICA_STORE", None)
        else:
            env["PADDLE_TPU_REPLICA_STORE"] = str(self.store_path)
        env["PADDLE_TPU_REPLICA_RPC"] = \
            f"{self.endpoint.host}:{self.endpoint.port}"
        env["PADDLE_TPU_REPLICA_SPEC"] = json.dumps(self.spec)
        env["PADDLE_TPU_REPLICA_T0"] = repr(time.time())
        if self.ttl is not None:
            env["PADDLE_TPU_REPLICA_TTL"] = repr(float(self.ttl))
        if self.max_backlog is not None:
            env["PADDLE_TPU_REPLICA_BACKLOG"] = str(self.max_backlog)
        if self.burst is not None:
            env["PADDLE_TPU_REPLICA_BURST"] = str(self.burst)
        # prewarm on by default in workers: a replacement's first
        # request must hit compiled programs, not the compile bill
        env.setdefault("PADDLE_TPU_SERVING_PREWARM",
                       "1" if self._prewarm else "0")
        if self.log_dir is not None:
            # the worker flushes trace shards + flight-recorder
            # postmortems under the shared log dir (ISSUE 17)
            env["PADDLE_TPU_REPLICA_LOG_DIR"] = str(self.log_dir)
        out = subprocess.DEVNULL
        if self.log_dir is not None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._log_file = open(os.path.join(
                self.log_dir,
                f"{self.replica_id}.{self._spawns - 1}.log"), "w")
            out = self._log_file
        with self._lock:
            self._dead = False
            self._draining = False
            self.exit_code = None
            self._remote_ready = False
            self._registered_seen = False
            self._stop = threading.Event()   # fresh: old poller owns its own
            self._spawn_t = time.monotonic()
            self._proc = subprocess.Popen(
                [sys.executable, "-m",
                 "paddle_tpu.inference.replica_worker"],
                env=env, stdout=out, stderr=subprocess.STDOUT)
        self._poller = threading.Thread(
            target=self._poll_loop,
            args=(self._stop, self._proc), daemon=True,
            name=f"replica-{self.replica_id}-poll")
        self._poller.start()
        return self

    def _retire_poller(self):
        self._stop.set()
        t = self._poller
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        if self._log_file is not None:
            try:
                self._log_file.close()
            except OSError:
                pass
            self._log_file = None

    # -- the result pump ------------------------------------------------
    def _poll_loop(self, stop, proc):
        from . import replica_worker as _rw

        misses: dict[str, int] = {}
        interval = self.poll_interval
        while not stop.wait(interval):
            if proc.poll() is not None:
                with self._lock:
                    if not self._dead:
                        self._dead = True
                        self._m_dead.inc()
                    self.exit_code = proc.returncode
                return
            with self._lock:
                ids = list(self._tracked)
            # idle polls only refresh load/readiness — ease off so the
            # router is not churning a connection per 50 ms per replica
            # (each call opens a fresh store connection + waiter
            # thread); with requests in flight, poll at full rate
            interval = self.poll_interval if ids \
                else max(self.poll_interval, 0.25)
            try:
                rsp = self.endpoint.call_sync(
                    self.replica_id, _rw._worker_poll, (ids,),
                    timeout=2.0, retries=1)
            except Exception:
                continue    # starting or wedged: proc + TTL judge that
            self._remote_ready = bool(rsp.get("ready"))
            if rsp.get("epoch") is not None:
                self.epoch = rsp["epoch"]
            # NOTE: rpc reachability is NOT membership — the worker's
            # dispatcher is up before it registers, and latching
            # _registered_seen here would turn "still starting" into
            # "silently aged out" at the next sweep (a spurious death
            # per warm restart, phantom breaker counts). Only the
            # supervisor's own membership observation (is_dead) sets it.
            self._load = rsp.get("load")
            if rsp.get("restart_ttft") is not None:
                self.restart_ttft = rsp["restart_ttft"]
            if rsp.get("cache") is not None:
                self.cache_stats = rsp["cache"]
            for req_id, state in (rsp.get("requests") or {}).items():
                with self._lock:
                    creq = self._tracked.get(req_id)
                if creq is None:
                    continue
                if state is None:
                    # the worker does not know this request (reply to
                    # its submit was lost, or a restart raced us):
                    # after a few confirmations, orphan it back to the
                    # router for failover — never strand the handle
                    misses[req_id] = misses.get(req_id, 0) + 1
                    if misses[req_id] >= 3:
                        misses.pop(req_id, None)
                        self._untrack(creq)
                        if self.on_orphan is not None:
                            self.on_orphan(creq, self.replica_id)
                    continue
                misses.pop(req_id, None)
                if state.get("done"):
                    self._untrack(creq)
                    creq._finish_remote(state.get("status"),
                                        state.get("output_ids"),
                                        state.get("error"))
                else:
                    # live request: mirror the partial output so a
                    # streaming frontend can push tokens while the
                    # request is still decoding on the worker
                    creq._mirror_partial(state.get("output_ids"))

    def _untrack(self, creq):
        with self._lock:
            req_id = self._ids.pop(creq, None)
            if req_id is not None:
                self._tracked.pop(req_id, None)

    # -- router-facing surface -----------------------------------------
    def alive(self):
        p = self._proc
        return (not self._dead) and p is not None and p.poll() is None

    def is_dead(self, registered):
        """Death verdict: exited process (any exit code), a registered
        replica whose stamp aged out (frozen heartbeats / SIGKILL), or
        a spawn that never reached membership within ``spawn_grace``
        (wedged startup)."""
        p = self._proc
        if p is None or self._dead or p.poll() is not None:
            return True
        if registered:
            self._registered_seen = True
            return False
        if self._draining:
            return False
        if self._registered_seen:
            return True         # was in membership, silently aged out
        return (time.monotonic() - self._spawn_t) > self.spawn_grace

    def ready(self):
        return self.alive() and not self._draining and self._remote_ready

    def load(self):
        l = self._load
        if not self.alive() or l is None:
            return {"score": float("inf"), "live": 0, "backlog": 0,
                    "kv_util": 1.0, "prefill_backlog": 0}
        return l

    def submit(self, creq):
        from . import replica_worker as _rw

        with self._lock:
            if self._dead or self._draining or not self._remote_ready:
                state = "dead" if self._dead else \
                    "draining" if self._draining else "starting"
                raise AdmissionError(
                    f"replica {self.replica_id} not accepting ({state})",
                    live=0, max_batch=0, free_pages=0, num_pages=0,
                    retries=0)
        spec = creq._attempt_spec(self.replica_id)
        if spec is None:
            return          # finished typed (cluster deadline) already
        # fence the submission with the epoch this router observed: if
        # the call lands in a partitioned OLD incarnation's dispatcher
        # (both incarnations share the name-keyed mailbox), that
        # incarnation rejects it typed instead of serving as a ghost
        spec["epoch"] = self.epoch
        try:
            req_id = self.endpoint.call_sync(
                self.replica_id, _rw._worker_submit, (spec,),
                timeout=self.submit_timeout)
        except AdmissionError:
            raise           # typed backpressure, fields intact (pickled)
        except StaleEpochError as e:
            # OUR view of the epoch is stale (the worker restarted
            # under a newer one): not accepting right now — the poller
            # refreshes the epoch and the router retries a peer
            raise AdmissionError(
                f"replica {self.replica_id} rejected a stale-epoch "
                f"submission ({e})", live=0, max_batch=0, free_pages=0,
                num_pages=0, retries=0) from e
        except Exception as e:
            # transport failure == not accepting: the router's cue to
            # try a peer; liveness is the supervisor's job, not submit's
            raise AdmissionError(
                f"replica {self.replica_id} unreachable "
                f"({type(e).__name__})", live=0, max_batch=0,
                free_pages=0, num_pages=0, retries=0) from e
        with self._lock:
            self._tracked[req_id] = creq
            self._ids[creq] = req_id

    def cancel_attempt(self, creq):
        from . import replica_worker as _rw

        with self._lock:
            req_id = self._ids.get(creq)
        if req_id is None:
            return
        try:
            self.endpoint.call_sync(self.replica_id, _rw._worker_cancel,
                                    (req_id,), timeout=5.0, retries=1)
        except Exception:
            pass            # dead replica: the monitor reaps it anyway

    # -- lifecycle ------------------------------------------------------
    def begin_drain(self):
        from . import replica_worker as _rw

        with self._lock:
            self._draining = True
        try:
            self.endpoint.call_sync(self.replica_id,
                                    _rw._worker_begin_drain, (),
                                    timeout=5.0, retries=1)
        except Exception:
            pass

    def take_backlog(self):
        """Pull queued-but-unadmitted requests back from the worker (the
        router re-routes them before a drain)."""
        from . import replica_worker as _rw

        try:
            ids = self.endpoint.call_sync(
                self.replica_id, _rw._worker_take_backlog, (),
                timeout=5.0, retries=1)
        except Exception:
            return []
        out = []
        with self._lock:
            for req_id in ids:
                creq = self._tracked.pop(req_id, None)
                if creq is not None:
                    self._ids.pop(creq, None)
                    if not creq.done:
                        out.append(creq)
        return out

    def take_unfinished(self):
        """Every tracked non-terminal request — the failover set after
        this replica's process died."""
        with self._lock:
            out = [c for c in self._tracked.values() if not c.done]
            self._tracked.clear()
            self._ids.clear()
        return out

    def stop_worker(self, timeout=10.0):
        """In-process replicas stop their worker thread here; for a
        subprocess the worker loop is stopped by :meth:`drain` inside
        the worker itself. A DEAD process is reaped and its poller
        retired."""
        if not self.alive():
            self._retire_poller()

    def drain(self, grace=30.0):
        from . import replica_worker as _rw

        try:
            # retries=0: the per-attempt budget already covers a full
            # worker-side drain (grace + slack), so a timeout means a
            # dead/partitioned worker — retrying would stall a rolling
            # restart by another grace+30 for a benign fallback (the
            # reap + failover paths own the requests either way)
            stats = self.endpoint.call_sync(
                self.replica_id, _rw._worker_drain, (grace,),
                timeout=grace + 30.0, retries=0)
        except Exception:
            stats = {"seconds": 0.0, "completed": 0, "expired": 0}
        # mirror the drained requests' terminal states NOW (the
        # in-process drain ends with a synchronous _reap_completed):
        # a restart right after this would kill the worker — and with
        # it the results — before the 50ms poller's next pass
        self._reap_tracked()
        return stats

    def _reap_tracked(self):
        """One synchronous poll that adopts every tracked request's
        terminal state — the subprocess analog of
        :meth:`EngineReplica._reap_completed`."""
        from . import replica_worker as _rw

        with self._lock:
            ids = list(self._tracked)
        if not ids:
            return
        try:
            rsp = self.endpoint.call_sync(
                self.replica_id, _rw._worker_poll, (ids,), timeout=10.0,
                retries=1)
        except Exception:
            return          # dead/unreachable: failover owns these
        for req_id, state in (rsp.get("requests") or {}).items():
            with self._lock:
                creq = self._tracked.get(req_id)
            if creq is None or state is None or not state.get("done"):
                continue
            self._untrack(creq)
            creq._finish_remote(state.get("status"),
                                state.get("output_ids"),
                                state.get("error"))

    def restart(self):
        """Replace the process: clean-exit the old one if it is still
        up, then spawn fresh. Requests whose terminal state was never
        mirrored back (and are not yet done) are handed to
        ``on_orphan`` for failover — a restart must never strand a
        handle in limbo."""
        self._request_exit(timeout=5.0)
        self._retire_poller()
        with self._lock:
            leftovers = [c for c in self._tracked.values()
                         if not c.done]
            self._tracked.clear()
            self._ids.clear()
        for creq in leftovers:
            if self.on_orphan is not None:
                self.on_orphan(creq, self.replica_id)
        return self.start()

    def _request_exit(self, timeout=5.0):
        from . import replica_worker as _rw

        p = self._proc
        if p is None:
            return
        if p.poll() is None:
            for _ in range(2):      # a lost first ask is retried once
                try:
                    # retries=0: this loop IS the retry policy — the
                    # rpc layer doubling it would block stop() for up
                    # to 6 attempts against an already-exiting worker
                    self.endpoint.call_sync(self.replica_id,
                                            _rw._worker_exit, (),
                                            timeout=timeout, retries=0)
                    break
                except Exception:
                    continue
            try:
                p.wait(timeout=timeout)
            except Exception:
                p.terminate()
                try:
                    p.wait(timeout=timeout)
                except Exception:
                    p.kill()
                    p.wait()
        self.exit_code = p.returncode

    def kill(self):
        """SIGKILL the worker process: no drain, no deregistration —
        membership TTL (or the exit code) is what detects it."""
        with self._lock:
            self._dead = True
        self._m_dead.inc()
        p = self._proc
        if p is not None and p.poll() is None:
            p.kill()

    def stop(self, timeout=10.0):
        """Clean shutdown: the worker drains nothing but deregisters
        from membership and exits 0."""
        self._request_exit(timeout=timeout)
        self._retire_poller()


class _RestartState:
    """Supervisor bookkeeping for ONE replica id: when it died, whether
    its death has been processed, when the next (backed-off) restart is
    due, and whether the crash-loop breaker holds it out."""

    __slots__ = ("deaths", "down", "restart_at", "quarantined",
                 "postmortem")

    def __init__(self):
        self.deaths = collections.deque(maxlen=64)  # monotonic stamps
        self.down = False
        self.restart_at = None
        self.quarantined = False
        self.postmortem = None      # newest harvested postmortem dir


class ServingCluster:
    """Routing frontend + supervisor over N replicas.

    Replicas are in-process :class:`EngineReplica` threads (tests,
    single-tenant embedding) or — with ``engine_spec`` — real
    :class:`SubprocessReplica` processes: crash containment, exit-code
    liveness, and warm restart via the persistent compile cache.

    The supervisor (the monitor thread's sweep) restarts dead replicas
    with exponential backoff + jitter, bounded by a crash-loop circuit
    breaker: ``breaker_threshold`` deaths inside ``breaker_window``
    seconds quarantine the replica (``cluster_replica_quarantined_
    total``) — capacity shrinks and the tier sheds with typed
    backpressure instead of burning a restart storm. A dead replica's
    membership stamp is swept immediately so membership never shows a
    ghost, and its unfinished requests fail over to its peers.

    Args:
        engine_factory: zero-arg callable building a fresh
            :class:`LlamaServingEngine` (in-process replicas; ignored
            when ``engine_spec`` is given).
        num_replicas: replica count at start().
        store_path: membership directory (a shared filesystem in a
            real deployment); default: a private temp dir.
        store_addr: ``"host:port"`` of a
            :class:`~paddle_tpu.distributed.net_store
            .LeaseStoreServer` — switches the WHOLE control plane
            (membership + rpc mailboxes) to TCP, no shared filesystem
            anywhere; overrides ``store_path``.
        ttl: membership TTL in seconds — a replica whose heartbeat is
            older ages out and is treated as dead.
        monitor_interval: seconds between membership sweeps.
        store_outage_grace: seconds of store unreachability after
            which NEW admissions are rejected typed (``retry_after``).
            In-flight requests always run to completion from the
            last-known-membership cache, and store silence alone never
            fails a replica over.
        auto_replace: rebuild dead replicas automatically
            (kill-and-replace).
        failover_budget: default per-request failover budget.
        engine_spec: JSON-able spec for subprocess replicas (see
            :mod:`paddle_tpu.inference.replica_worker`); switches the
            cluster to process-isolated mode.
        restart_backoff / restart_backoff_max / restart_jitter:
            supervisor restart delay: ``min(max, backoff * 2**(deaths
            in window - 1)) * (1 + jitter*rand)``.
        breaker_threshold / breaker_window: crash-loop circuit breaker
            (N deaths in window seconds -> quarantine).
        spawn_grace: seconds a subprocess may spend starting (imports +
            compiles) before a missing membership stamp means "wedged".
        subprocess_env: extra environment for worker processes (e.g.
            ``PADDLE_TPU_COMPILE_CACHE_DIR`` so replicas share a warm
            cache).
        log_dir: per-worker stdout/stderr log files (default: discard).
    """

    def __init__(self, engine_factory=None, num_replicas=2,
                 store_path=None, store_addr=None, ttl=2.0,
                 monitor_interval=0.05, store_outage_grace=5.0,
                 auto_replace=True, failover_budget=3, max_backlog=None,
                 burst=None, engine_spec=None, subprocess_env=None,
                 restart_backoff=0.1, restart_backoff_max=30.0,
                 restart_jitter=0.25, breaker_threshold=5,
                 breaker_window=30.0, spawn_grace=180.0,
                 submit_timeout=15.0, log_dir=None, prewarm=True,
                 affinity_weight=1.0, slo_interval=5.0, slos=None):
        if engine_factory is None and engine_spec is None:
            raise ValueError(
                "ServingCluster needs engine_factory (in-process "
                "replicas) or engine_spec (subprocess replicas)")
        self._factory = engine_factory
        self._spec = engine_spec
        self.num_replicas = int(num_replicas)
        self.ttl = ttl
        if store_addr is not None:
            # TCP-only control plane: membership AND the rpc mailboxes
            # ride one LeaseStoreServer at store_addr — no shared
            # filesystem anywhere (replicas may span hosts)
            self.store_addr = str(store_addr)
            self._store_path = None
            self.store = LeaseStore(store_addr, ttl=ttl)
        else:
            self.store_addr = None
            self._store_path = store_path \
                or tempfile.mkdtemp(prefix="paddle_tpu_cluster_")
            self.store = FileStore(self._store_path, ttl=ttl)
        # store-outage degradation (see _live_hosts/submit): routing
        # serves from the last-known-membership cache for the whole
        # outage, but NEW admissions are rejected typed (retry_after)
        # once the outage exceeds this grace window
        self.store_outage_grace = float(store_outage_grace)
        self._member_cache: set = set()
        self._member_cache_t = None
        self._outage_since = None
        self._lenient_until = 0.0
        self._store_gen = 0
        self._m_cache_age = _om.gauge(
            "cluster_membership_cache_age_seconds",
            "age of the membership view routing decisions are based "
            "on (0 while the store is reachable)")
        self.monitor_interval = float(monitor_interval)
        self.auto_replace = auto_replace
        self.failover_budget = int(failover_budget)
        self.max_backlog = max_backlog
        self.burst = burst
        self.subprocess_env = dict(subprocess_env or {})
        self.restart_backoff = float(restart_backoff)
        self.restart_backoff_max = float(restart_backoff_max)
        self.restart_jitter = float(restart_jitter)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_window = float(breaker_window)
        self.spawn_grace = float(spawn_grace)
        self.submit_timeout = float(submit_timeout)
        self.log_dir = log_dir
        self.prewarm = prewarm
        # prefix-affinity routing (ROADMAP item 2b): a full chain-hash
        # overlap between a prompt's page-aligned prefix and a
        # replica's advertised hot-prefix set discounts that replica's
        # load score by this much — enough to beat modest load deltas,
        # never enough to pile every request on one replica (a full
        # batch of load outweighs it). 0 disables (load-only routing).
        self.affinity_weight = float(affinity_weight)
        self._endpoint = None
        self._replicas: dict[str, object] = {}
        self._restarts: dict[str, _RestartState] = {}
        self._maintenance: set[str] = set()   # ids mid-rolling-restart
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._monitor_thread = None
        self._elastic = None
        self._m = _router_metrics()
        self._route_count = 0
        self._started = False
        # SLO burn-rate engine: fed cluster-aggregated TTFT/TPOT
        # histograms by the sweep every ``slo_interval`` seconds;
        # surfaces on membership_info() and the
        # serving_slo_burn_rate{slo,window} gauge
        self.slo_interval = float(slo_interval)
        self.slo = _slo.SloEngine(slos=slos)
        self._slo_last = 0.0
        self._slo_burn = {}

    # ------------------------------------------------------------------
    def _make_replica(self, rid):
        if self._spec is not None:
            return SubprocessReplica(
                rid, self._spec, self._endpoint, self.store,
                self._store_path, ttl=self.ttl,
                max_backlog=self.max_backlog, burst=self.burst,
                spawn_grace=self.spawn_grace,
                submit_timeout=self.submit_timeout,
                env=self.subprocess_env, on_orphan=self._orphaned,
                prewarm=self.prewarm, log_dir=self.log_dir,
                store_addr=self.store_addr)
        return EngineReplica(rid, self._factory, store=self.store,
                             ttl=self.ttl, max_backlog=self.max_backlog,
                             burst=self.burst)

    def _restart_state(self, rid):
        with self._lock:
            st = self._restarts.get(rid)
            if st is None:
                st = self._restarts[rid] = _RestartState()
            return st

    def start(self):
        with self._lock:
            if self._started:
                return self
            self._started = True
        if self._spec is not None and self._endpoint is None:
            from ..distributed.rpc import RpcEndpoint

            if self.store_addr is not None:
                # TCP-only mode: the router mailbox rides the SAME
                # lease server as membership (its own session), so a
                # store restart is the only control-plane failure
                # domain and the mailboxes resync through it
                self._endpoint = RpcEndpoint(
                    "router", store=self.store.clone())
            else:
                self._endpoint = RpcEndpoint("router", is_master=True,
                                             port=0)
        for i in range(self.num_replicas):
            rid = f"replica-{i}"
            rep = self._make_replica(rid)
            try:
                rep.start()
            except Exception:
                # a failed first spawn is a death like any other: the
                # same bookkeeping backs off, counts toward the
                # breaker, and quarantines — the cluster comes up on
                # the replicas that did start
                st = self._restart_state(rid)
                st.down = True
                self._record_death(rid, st)
            self._replicas[rid] = rep
        self._elastic = ElasticManager(self.store, "router",
                                       self.num_replicas)
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True, name="cluster-monitor")
        self._monitor_thread.start()
        return self

    def _orphaned(self, creq, rid):
        """A subprocess replica forgot a tracked request (lost submit
        reply, mid-restart race): fail it over like a death would."""
        self._failover(creq, dead_rid=rid)

    def replicas(self):
        with self._lock:
            return dict(self._replicas)

    def ready(self):
        """Cluster readiness: at least one routable replica (wire to
        ``start_http_server(ready=cluster.ready)`` for ``/readyz``)."""
        return any(r.ready() for r in self.replicas().values())

    def membership_info(self):
        """Per-replica membership view for /healthz: current epoch,
        last-heartbeat age (fs-server clock), and liveness — what an
        operator reads to spot a fenced-out stale incarnation without
        grepping logs."""
        out = {}
        quarantined = self.quarantined()
        with self._lock:
            postmortems = {rid: st.postmortem
                           for rid, st in self._restarts.items()}
        for rid, rep in self.replicas().items():
            try:
                hb_age = self.store.heartbeat_age(rid)
            except OSError:
                hb_age = None   # store outage: age unknown, not 0
            out[rid] = {
                "epoch": getattr(rep, "epoch", None),
                "heartbeat_age_seconds": hb_age,
                "alive": rep.alive(),
                "ready": rep.ready(),
                "quarantined": rid in quarantined,
                "postmortem": postmortems.get(rid),
            }
        info = {"membership": out}
        if _om.enabled():
            info["slo_burn_rates"] = self._slo_tick()
        return info

    def start_http_server(self, port=0, addr="127.0.0.1"):
        """One-pane endpoint for the whole tier: ``/metrics`` and
        ``/metrics.json`` render the *merged* cluster scrape (every
        replica's registry under a ``replica`` label — see
        :meth:`scrape`), ``/healthz`` carries :meth:`membership_info`
        (epochs + heartbeat ages + SLO burn rates)."""
        from ..observability.export import start_http_server
        return start_http_server(port=port, addr=addr, ready=self.ready,
                                 health_info=self.membership_info,
                                 snapshot_fn=self.scrape,
                                 profile_fn=self.capture_profile)

    # -- one-pane observability ----------------------------------------
    def scrape(self):
        """Cluster-wide metrics snapshot: every subprocess replica's
        registry (pulled over the rpc path) plus this router process's
        own, merged under a ``replica`` label (``replica="router"`` for
        the local registry). In-process replicas share the router's
        registry, so they are already covered by the local snapshot.
        A replica whose scrape rpc fails is skipped (and counted on
        ``cluster_scrape_failures_total``) — one sick replica must not
        blank the pane."""
        sources = []
        if self._spec is not None and self._endpoint is not None:
            from . import replica_worker as _rw
            for rid, rep in self.replicas().items():
                if not rep.alive():
                    continue
                try:
                    rsp = self._endpoint.call_sync(
                        rid, _rw._worker_scrape, (),
                        timeout=2.0, retries=1)
                    sources.append((rid, rsp.get("snapshot") or []))
                except Exception:
                    self._m["scrape_failures"].labels(rid).inc()
        sources.append(("router", json_snapshot()))
        return merge_snapshots(sources)

    def _slo_tick(self, force=False):
        """Feed the SLO engine one cumulative TTFT/TPOT point from the
        cluster-aggregated scrape (rate-limited to ``slo_interval``)."""
        if not _om.enabled():
            return self._slo_burn
        now = time.monotonic()
        if not force and now - self._slo_last < self.slo_interval:
            return self._slo_burn
        self._slo_last = now
        agg = {e["name"]: e for e in aggregate_snapshot(self.scrape())}
        for spec in self.slo.slos:
            entry = agg.get(spec.metric)
            if entry is None or entry.get("type") != "histogram":
                continue
            buckets = counts = None
            for sample in entry.get("samples", ()):
                if buckets is None:
                    buckets = list(sample["buckets"])
                    counts = list(sample["counts"])
                elif list(sample["buckets"]) == buckets:
                    counts = [a + b for a, b
                              in zip(counts, sample["counts"])]
            if buckets is not None:
                self.slo.observe(spec.name, buckets, counts, now=now)
        self._slo_burn = self.slo.burn_rates(now=now)
        return self._slo_burn

    def collect_trace(self, path=None):
        """Harvest every worker's span shard from ``log_dir`` plus this
        process's own live span ring and merge them into ONE Perfetto-
        loadable chrome-trace document, shard timestamps shifted onto a
        common clock via each process's recorded monotonic<->epoch
        offset (see ``tracing.merge_shards``). ``path`` additionally
        writes the JSON there. Returns the merged document (``None``
        under ``PADDLE_TPU_METRICS=0``)."""
        if not _om.enabled():
            return None
        shards = []
        if self.log_dir is not None:
            shards.extend(_tracing.harvest_shards(self.log_dir))
        shards.append(_tracing.local_shard("router"))
        merged = _tracing.merge_shards(shards)
        if path is not None:
            with open(path, "w") as f:
                json.dump(merged, f)
        return merged

    def capture_profile(self, seconds=1.0, path=None):
        """Cluster-wide on-demand profiler capture: fan
        ``_worker_capture_profile`` out to every live subprocess
        replica over the rpc path — each runs a ``jax.profiler``
        window of ``seconds`` while it keeps serving — capture the
        router's own window concurrently, and merge all shards with
        the PR-17 clock machinery into ONE Perfetto-loadable bundle
        (``/debug/profile?seconds=N`` on :meth:`start_http_server`
        serves exactly this). A replica whose capture rpc fails is
        skipped (counted on ``cluster_scrape_failures_total``) — one
        sick replica must not blank the capture. ``path`` additionally
        writes the JSON there. Returns the merged document (``None``
        under ``PADDLE_TPU_METRICS=0``)."""
        from ..observability import perf as _perf

        if not _om.enabled():
            return None
        seconds = min(max(float(seconds), 0.0), 30.0)
        shards = []
        shard_lock = threading.Lock()

        def _pull(rid):
            from . import replica_worker as _rw
            try:
                shard = self._endpoint.call_sync(
                    rid, _rw._worker_capture_profile, (seconds,),
                    timeout=seconds + 30.0, retries=0)
                with shard_lock:
                    shards.append(shard)
            except Exception:
                self._m["scrape_failures"].labels(rid).inc()

        pullers = []
        if self._spec is not None and self._endpoint is not None:
            for rid, rep in self.replicas().items():
                if not rep.alive():
                    continue
                t = threading.Thread(target=_pull, args=(rid,),
                                     name=f"profile-{rid}", daemon=True)
                t.start()
                pullers.append(t)
        # the router's own window runs concurrently with the fan-out
        shards.append(_perf.capture_local(seconds, worker_name="router"))
        for t in pullers:
            t.join(timeout=seconds + 35.0)
        merged = _tracing.merge_shards(shards)
        merged["capture"] = {
            "seconds": seconds,
            "workers": [s.get("worker") for s in shards],
            "pids": sorted({s.get("pid") for s in shards
                            if s.get("pid") is not None}),
            "profiler": {s.get("worker"): s.get("profiler")
                         for s in shards},
        }
        if path is not None:
            with open(path, "w") as f:
                json.dump(merged, f)
        return merged

    def request_trace(self, trace_id):
        """One request's cross-process timeline: the parent-linked span
        tree for ``trace_id`` assembled from the merged cluster trace
        (what ``GET /v1/requests/<id>/trace`` serves)."""
        merged = self.collect_trace()
        if merged is None:
            return {"trace_id": trace_id, "spans": []}
        return {"trace_id": trace_id,
                "spans": _tracing.span_tree(merged["traceEvents"],
                                            trace_id)}

    # -- routing --------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
               deadline=None, token_budget=None, priority=0,
               retry_budget=1, failover_budget=None, sampling=None,
               stop=(), on_token=None):
        """Route one request to the least-loaded ready replica.
        Returns a :class:`ClusterRequest`; raises a typed
        :class:`AdmissionError` carrying the smallest ``retry_after``
        across replicas when the whole tier is at capacity.
        ``sampling``/``stop``/``on_token`` ride the request to the
        engine (see :class:`ClusterRequest`)."""
        outage = self._store_outage_age()
        if outage > self.store_outage_grace:
            # degraded mode: in-flight work keeps running off the
            # membership cache, but admitting NEW work against a view
            # this stale risks routing onto corpses — reject typed,
            # with a retry_after sized to one lease period
            self._m["backpressure"].inc()
            raise AdmissionError(
                f"control-plane store {getattr(self, 'store_addr', None)} "
                f"unreachable for {outage:.1f}s (grace "
                f"{self.store_outage_grace:.1f}s): new admissions "
                "rejected until it reconnects",
                live=0, max_batch=0, free_pages=0, num_pages=0,
                retries=0,
                retry_after=min(5.0, max(0.5, float(self.ttl or 1.0))))
        creq = ClusterRequest(
            prompt_ids, max_new_tokens, eos_token_id, deadline,
            token_budget, priority, retry_budget,
            self.failover_budget if failover_budget is None
            else failover_budget, sampling=sampling, stop=stop,
            on_token=on_token)
        creq._t_submit = time.perf_counter()
        self._route(creq)
        return creq

    def _live_hosts(self):
        """Membership scan that tolerates store outages. A successful
        scan refreshes the last-known-membership cache; an unreachable
        store serves the cache instead, age-stamped on the
        ``cluster_membership_cache_age_seconds`` gauge — a store
        outage is NOT a replica death, so routing and the sweep keep
        working from the cached view (process death via ``alive()``
        still surfaces). On reconnect, a lenient window of
        ttl + outage credit unions the cache into the live set while
        replicas re-register their leases against the (possibly
        restarted) server."""
        now = time.monotonic()
        gen = getattr(self.store, "restarts", None)
        try:
            hosts = set(self.store.hosts())
        except StoreUnavailableError:
            if self._outage_since is None:
                self._outage_since = now
            if self._member_cache_t is not None:
                self._m_cache_age.set(now - self._member_cache_t)
            return set(self._member_cache)
        # a server RESTART can be invisible to this thread's exception
        # bookkeeping (a short outage may be fully absorbed by other
        # threads' retry envelopes on the shared client) — but the
        # session's boot-nonce generation can't miss it
        cur_gen = gen() if gen is not None else 0
        restarted = cur_gen != getattr(self, "_store_gen", 0)
        self._store_gen = cur_gen
        if self._outage_since is not None or restarted:
            outage = 0.0 if self._outage_since is None \
                else now - self._outage_since
            self._outage_since = None
            # outage credit: cached heartbeats could not refresh while
            # the server was down, and a restarted server holds no
            # leases until replicas re-register — suppress age-out
            # verdicts for ttl + credit while membership reconverges
            credit = min(30.0, max(outage, 1.0 if restarted else 0.0))
            self._lenient_until = now + float(self.ttl or 0.0) + credit
        if now < self._lenient_until:
            hosts |= self._member_cache
        else:
            self._member_cache = set(hosts)
        self._member_cache_t = now
        self._m_cache_age.set(0.0)
        return hosts

    def _store_outage_age(self):
        # the store client stamps its outage at the FIRST unanswered
        # attempt — earlier (and so more honest for the admission
        # grace) than the sweep noticing a whole scan's retry
        # envelope failed
        age = getattr(self.store, "outage_age", None)
        if age is not None:
            return age()
        if self._outage_since is None:
            return 0.0
        return time.monotonic() - self._outage_since

    def _routable(self, exclude=()):
        live_hosts = self._live_hosts()
        with self._lock:
            reps = [r for rid, r in self._replicas.items()
                    if rid not in exclude
                    and rid not in self._maintenance
                    and r.ready() and rid in live_hosts]
        return reps

    def _route(self, creq, exclude=()):
        with self._lock:
            step = self._route_count
            self._route_count += 1
        # deterministic routing-error injection for CI plans
        _faults.fire("router.route", step=step)
        # score = load - affinity_weight * prefix overlap: replicas
        # whose advertised hot-prefix set chain-hashes over this
        # prompt's page-aligned prefix are preferred (their cache
        # already holds the K/V), falling back to pure load when no
        # replica advertises keys or nothing overlaps
        candidates = []
        key_cache: dict[int, set] = {}
        for rep in self._routable(exclude):
            l = rep.load()
            score = l.get("score", float("inf"))
            overlap = 0
            adv = l.get("prefix_keys")
            page = int(l.get("page_size") or 0)
            if adv and page > 0 and self.affinity_weight:
                keys = key_cache.get(page)
                if keys is None:
                    from .prefix_cache import chain_keys
                    keys = key_cache[page] = {
                        k.hex() for k in chain_keys(
                            creq.prompt_ids, page, limit=8)}
                if keys:
                    overlap = len(keys & set(adv))
                    score -= self.affinity_weight * overlap / len(keys)
            candidates.append((score, overlap, rep))
        candidates.sort(key=lambda t: t[0])
        retry_after = None
        stats = {"live": 0, "max_batch": 0, "free_pages": 0,
                 "num_pages": 0}
        for score, overlap, rep in candidates:
            try:
                with _span("cluster.route", replica=rep.replica_id):
                    rep.submit(creq)
            except AdmissionError as e:
                if e.retry_after is not None:
                    retry_after = e.retry_after if retry_after is None \
                        else min(retry_after, e.retry_after)
                for k in stats:
                    stats[k] += getattr(e, k, 0)
                continue
            creq.replica_id = rep.replica_id
            self._m["routed"].labels(rep.replica_id).inc()
            if overlap:
                self._m["affinity_hits"].inc()
            return rep

        self._m["backpressure"].inc()
        raise AdmissionError(
            f"no replica accepted the request "
            f"({len(candidates)} routable of {len(self._replicas)})",
            retries=0, retry_after=retry_after, **stats)

    def cancel(self, creq):
        """Cancel a cluster request: the handle turns terminal and the
        current attempt (if any) is cancelled on its replica — in
        process directly, over rpc for a subprocess replica."""
        req = creq.cancel()
        rep = self._replicas.get(creq.replica_id)
        if req is not None and rep is not None:
            rep.cancel_attempt(creq)

    # -- membership monitor --------------------------------------------
    def _monitor(self):
        while not self._stop.wait(self.monitor_interval):
            try:
                self._sweep()
            except Exception:
                # the monitor must survive transient store errors; the
                # next sweep retries
                pass

    def _claim(self, rid, rep=None):
        """Atomically claim a replica for exclusive maintenance (the
        monitor's death handling vs rolling_restart — whoever claims
        first proceeds; the other skips or waits). Returns False when
        already claimed, or when ``rep`` no longer IS the registered
        replica (a stale snapshot)."""
        with self._lock:
            if rid in self._maintenance:
                return False
            if rep is not None and self._replicas.get(rid) is not rep:
                return False
            self._maintenance.add(rid)
            return True

    def _release_claim(self, rid):
        with self._lock:
            self._maintenance.discard(rid)

    def _sweep(self):
        if self._elastic is not None:
            try:
                self._elastic.watch_once()  # live-host gauge + events
            except OSError:
                pass    # store outage: membership events pause
        live_hosts = self._live_hosts()
        now = time.monotonic()
        with self._lock:
            reps = [(rid, r) for rid, r in self._replicas.items()
                    if rid not in self._maintenance]
        ready = 0
        for rid, rep in reps:
            st = self._restart_state(rid)
            if st.quarantined:
                continue        # held out by the breaker; capacity down
            if st.down:
                # death already processed — restart when the backoff
                # delay is up (never block the sweep sleeping on it)
                if self.auto_replace and st.restart_at is not None \
                        and now >= st.restart_at \
                        and self._claim(rid, rep):
                    try:
                        self._try_restart(rid, rep, st)
                    finally:
                        self._release_claim(rid)
                continue
            if rep.is_dead(rid in live_hosts):
                # claim BEFORE touching the replica: rolling_restart
                # may have started on it since the snapshot (its
                # stop_worker looks like a death), and two rebuilders
                # racing one replica would tear its engine
                if not self._claim(rid, rep):
                    continue
                try:
                    self._handle_death(rid, rep, st)
                finally:
                    self._release_claim(rid)
            elif rep.ready():
                ready += 1
        self._m["ready"].set(ready)
        with self._lock:
            quarantined = sum(1 for s in self._restarts.values()
                              if s.quarantined)
        self._m["quarantined_now"].set(quarantined)
        self._slo_tick()

    def _backoff_delay(self, st, now):
        """Restart delay from the deaths inside the breaker window:
        exponential from ``restart_backoff``, capped, jittered so a
        correlated mass failure does not respawn in lockstep."""
        recent = sum(1 for t in st.deaths
                     if now - t <= self.breaker_window)
        delay = min(self.restart_backoff_max,
                    self.restart_backoff * (2 ** max(0, recent - 1)))
        return delay * (1.0 + self.restart_jitter * random.random())

    def _record_death(self, rid, st):
        """Append one death; trip the breaker when the window fills.
        Returns True when the replica is now quarantined."""
        now = time.monotonic()
        st.deaths.append(now)
        recent = sum(1 for t in st.deaths
                     if now - t <= self.breaker_window)
        if recent >= self.breaker_threshold:
            st.quarantined = True
            st.restart_at = None
            self._m["quarantined"].inc()
            return True
        if self.auto_replace:
            st.restart_at = now + self._backoff_delay(st, now)
        return False

    def _handle_death(self, rid, rep, st):
        """Fail over a dead replica's requests and schedule its
        (backed-off) rebuild. Caller holds the maintenance claim."""
        orphans = rep.take_unfinished()
        rep.stop_worker(timeout=1.0)
        # ghost sweep: a confirmed-dead replica leaves membership NOW —
        # the TTL detects silent death, it is not a grace period during
        # which routing peers may still see the ghost
        try:
            self.store.deregister(rid)
        except OSError:
            pass
        for creq in orphans:
            self._failover(creq, dead_rid=rid)
        self._harvest_postmortem(rid, rep, st)
        st.down = True
        self._record_death(rid, st)

    def _harvest_postmortem(self, rid, rep, st):
        """A subprocess worker's fatal handler dumps a flight-recorder
        bundle under ``<log_dir>/<rid>/postmortem/<run>``; record the
        newest one on the replica's restart state so an operator (or
        ``stats()``) finds it without grepping the log dir. Run names
        sort lexicographically ~= chronologically."""
        log_dir = getattr(rep, "log_dir", None)
        if log_dir is None:
            return
        pm_dir = os.path.join(str(log_dir), rid, "postmortem")
        try:
            bundles = sorted(os.listdir(pm_dir))
        except OSError:
            return
        if not bundles:
            return
        path = os.path.join(pm_dir, bundles[-1])
        if path == st.postmortem:
            return              # same bundle as the previous death
        st.postmortem = path
        logging.getLogger("paddle_tpu.cluster").warning(
            "replica %s died; postmortem bundle at %s", rid, path)

    def _try_restart(self, rid, rep, st):
        """One backed-off restart attempt. A failed spawn (serve.spawn
        fault, OS error) counts as another death — backoff grows, and
        the breaker quarantines a crash loop."""
        try:
            rep.restart()
        except Exception:
            self._record_death(rid, st)
            return
        st.down = False
        st.restart_at = None
        self._m["replaced"].inc()

    def quarantined(self):
        """Replica ids currently held out by the circuit breaker."""
        with self._lock:
            return {rid for rid, st in self._restarts.items()
                    if st.quarantined}

    def rehabilitate(self, rid):
        """Operator override: clear a quarantined replica's breaker
        state and schedule an immediate restart attempt."""
        st = self._restart_state(rid)
        with self._lock:
            st.quarantined = False
            st.deaths.clear()
            st.down = True
            st.restart_at = time.monotonic()

    def _failover(self, creq, dead_rid):
        if creq.done:
            return
        creq.failovers += 1
        if creq.failovers > creq.failover_budget:
            self._m["lost"].inc()
            creq._fail("evicted", ReplicaLostError(
                f"replica {dead_rid} died and the failover budget "
                f"({creq.failover_budget}) is exhausted",
                replica_id=dead_rid, failovers=creq.failovers))
            return
        self._m["failover"].inc()
        try:
            self._route(creq, exclude=(dead_rid,))
        except AdmissionError as e:
            # the tier is saturated right now — typed terminal rather
            # than a silent drop; callers see the backpressure reason
            self._m["lost"].inc()
            creq._fail("evicted", e)

    # -- rolling restart ------------------------------------------------
    def rolling_restart(self, grace=30.0):
        """Cycle every replica through drain -> replace, one at a time,
        with the router live the whole way: a draining replica takes no
        new routes, its backlog re-routes to its peers, its in-flight
        requests finish (or expire typed) inside ``grace``, then a
        fresh engine rejoins membership before the next replica starts.
        Returns per-replica drain stats."""
        results = {}
        for rid in list(self.replicas()):
            rep = self._replicas.get(rid)
            if rep is None or self._restart_state(rid).quarantined:
                continue        # the breaker owns quarantined replicas
            # wait out a monitor-side rebuild of this replica (it ends
            # with a fresh engine anyway — but the restart must still
            # cycle it deliberately, so claim rather than skip)
            claimed = self._claim(rid)
            t0 = time.monotonic()
            while not claimed and time.monotonic() - t0 < grace:
                time.sleep(0.02)
                claimed = self._claim(rid)
            if not claimed:
                continue            # could not get exclusive access
            rep = self._replicas.get(rid, rep)
            try:
                with _span("cluster.rolling_restart", replica=rid):
                    rep.begin_drain()
                    for creq in rep.take_backlog():
                        if creq.done:
                            continue
                        try:
                            self._route(creq, exclude=(rid,))
                        except AdmissionError as e:
                            creq._fail("evicted", e)
                    rep.stop_worker()
                    stats = rep.drain(grace)
                    rep.restart()
                    st = self._restart_state(rid)
                    st.down = False     # a deliberate cycle is not a
                    st.restart_at = None    # death the supervisor owns
                    # hold the next cycle until THIS replacement can
                    # take routes again — an in-process restart is
                    # ready immediately, but a subprocess replacement
                    # pays import + (cached) compile first, and cycling
                    # on without it would walk the tier down to zero
                    # routable capacity
                    t_up = time.monotonic()
                    while not rep.ready() \
                            and time.monotonic() - t_up < grace:
                        time.sleep(0.05)
                    results[rid] = stats
                    self._m["restarts"].inc()
            finally:
                with self._lock:
                    self._maintenance.discard(rid)
        return results

    # -- shutdown -------------------------------------------------------
    def drain(self, grace=30.0):
        """Drain the whole tier (no restarts): stop routing, drain each
        replica, leave admission closed."""
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5.0)
        stats = {}
        for rid, rep in self.replicas().items():
            rep.begin_drain()
            for creq in rep.take_backlog():
                if not creq.done:
                    creq._fail("evicted", AdmissionError(
                        "cluster draining", live=0, max_batch=0,
                        free_pages=0, num_pages=0, retries=0))
            rep.stop_worker()
            stats[rid] = rep.drain(grace)
        return stats

    def stop(self):
        """Stop monitor + replicas (graceful; engines closed / worker
        processes clean-exited) and the rpc endpoint."""
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5.0)
        for rep in self.replicas().values():
            rep.stop()
        if self._endpoint is not None:
            self._endpoint.stop()
            self._endpoint = None

    def stats(self):
        out = {}
        for rid, rep in self.replicas().items():
            d = rep.load()
            d["alive"] = rep.alive()
            d["ready"] = rep.ready()
            e = rep.engine
            if e is not None and e.prefix is not None:
                d["prefix"] = e.prefix.stats()
            out[rid] = d
        return out
