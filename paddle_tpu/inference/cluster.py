"""Multi-replica serving tier: load-aware routing, membership, rolling
restart.

One :class:`~paddle_tpu.inference.serving.LlamaServingEngine` is a
single continuous batch on a single chip; this module is the layer that
makes N of them look like one service (ROADMAP item 2 — the
millions-of-users story, cf. the Gemma-on-TPU serving comparison in
PAPERS.md):

- :class:`EngineReplica` — one engine driven by its own worker thread,
  registered in the shared :class:`~paddle_tpu.distributed.watchdog
  .FileStore` membership store with TTL heartbeats (the elastic
  launcher's liveness mechanism, reused for serving). A replica that
  dies — fault-injected via the ``replica.dead`` point, or a simulated
  SIGKILL via :meth:`EngineReplica.kill` — simply stops heartbeating
  and ages out of membership.
- :class:`ClusterRequest` — the router-level request handle. It
  survives its replica: if the replica dies before the request
  finishes, the router re-submits it elsewhere (bounded by
  ``failover_budget``), and a cluster-level ``deadline`` keeps ticking
  across attempts — a request always ends terminal (completed or a
  typed error), never lost.
- :class:`ServingCluster` — the routing frontend. ``submit()`` picks
  the least-loaded ready replica from the engines' own queue-depth /
  KV-page-utilization gauges; when every replica sheds, the typed
  :class:`~paddle_tpu.inference.serving.AdmissionError` propagates with
  the smallest ``retry_after`` hint (backpressure, not a drop). A
  monitor thread watches membership through an
  :class:`~paddle_tpu.distributed.watchdog.ElasticManager`, fails over
  the requests of dead replicas and (``auto_replace=True``) rebuilds
  them. :meth:`ServingCluster.rolling_restart` cycles replicas through
  ``drain()`` one at a time — the router stops routing to a draining
  replica, its backlog is re-routed, in-flight requests finish or
  expire typed inside the grace window, and a fresh engine takes over.

Each replica's engine keeps its own shared-prefix KV cache, so a hot
system prompt is prefilled once per replica. In tests replicas are
in-process engines; a subprocess deployment drives the same surface
(the worker loop maps 1:1 onto a process main loop with the store on a
shared filesystem).

Fault points: ``router.route`` fires per routing decision and
``replica.dead`` fires per worker-loop tick, so a ``PADDLE_TPU_FAULTS``
plan can inject routing errors or kill replica N at tick K
deterministically in CI.
"""

from __future__ import annotations

import collections
import tempfile
import threading
import time

import numpy as np

from ..distributed.watchdog import ElasticManager, FileStore
from ..observability import metrics as _om
from ..observability.trace import span as _span
from ..testing import faults as _faults
from .serving import (AdmissionError, DeadlineExceeded,
                      LlamaServingEngine, Request)

__all__ = ["ClusterRequest", "EngineReplica", "ServingCluster",
           "ReplicaLostError"]


class ReplicaLostError(RuntimeError):
    """Terminal cluster-level failure: the request's replica died and
    its failover budget is spent. Carries enough to alert on."""

    def __init__(self, msg, replica_id=None, failovers=0):
        super().__init__(msg)
        self.replica_id = replica_id
        self.failovers = failovers


def _router_metrics():
    return {
        "routed": _om.counter(
            "router_requests_routed_total",
            "requests routed to a replica", labelnames=("replica",)),
        "backpressure": _om.counter(
            "router_backpressure_total",
            "submissions rejected because every replica shed"),
        "failover": _om.counter(
            "router_failovers_total",
            "requests re-submitted after their replica died"),
        "lost": _om.counter(
            "router_requests_lost_total",
            "requests that exhausted their failover budget"),
        "replaced": _om.counter(
            "router_replicas_replaced_total",
            "dead replicas rebuilt by the monitor"),
        "restarts": _om.counter(
            "router_rolling_restarts_total",
            "replicas cycled through a rolling restart"),
        "ready": _om.gauge(
            "router_replicas_ready",
            "replicas currently routable (alive, registered, not "
            "draining)"),
    }


class ClusterRequest:
    """One generation request at the routing tier.

    Holds the *intent* (prompt, budgets, priority); each submission to
    a replica materializes a fresh engine-level
    :class:`~paddle_tpu.inference.serving.Request` so a failover
    restarts cleanly. ``deadline`` is a cluster-level wall-clock TTL
    measured from the first ``submit()`` — it keeps ticking across
    failovers, so a request bouncing between dying replicas still ends
    in a typed :class:`DeadlineExceeded` rather than living forever.
    """

    def __init__(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
                 deadline=None, token_budget=None, priority=0,
                 retry_budget=1, failover_budget=3):
        self.prompt_ids = np.asarray(prompt_ids, np.int64).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.deadline = None if deadline is None else float(deadline)
        self.token_budget = token_budget
        self.priority = int(priority)
        self.retry_budget = int(retry_budget)
        self.failover_budget = int(failover_budget)
        self.failovers = 0
        self.request: Request | None = None   # current engine attempt
        self.replica_id = None
        self.status = "pending"
        self.error = None
        self.output_ids: list[int] = []
        self._t_submit = None
        self._finished = threading.Event()
        self._lock = threading.Lock()
        # constructing the engine request up front validates the args
        # at submit() time, not on a replica's worker thread
        Request(self.prompt_ids, self.max_new_tokens, eos_token_id,
                deadline, token_budget, priority, retry_budget)

    # ------------------------------------------------------------------
    @property
    def done(self):
        return self._finished.is_set()

    def wait(self, timeout=None):
        """Block until terminal; True if it finished in time."""
        return self._finished.wait(timeout)

    def result(self, timeout=None):
        """Output ids, or raises the typed terminal error (or
        :class:`TimeoutError` if still running past ``timeout``)."""
        if not self._finished.wait(timeout):
            raise TimeoutError(
                f"request not finished within {timeout}s "
                f"(status={self.status})")
        if self.error is not None:
            raise self.error
        return self.output_ids

    # -- replica-side hooks --------------------------------------------
    def _remaining_ttl(self, now=None):
        if self.deadline is None:
            return None
        now = time.perf_counter() if now is None else now
        return self.deadline - (now - self._t_submit)

    def _new_attempt(self, replica_id):
        """Engine-level request for one submission attempt, or None if
        the cluster deadline already lapsed (the request is finished
        typed here — never silently dropped)."""
        with self._lock:
            if self._finished.is_set():
                return None
            ttl = self._remaining_ttl()
            if ttl is not None and ttl <= 0:
                self._finish_locked(
                    "deadline_exceeded",
                    DeadlineExceeded(
                        f"cluster deadline of {self.deadline}s lapsed "
                        f"before the request reached a live replica",
                        tokens_emitted=len(self.output_ids),
                        reason="cluster deadline"))
                return None
            r = Request(self.prompt_ids, self.max_new_tokens,
                        self.eos_token_id, ttl, self.token_budget,
                        self.priority, self.retry_budget)
            self.request = r
            self.replica_id = replica_id
            self.status = "live"
            return r

    def _finish_locked(self, status, error):
        self.status = status
        self.error = error
        self._finished.set()

    def _finish_from(self, req):
        """Adopt an engine request's terminal state."""
        with self._lock:
            if self._finished.is_set():
                return
            self.output_ids = list(req.output_ids)
            self._finish_locked(req.status, req.error)

    def _fail(self, status, error):
        with self._lock:
            if not self._finished.is_set():
                self._finish_locked(status, error)

    def cancel(self):
        """Best-effort cancel: marks the handle terminal and cancels
        the current engine attempt if one is live."""
        with self._lock:
            req = self.request
            if not self._finished.is_set():
                self._finish_locked("cancelled", None)
        return req


class EngineReplica:
    """One serving replica: an engine plus the worker thread that
    drives it (admission from a backlog queue, decode steps, completion
    reaping, membership heartbeats). The worker thread is the ONLY
    thread that touches the engine's dispatch path; the router merely
    appends to the backlog, so replica-internal state never races.

    ``kill()`` simulates a SIGKILL: the worker stops mid-loop without
    draining or deregistering — exactly what a preempted host looks
    like to the membership store (its stamp ages out after ``ttl``).
    """

    def __init__(self, replica_id, engine_factory, store=None,
                 ttl=None, heartbeat_interval=None, max_backlog=None,
                 idle_sleep=0.002, burst=None):
        self.replica_id = str(replica_id)
        self._factory = engine_factory
        self.engine: LlamaServingEngine | None = None
        self.store = store
        self.ttl = ttl
        self._hb_interval = heartbeat_interval or (
            ttl / 3.0 if ttl else 0.5)
        self.max_backlog = max_backlog
        self.idle_sleep = float(idle_sleep)
        self.burst = burst                  # decode chunk per loop tick
        self._backlog: collections.deque[ClusterRequest] = \
            collections.deque()
        self._tracked: dict[Request, ClusterRequest] = {}
        # requests popped from the backlog but not yet admitted: the
        # worker can die (fault injection) mid-admission, and a
        # request in that window must still be found by failover
        self._pending_admit: list[ClusterRequest] = []
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread = None
        self._hb_thread = None
        self._draining = False
        self._dead = False
        self._death_reason = None
        self._last_beat = 0.0
        self._ticks = 0
        self._m_dead = _om.counter(
            "replica_deaths_total",
            "replica worker loops that died uncleanly")

    # ------------------------------------------------------------------
    def start(self):
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._draining = False
            self._dead = False
            self._death_reason = None
        if self.engine is None:
            self.engine = self._factory()
        if self.max_backlog is None:
            self.max_backlog = self.engine.max_batch * 4
        self._register()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"replica-{self.replica_id}")
        self._thread.start()
        if self.store is not None:
            # heartbeats ride a sidecar thread: a worker mid-compile
            # (multi-second XLA trace) must not age out of membership;
            # a DEAD worker stops the sidecar, so death still surfaces
            # as TTL expiry
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True,
                name=f"replica-{self.replica_id}-hb")
            self._hb_thread.start()
        return self

    def _register(self):
        if self.store is not None:
            self.store.register(self.replica_id)
            self._last_beat = time.monotonic()

    def _hb_loop(self):
        while not self._stop.wait(self._hb_interval):
            if self._dead or not self.alive():
                return      # a crashed host never says goodbye
            try:
                self.store.heartbeat(self.replica_id)
            except OSError:
                pass

    # -- router-facing surface -----------------------------------------
    def alive(self):
        t = self._thread
        return (not self._dead) and t is not None and t.is_alive()

    def ready(self):
        return (self.alive() and not self._draining
                and self.engine is not None and self.engine.is_ready())

    def load(self):
        """Load score from the engine's own admission gauges: live
        batch occupancy + backlog depth (normalized to max_batch) +
        KV-page utilization. Lower is better."""
        e = self.engine
        with self._lock:
            backlog = len(self._backlog)
        if e is None:
            return {"score": float("inf"), "live": 0, "backlog": backlog,
                    "kv_util": 1.0}
        live = len(e._live)
        kv_util = 1.0 - e.alloc.free_pages / e.alloc.num_pages
        score = (live + backlog) / max(1, e.max_batch) + kv_util
        return {"score": score, "live": live, "backlog": backlog,
                "kv_util": kv_util}

    def submit(self, creq):
        """Queue a request for this replica's worker. Raises a typed
        :class:`AdmissionError` (with the engine's ``retry_after``
        estimate) when the replica is not accepting or its backlog is
        full — the router's cue to pick another replica."""
        e = self.engine
        with self._lock:
            if self._dead or self._draining or e is None:
                raise AdmissionError(
                    f"replica {self.replica_id} not accepting "
                    f"({'dead' if self._dead else 'draining'})",
                    live=0 if e is None else len(e._live),
                    max_batch=0 if e is None else e.max_batch,
                    free_pages=0 if e is None else e.alloc.free_pages,
                    num_pages=0 if e is None else e.alloc.num_pages,
                    retries=0)
            if len(self._backlog) >= self.max_backlog:
                raise AdmissionError(
                    f"replica {self.replica_id} backlog full",
                    live=len(e._live), max_batch=e.max_batch,
                    free_pages=e.alloc.free_pages,
                    num_pages=e.alloc.num_pages, retries=0,
                    retry_after=e._retry_after())
            self._backlog.append(creq)

    # -- worker loop ----------------------------------------------------
    def _run(self):
        try:
            while not self._stop.is_set():
                # deterministic kill switch for CI plans: a rule at
                # replica.dead (action raise/hang) takes this worker
                # down as a crash, not a drain
                _faults.fire("replica.dead", step=self._ticks,
                             path=self.replica_id)
                self._ticks += 1
                self._admit_from_backlog()
                served = 0
                e = self.engine
                if e is not None \
                        and any(not r.done for r in e._live.values()):
                    served = e.decode_many(self.burst) if self.burst \
                        else e.step()
                self._reap_completed()
                with self._lock:
                    idle = not served and not self._backlog
                if idle:
                    time.sleep(self.idle_sleep)
        except BaseException as exc:     # noqa: BLE001 — death IS the event
            with self._lock:
                self._dead = True
                self._death_reason = exc
            self._m_dead.inc()
            # no deregister: a crashed host never says goodbye — the
            # membership TTL is what detects it

    def _admit_from_backlog(self):
        e = self.engine
        admitted = []
        while True:
            with self._lock:
                if (self._draining or not self._backlog
                        or len(e._live) >= e.max_batch):
                    break
                creq = self._backlog.popleft()
                self._pending_admit.append(creq)
            # removal from _pending_admit happens ONLY on the normal
            # paths below: a crash anywhere in between leaves the
            # request discoverable by take_unfinished()
            if creq.done:
                self._unpend(creq)
                continue
            req = creq._new_attempt(self.replica_id)
            if req is None:
                self._unpend(creq)
                continue        # finished typed (cluster deadline)
            try:
                e._admit(req)
            except AdmissionError:
                with self._lock:
                    self._backlog.appendleft(creq)
                    self._pending_admit.remove(creq)
                break
            except ValueError as exc:
                # never-fitting prompt: typed terminal, not a retry
                creq._fail("evicted", exc)
                self._unpend(creq)
                continue
            with self._lock:
                self._tracked[req] = creq
                self._pending_admit.remove(creq)
            admitted.append(req)
        if admitted:
            e._prefill_wave(admitted)

    def _unpend(self, creq):
        with self._lock:
            if creq in self._pending_admit:
                self._pending_admit.remove(creq)

    def _reap_completed(self):
        with self._lock:
            finished = [(r, c) for r, c in self._tracked.items()
                        if r.done]
            for r, _ in finished:
                del self._tracked[r]
        for r, c in finished:
            c._finish_from(r)

    # -- lifecycle ------------------------------------------------------
    def begin_drain(self):
        """Stop accepting routes; the worker finishes what's admitted."""
        with self._lock:
            self._draining = True

    def take_backlog(self):
        """Pull every queued-but-unadmitted request (the router
        re-routes them before a drain or after a death)."""
        with self._lock:
            out = list(self._backlog)
            self._backlog.clear()
        return out

    def take_unfinished(self):
        """Backlog + mid-admission + tracked in-flight requests that
        are not terminal — the failover set after this replica died."""
        with self._lock:
            out = [c for c in self._backlog if not c.done]
            self._backlog.clear()
            out += [c for c in self._pending_admit if not c.done]
            self._pending_admit.clear()
            out += [c for r, c in self._tracked.items() if not c.done]
            self._tracked.clear()
        return out

    def stop_worker(self, timeout=10.0):
        """Ask the worker loop to exit and join it (the engine itself
        stays usable — rolling restart drains it next)."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout)

    def drain(self, grace=30.0):
        """Drain the engine (worker must be stopped first so only one
        thread drives dispatches), then reap terminal requests."""
        stats = self.engine.drain(grace) if self.engine is not None \
            else {"seconds": 0.0, "completed": 0, "expired": 0}
        self._reap_completed()
        return stats

    def restart(self):
        """Replace the engine via the factory and rejoin the cluster —
        the second half of a rolling restart (or a kill-and-replace).
        Unfinished requests are NOT carried over; the caller fails
        them over first."""
        old = self.engine
        if old is not None:
            try:
                old.close()
            except Exception:
                pass
        self.engine = self._factory()
        with self._lock:
            self._tracked.clear()
            self._backlog.clear()
            self._pending_admit.clear()
        return self.start()

    def kill(self):
        """Simulate a SIGKILL: stop the worker abruptly, no drain, no
        deregistration — detected only by membership TTL expiry (or
        the monitor noticing the dead thread)."""
        with self._lock:
            self._dead = True
            self._death_reason = RuntimeError("killed")
        self._m_dead.inc()
        self._stop.set()

    def stop(self, timeout=10.0):
        """Clean shutdown: stop the worker and leave membership."""
        self.stop_worker(timeout)
        if self.store is not None:
            try:
                self.store.deregister(self.replica_id)
            except OSError:
                pass
        if self.engine is not None:
            self.engine.close()


class ServingCluster:
    """Routing frontend over N :class:`EngineReplica` instances.

    Args:
        engine_factory: zero-arg callable building a fresh
            :class:`LlamaServingEngine` (called per replica and per
            restart/replacement).
        num_replicas: replica count at start().
        store_path: membership directory (a shared filesystem in a
            real deployment); default: a private temp dir.
        ttl: membership TTL in seconds — a replica whose heartbeat is
            older ages out and is treated as dead.
        monitor_interval: seconds between membership sweeps.
        auto_replace: rebuild dead replicas automatically
            (kill-and-replace).
        failover_budget: default per-request failover budget.
    """

    def __init__(self, engine_factory, num_replicas=2, store_path=None,
                 ttl=2.0, monitor_interval=0.05, auto_replace=True,
                 failover_budget=3, max_backlog=None, burst=None):
        self._factory = engine_factory
        self.num_replicas = int(num_replicas)
        self.ttl = ttl
        self.store = FileStore(
            store_path or tempfile.mkdtemp(prefix="paddle_tpu_cluster_"),
            ttl=ttl)
        self.monitor_interval = float(monitor_interval)
        self.auto_replace = auto_replace
        self.failover_budget = int(failover_budget)
        self.max_backlog = max_backlog
        self.burst = burst
        self._replicas: dict[str, EngineReplica] = {}
        self._maintenance: set[str] = set()   # ids mid-rolling-restart
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._monitor_thread = None
        self._elastic = None
        self._m = _router_metrics()
        self._route_count = 0
        self._started = False

    # ------------------------------------------------------------------
    def start(self):
        with self._lock:
            if self._started:
                return self
            self._started = True
        for i in range(self.num_replicas):
            rid = f"replica-{i}"
            rep = EngineReplica(rid, self._factory, store=self.store,
                                ttl=self.ttl,
                                max_backlog=self.max_backlog,
                                burst=self.burst)
            rep.start()
            self._replicas[rid] = rep
        self._elastic = ElasticManager(self.store, "router",
                                       self.num_replicas)
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True, name="cluster-monitor")
        self._monitor_thread.start()
        return self

    def replicas(self):
        with self._lock:
            return dict(self._replicas)

    def ready(self):
        """Cluster readiness: at least one routable replica (wire to
        ``start_http_server(ready=cluster.ready)`` for ``/readyz``)."""
        return any(r.ready() for r in self.replicas().values())

    def start_http_server(self, port=0, addr="127.0.0.1"):
        """Metrics + /healthz + /readyz endpoint for the whole tier."""
        from ..observability.export import start_http_server
        return start_http_server(port=port, addr=addr, ready=self.ready)

    # -- routing --------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
               deadline=None, token_budget=None, priority=0,
               retry_budget=1, failover_budget=None):
        """Route one request to the least-loaded ready replica.
        Returns a :class:`ClusterRequest`; raises a typed
        :class:`AdmissionError` carrying the smallest ``retry_after``
        across replicas when the whole tier is at capacity."""
        creq = ClusterRequest(
            prompt_ids, max_new_tokens, eos_token_id, deadline,
            token_budget, priority, retry_budget,
            self.failover_budget if failover_budget is None
            else failover_budget)
        creq._t_submit = time.perf_counter()
        self._route(creq)
        return creq

    def _routable(self, exclude=()):
        live_hosts = set(self.store.hosts())
        with self._lock:
            reps = [r for rid, r in self._replicas.items()
                    if rid not in exclude
                    and rid not in self._maintenance
                    and r.ready() and rid in live_hosts]
        return reps

    def _route(self, creq, exclude=()):
        with self._lock:
            step = self._route_count
            self._route_count += 1
        # deterministic routing-error injection for CI plans
        _faults.fire("router.route", step=step)
        candidates = sorted(self._routable(exclude),
                            key=lambda r: r.load()["score"])
        retry_after = None
        stats = {"live": 0, "max_batch": 0, "free_pages": 0,
                 "num_pages": 0}
        for rep in candidates:
            try:
                with _span("cluster.route", replica=rep.replica_id):
                    rep.submit(creq)
            except AdmissionError as e:
                if e.retry_after is not None:
                    retry_after = e.retry_after if retry_after is None \
                        else min(retry_after, e.retry_after)
                for k in stats:
                    stats[k] += getattr(e, k, 0)
                continue
            creq.replica_id = rep.replica_id
            self._m["routed"].labels(rep.replica_id).inc()
            return rep

        self._m["backpressure"].inc()
        raise AdmissionError(
            f"no replica accepted the request "
            f"({len(candidates)} routable of {len(self._replicas)})",
            retries=0, retry_after=retry_after, **stats)

    def cancel(self, creq):
        """Cancel a cluster request: the handle turns terminal and the
        current engine attempt (if any) is cancelled on its replica."""
        req = creq.cancel()
        rep = self._replicas.get(creq.replica_id)
        if req is not None and rep is not None \
                and rep.engine is not None:
            rep.engine.cancel(req)

    # -- membership monitor --------------------------------------------
    def _monitor(self):
        while not self._stop.wait(self.monitor_interval):
            try:
                self._sweep()
            except Exception:
                # the monitor must survive transient store errors; the
                # next sweep retries
                pass

    def _claim(self, rid, rep=None):
        """Atomically claim a replica for exclusive maintenance (the
        monitor's death handling vs rolling_restart — whoever claims
        first proceeds; the other skips or waits). Returns False when
        already claimed, or when ``rep`` no longer IS the registered
        replica (a stale snapshot)."""
        with self._lock:
            if rid in self._maintenance:
                return False
            if rep is not None and self._replicas.get(rid) is not rep:
                return False
            self._maintenance.add(rid)
            return True

    def _release_claim(self, rid):
        with self._lock:
            self._maintenance.discard(rid)

    def _sweep(self):
        if self._elastic is not None:
            self._elastic.watch_once()      # live-host gauge + events
        live_hosts = set(self.store.hosts())
        with self._lock:
            reps = [(rid, r) for rid, r in self._replicas.items()
                    if rid not in self._maintenance]
        ready = 0
        for rid, rep in reps:
            dead = (not rep.alive()) or (rid not in live_hosts
                                         and not rep._draining)
            if dead:
                # claim BEFORE touching the replica: rolling_restart
                # may have started on it since the snapshot (its
                # stop_worker looks like a death), and two rebuilders
                # racing one replica would tear its engine
                if not self._claim(rid, rep):
                    continue
                try:
                    self._handle_death(rid, rep)
                finally:
                    self._release_claim(rid)
            elif rep.ready():
                ready += 1
        self._m["ready"].set(ready)

    def _handle_death(self, rid, rep):
        """Fail over a dead replica's requests; optionally rebuild it.
        Caller holds the maintenance claim for ``rid``."""
        orphans = rep.take_unfinished()
        rep.stop_worker(timeout=1.0)
        for creq in orphans:
            self._failover(creq, dead_rid=rid)
        if self.auto_replace:
            rep.restart()
            self._m["replaced"].inc()

    def _failover(self, creq, dead_rid):
        if creq.done:
            return
        creq.failovers += 1
        if creq.failovers > creq.failover_budget:
            self._m["lost"].inc()
            creq._fail("evicted", ReplicaLostError(
                f"replica {dead_rid} died and the failover budget "
                f"({creq.failover_budget}) is exhausted",
                replica_id=dead_rid, failovers=creq.failovers))
            return
        self._m["failover"].inc()
        try:
            self._route(creq, exclude=(dead_rid,))
        except AdmissionError as e:
            # the tier is saturated right now — typed terminal rather
            # than a silent drop; callers see the backpressure reason
            self._m["lost"].inc()
            creq._fail("evicted", e)

    # -- rolling restart ------------------------------------------------
    def rolling_restart(self, grace=30.0):
        """Cycle every replica through drain -> replace, one at a time,
        with the router live the whole way: a draining replica takes no
        new routes, its backlog re-routes to its peers, its in-flight
        requests finish (or expire typed) inside ``grace``, then a
        fresh engine rejoins membership before the next replica starts.
        Returns per-replica drain stats."""
        results = {}
        for rid in list(self.replicas()):
            rep = self._replicas.get(rid)
            if rep is None:
                continue
            # wait out a monitor-side rebuild of this replica (it ends
            # with a fresh engine anyway — but the restart must still
            # cycle it deliberately, so claim rather than skip)
            claimed = self._claim(rid)
            t0 = time.monotonic()
            while not claimed and time.monotonic() - t0 < grace:
                time.sleep(0.02)
                claimed = self._claim(rid)
            if not claimed:
                continue            # could not get exclusive access
            rep = self._replicas.get(rid, rep)
            try:
                with _span("cluster.rolling_restart", replica=rid):
                    rep.begin_drain()
                    for creq in rep.take_backlog():
                        if creq.done:
                            continue
                        try:
                            self._route(creq, exclude=(rid,))
                        except AdmissionError as e:
                            creq._fail("evicted", e)
                    rep.stop_worker()
                    stats = rep.drain(grace)
                    rep.restart()
                    results[rid] = stats
                    self._m["restarts"].inc()
            finally:
                with self._lock:
                    self._maintenance.discard(rid)
        return results

    # -- shutdown -------------------------------------------------------
    def drain(self, grace=30.0):
        """Drain the whole tier (no restarts): stop routing, drain each
        replica, leave admission closed."""
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5.0)
        stats = {}
        for rid, rep in self.replicas().items():
            rep.begin_drain()
            for creq in rep.take_backlog():
                if not creq.done:
                    creq._fail("evicted", AdmissionError(
                        "cluster draining", live=0, max_batch=0,
                        free_pages=0, num_pages=0, retries=0))
            rep.stop_worker()
            stats[rid] = rep.drain(grace)
        return stats

    def stop(self):
        """Stop monitor + replicas (graceful; engines closed)."""
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5.0)
        for rep in self.replicas().values():
            rep.stop()

    def stats(self):
        out = {}
        for rid, rep in self.replicas().items():
            d = rep.load()
            d["alive"] = rep.alive()
            d["ready"] = rep.ready()
            e = rep.engine
            if e is not None and e.prefix is not None:
                d["prefix"] = e.prefix.stats()
            out[rid] = d
        return out
