"""``paddle.inference`` — the deployment predictor API.

Reference: `paddle/fluid/inference/api/analysis_predictor.h:100`
(``AnalysisPredictor``: load model -> optimize -> zero-copy run) and
`paddle_analysis_config.h` (``Config``). TPU-native: the "optimized
program" is the exported StableHLO from ``jit.save`` — XLA re-optimizes
it for the serving chip at load; handles wrap device arrays.
"""

from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor

from .paged_cache import PagedKVCache  # noqa: F401

__all__ = ["Config", "Predictor", "create_predictor", "PagedKVCache"]


class Config:
    """Reference AnalysisConfig. ``prog_file`` is the ``jit.save`` path
    prefix (the ``.pdmodel``/``.pdiparams`` pair)."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._prefix = prog_file
        self._device = None

    def set_prog_file(self, path):
        self._prefix = path

    def prog_file(self):
        return self._prefix

    # device knobs are accepted for API parity; placement is jax's
    def enable_use_gpu(self, *a, **k):
        self._device = "gpu"

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self, *a, **k):
        pass

    def switch_ir_optim(self, *a, **k):
        pass


class _Handle:
    """Zero-copy-style input/output handle (reference ZeroCopyTensor)."""

    def __init__(self, store, name):
        self._store = store
        self._name = name

    def copy_from_cpu(self, arr):
        self._store[self._name] = np.ascontiguousarray(arr)

    def reshape(self, shape):
        pass  # shapes come from the array itself

    def copy_to_cpu(self):
        return np.asarray(self._store[self._name])

    def shape(self):
        return list(np.asarray(self._store[self._name]).shape)


class Predictor:
    def __init__(self, config):
        from ..jit import load as jit_load
        if not config.prog_file():
            raise ValueError("Config needs the jit.save path prefix")
        self._layer = jit_load(config.prog_file())
        n_in = len(self._layer._meta.get("inputs", []))
        self._in_names = [f"input_{i}" for i in range(n_in)]
        self._inputs = {}
        self._outputs = {}
        self._out_names = []

    def get_input_names(self):
        return list(self._in_names)

    def get_input_handle(self, name):
        return _Handle(self._inputs, name)

    def run(self, inputs=None):
        if inputs is not None:                   # direct-call convenience
            arrays = [np.asarray(a) for a in inputs]
        else:
            arrays = [self._inputs[n] for n in self._in_names]
        out = self._layer(*arrays)
        outs = out if isinstance(out, tuple) else (out,)
        self._out_names = [f"output_{i}" for i in range(len(outs))]
        for n, o in zip(self._out_names, outs):
            self._outputs[n] = o.numpy()
        return [self._outputs[n] for n in self._out_names]

    def get_output_names(self):
        return list(self._out_names)

    def get_output_handle(self, name):
        return _Handle(self._outputs, name)


def create_predictor(config):
    return Predictor(config)


_LAZY = {
    # the serving/cluster stack imports the model zoo — load on demand
    "LlamaServingEngine": "serving", "Request": "serving",
    "AdmissionError": "serving", "DeadlineExceeded": "serving",
    "ServingCluster": "cluster", "EngineReplica": "cluster",
    "SubprocessReplica": "cluster", "ReplicaLostError": "cluster",
    "StaleEpochError": "cluster",
    "ClusterRequest": "cluster", "PrefixCache": "prefix_cache",
    "PageAllocator": "paged_cache", "replica_main": "replica_worker",
    "NGramDrafter": "speculative",
    # the real-traffic front door (ROADMAP item 4)
    "SamplingParams": "sampling", "ServingFrontend": "frontend",
    "ByteTokenizer": "frontend", "QosGate": "qos", "Tenant": "qos",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
