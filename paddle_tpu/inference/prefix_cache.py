"""Shared-prefix KV-cache: content-addressed page reuse across requests.

The millions-of-users serving pattern (ROADMAP item 2, cf. the
Gemma-on-TPU serving study in PAPERS.md) is thousands of requests that
share a long page-aligned prefix — a system prompt, a few-shot header —
followed by a short unique suffix. Without reuse every request
re-prefills the whole prompt; with it the prefix is prefilled ONCE per
replica and later requests admit directly against the cached pages,
paying only the suffix.

Design:

- **Content addressing by chain hash.** Page ``i`` of a prompt is keyed
  by ``H(key_{i-1} || tokens_of_page_i)`` — a page's key commits to the
  entire token prefix before it, so two prompts share a cached page
  only when every token up to and including that page is identical.
- **Page granularity.** Only FULL pages are cached (a partial page's
  K/V layout depends on tokens that haven't arrived), and a match never
  covers the final prompt token — the engine needs at least one real
  token to run through the model to produce the first-output logits.
  Because matches are therefore page-aligned, a sequence admitted on
  cached pages writes its suffix K/V into pages it exclusively owns;
  the shared pages stay immutable (and :meth:`PageAllocator
  .ensure_writable` copy-on-writes as a backstop).
- **Refcounted pinning.** The cache holds one allocator reference per
  cached page (``PageAllocator`` refcounts), so pages survive the
  sequence that prefilled them and are freed only when evicted here
  AND unreferenced by every live sequence.
- **LRU eviction, leaves first.** Evicting a middle page would strand
  its descendants unreachable (their keys chain through it), so only
  chain tails are eviction candidates; under pool pressure the serving
  engine asks the cache to give pages back before walking its
  degradation ladder.
"""

from __future__ import annotations

import hashlib
import threading

import numpy as np

__all__ = ["PrefixCache", "chain_keys", "cacheable_pages"]

_SEED = b"paddle_tpu.prefix"


def cacheable_pages(n_tokens, page_size):
    """Full pages of an ``n_tokens`` prompt eligible for caching —
    never covering the final token (the engine must run at least one
    real token through the model to get first-output logits)."""
    full = n_tokens // page_size
    if full and full * page_size >= n_tokens:
        full -= 1
    return full


def chain_keys(prompt_ids, page_size, n_pages=None, limit=None):
    """Chain-hash keys for a prompt's full page-aligned prefix pages.

    Page ``i`` is keyed by ``H(key_{i-1} || tokens_of_page_i)`` — the
    content address :class:`PrefixCache` stores pages under. Module-
    level so the cluster router can score prefix affinity with the
    SAME hashing a replica's cache uses, without holding any cache.
    ``limit`` caps the number of keys (hashing cost bound on the
    routing hot path)."""
    ids = np.asarray(prompt_ids, np.int64).reshape(-1)
    if n_pages is None:
        n_pages = cacheable_pages(len(ids), page_size)
    if limit is not None:
        n_pages = min(n_pages, int(limit))
    keys, prev = [], _SEED
    for i in range(n_pages):
        chunk = ids[i * page_size:(i + 1) * page_size]
        prev = hashlib.sha1(prev + chunk.tobytes()).digest()
        keys.append(prev)
    return keys


class _Entry:
    __slots__ = ("page", "key", "parent", "children", "last_used",
                 "depth")

    def __init__(self, page, key, parent, depth):
        self.page = page
        self.key = key
        self.parent = parent        # parent entry key, or None
        self.children = 0           # cached entries chaining through us
        self.last_used = 0
        self.depth = depth


class PrefixCache:
    """Per-engine (per-replica) shared-prefix page cache.

    Args:
        alloc: the engine's :class:`PageAllocator` (pages cached here
            are pinned with one allocator reference each).
        page_size: tokens per page; defaults to the allocator's.
        max_pages: optional cap on cached pages; inserting past it
            evicts LRU tails first.
    """

    def __init__(self, alloc, page_size=None, max_pages=None):
        self.alloc = alloc
        self.page_size = int(page_size or alloc.page_size)
        self.max_pages = max_pages
        self._entries: dict[bytes, _Entry] = {}
        # eviction candidates (entries no cached child chains through):
        # maintained incrementally so an eviction scans leaves — the
        # number of distinct chains — not every cached page
        self._leaves: dict[bytes, _Entry] = {}
        self._clock = 0
        self._lock = threading.RLock()
        # plain-int stats (always on); the engine layers the
        # serving_prefix_* metrics on top
        self.lookups = 0
        self.hits = 0
        self.saved_tokens = 0
        self.evictions = 0
        # demotion hook (host-DRAM KV tier): called as
        # ``demote(key, parent_key, page)`` for each page evict_pages
        # is about to drop, BEFORE its reference is released — the
        # serving engine wires it to a D2H copy into the host tier so
        # a cold system prompt survives pressure. Best-effort: any
        # exception is swallowed (the old behavior IS dropping the
        # page).
        self.demote = None

    # ------------------------------------------------------------------
    def _keys(self, prompt_ids, n_pages):
        """Chain keys for the first ``n_pages`` full pages."""
        return chain_keys(prompt_ids, self.page_size, n_pages=n_pages)

    def _cacheable_pages(self, n_tokens):
        return cacheable_pages(n_tokens, self.page_size)

    @property
    def pages(self):
        return len(self._entries)

    def hot_keys(self, n=16):
        """Hex chain keys of the ``n`` most recently used cached pages —
        the replica's advertised hot-prefix set. The cluster router
        hashes an incoming prompt with :func:`chain_keys` and scores
        replicas by overlap (prefix-affinity routing), so requests
        sharing a hot prefix land where its K/V already lives."""
        import heapq

        with self._lock:
            # nlargest, not a full sort: this runs per routable replica
            # per routing decision, and the cache can hold thousands of
            # entries
            es = heapq.nlargest(int(n), self._entries.values(),
                                key=lambda e: e.last_used)
            return [e.key.hex() for e in es]

    # ------------------------------------------------------------------
    def match(self, prompt_ids, record=True):
        """Longest cached page chain covering this prompt's prefix.

        Returns ``(pages, n_tokens)`` — the cached page ids (in prompt
        order) and the token count they cover (a multiple of
        ``page_size``, strictly less than ``len(prompt_ids)``). The
        caller passes ``pages`` to :meth:`PageAllocator.admit` as
        ``shared_pages`` (which takes the per-sequence references);
        this method takes none and only touches recency.

        ``record=False`` skips the lookup/hit/saved-token stats — for
        an admission's internal RE-match after a pressure retry, so
        one admission never counts twice."""
        n = len(np.asarray(prompt_ids).reshape(-1))
        with self._lock:
            if record:
                self.lookups += 1
            cand = self._cacheable_pages(n)
            pages = []
            for key in self._keys(prompt_ids, cand):
                e = self._entries.get(key)
                if e is None:
                    break
                self._clock += 1
                e.last_used = self._clock
                pages.append(e.page)
            if pages and record:
                self.hits += 1
                self.saved_tokens += len(pages) * self.page_size
            return pages, len(pages) * self.page_size

    def insert(self, prompt_ids, table):
        """Register a prefilled prompt's full pages for reuse.

        ``table`` is the sequence's block table (pages in prompt
        order). Every cacheable page not already present is pinned with
        one allocator reference. Present keys are left alone — the
        first writer wins, and a concurrent duplicate simply keeps its
        private pages. Returns the number of pages newly cached."""
        n = len(np.asarray(prompt_ids).reshape(-1))
        added = 0
        with self._lock:
            cand = min(self._cacheable_pages(n), len(table))
            parent = None
            for i, key in enumerate(self._keys(prompt_ids, cand)):
                e = self._entries.get(key)
                if e is None:
                    try:
                        self.alloc.incref(table[i])
                    except ValueError:
                        break       # page vanished (caller raced a release)
                    e = _Entry(table[i], key, parent, depth=i)
                    self._clock += 1
                    e.last_used = self._clock
                    self._entries[key] = e
                    self._leaves[key] = e
                    if parent is not None:
                        p = self._entries[parent]
                        p.children += 1
                        self._leaves.pop(parent, None)
                    added += 1
                parent = key
            if self.max_pages is not None:
                over = len(self._entries) - self.max_pages
                if over > 0:
                    self.evict_pages(over)
        return added

    def pin(self, key, page, parent=None, depth=0):
        """Adopt an already-allocated page under chain key ``key`` —
        the host-tier PROMOTION path: a demoted page was H2D-restored
        into ``page`` and rejoins the cache. The caller transfers ONE
        existing allocator reference (no incref here; on False the
        caller keeps its reference and should give the page back).
        ``parent`` must already be cached when given — promotion walks
        chains in order, so a dangling parent means the caller raced
        an eviction and the page is rejected. Returns True when
        adopted."""
        with self._lock:
            if key in self._entries:
                return False
            if parent is not None and parent not in self._entries:
                return False
            e = _Entry(page, key, parent, depth=depth)
            self._clock += 1
            e.last_used = self._clock
            self._entries[key] = e
            self._leaves[key] = e
            if parent is not None:
                p = self._entries[parent]
                p.children += 1
                self._leaves.pop(parent, None)
            return True

    # ------------------------------------------------------------------
    def evict_pages(self, n_pages):
        """Release up to ``n_pages`` cached pages, LRU chain-tails
        first. Returns how many pages went back to the allocator's
        free list (a page shared with a live sequence is unpinned from
        the cache but only frees once that sequence releases it)."""
        freed = 0
        with self._lock:
            for _ in range(int(n_pages)):
                if not self._leaves:
                    break
                v = min(self._leaves.values(),
                        key=lambda e: e.last_used)
                if self.demote is not None:
                    try:
                        self.demote(v.key, v.parent, v.page)
                    except Exception:
                        pass    # demotion is best-effort by contract
                del self._entries[v.key]
                del self._leaves[v.key]
                if v.parent is not None and v.parent in self._entries:
                    p = self._entries[v.parent]
                    p.children -= 1
                    if p.children == 0:
                        self._leaves[v.parent] = p
                self.evictions += 1
                if self.alloc.decref(v.page):
                    freed += 1
        return freed

    def clear(self):
        """Invalidate everything (weights reload, tokenizer change —
        any event that makes cached K/V wrong). Returns pages freed."""
        with self._lock:
            return self.evict_pages(len(self._entries))

    def stats(self):
        with self._lock:
            return {"pages": len(self._entries),
                    "lookups": self.lookups, "hits": self.hits,
                    "hit_rate": (self.hits / self.lookups
                                 if self.lookups else 0.0),
                    "saved_tokens": self.saved_tokens,
                    "evictions": self.evictions}
