"""Host-DRAM KV page tier: pause/resume for the degradation ladder.

ROADMAP item 5a. Under pool pressure the serving engine's evict rung
*destroys* work — the victim's KV pages are dropped and the request
re-prefills from token zero. The HBM-capacity study behind the paged
design (Gemma-on-TPU serving, arXiv 2605.25645) says capacity, not
FLOPs, caps concurrent sequences; this module turns "out of HBM" from
a work-destroying event into a graceful pause. The page table makes
pages the unit of migration: a victim's pages are D2H-copied (int8
pages at half the bytes; f32 scale sidecars travel with their pages,
preserving the COW/sidecar contract) into a bounded host pool, the HBM
pages return to the allocator, and the request parks in the ``paused``
lifecycle status until the requeue pump re-admits it — an H2D restore
into freshly admitted pages, after which the resumed request's
remaining tokens are bitwise what an uninterrupted run produces.

Robustness is the headline contract:

- every failure is TYPED (:class:`TierError` subclasses) and the
  serving engine degrades to the OLD behavior — a failed export falls
  through to the evict rung, a failed/torn restore to the
  evict→requeue path (never a wedge, never a leak);
- restore data is CRC-checked per page (the checkpoint checksum
  discipline): CRCs commit to the source bytes at export, so a host
  copy corrupted anywhere between D2H and H2D is detected and
  re-prefilled, never decoded into garbage;
- accounting is leak-proof: ``kv_tier_pages`` / ``kv_tier_bytes``
  return to baseline when parked requests resume, cancel, expire, or
  drain.

Fault points ``tier.d2h`` / ``tier.h2d`` (:mod:`paddle_tpu.testing
.faults`, via :func:`~paddle_tpu.testing.faults.fire_copy`) make every
path reproducibly testable: ``sleep`` = a slow copy, ``raise`` = a
failed copy, ``bitflip`` = a torn copy (this module flips one byte of
the in-flight host buffer — no file involved — so the CRC check must
catch it). Sequence copies fire with ``path="seq"`` and demoted
prefix-cache pages with ``path="prefix"``, so one plan can scope chaos
to either flow.

Restores ride :class:`~paddle_tpu.io.token_feed.DevicePrefetcher`-style
async staging: a daemon thread ``jax.device_put``\\ s the next resume
candidate's CRC-verified host arrays while decode runs, so the
boundary restore finds device-resident buffers instead of paying the
full H2D wall clock. Staging is skipped while a fault plan is active —
chaos runs stay deterministic.
"""

from __future__ import annotations

import binascii
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..observability import metrics as _om
from ..testing import faults as _faults

__all__ = ["KvPageTier", "TierError", "TierCapacityError",
           "TierExportError", "TierRestoreError", "TierCorruptError"]


class TierError(RuntimeError):
    """Base of every typed host-tier failure. The serving engine
    catches THIS and degrades to the pre-tier behavior (evict on
    export failure, evict→requeue on restore failure)."""


class TierCapacityError(TierError):
    """The bounded host pool cannot hold the copy (after demoted
    prefix pages — the tier's lowest-value tenants — were evicted to
    make room)."""


class TierExportError(TierError):
    """The D2H copy failed (injected or real)."""


class TierRestoreError(TierError):
    """The H2D restore failed (injected or real); the host copy is
    freed — the fallback re-prefills, stale bytes must not linger."""


class TierCorruptError(TierRestoreError):
    """A page of the host copy failed its CRC check: the copy was torn
    somewhere between export and restore. Caught BEFORE anything lands
    on device."""


#: H2D restore latency buckets (milliseconds): a one-page CPU-smoke
#: restore sits near the bottom, a multi-GB TPU restore near the top
_RESTORE_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                       50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0)


def _tier_metrics():
    return {
        "pages": _om.gauge(
            "kv_tier_pages",
            "KV pages currently held in the host-DRAM tier (paused "
            "sequences + demoted prefix pages)"),
        "bytes": _om.gauge(
            "kv_tier_bytes",
            "bytes of K/V data (plus int8 scale sidecars) currently "
            "held in the host-DRAM tier"),
        "restore_ms": _om.histogram(
            "kv_tier_restore_ms",
            "H2D restore latency of one paused sequence (CRC verify + "
            "device put + page scatter), milliseconds",
            buckets=_RESTORE_BUCKETS_MS),
        "errors": _om.counter(
            "kv_tier_errors_total",
            "typed host-tier failures by stage (d2h / h2d / crc / "
            "capacity); every one degraded to the pre-tier behavior",
            labelnames=("stage",)),
    }


def _data(pool):
    return getattr(pool, "_data", pool)


def _rewrap(pool, new_data):
    # the serving engine's pools are framework Tensors; unit tests may
    # hand raw jax arrays — return what was given
    return Tensor(new_data) if hasattr(pool, "_data") else new_data


def _gather_host(pools, idx):
    """ONE device gather per pool then ONE D2H transfer each — not a
    per-page round trip. Returns contiguous numpy arrays
    ``[n_pages, ...page shape]``, copied so the host pool OWNS its
    bytes (``np.asarray`` of a jax buffer is a read-only view whose
    device memory is about to be recycled)."""
    return [np.array(_data(p)[idx]) for p in pools]


def _page_crcs(arrays, n_pages):
    """crc32 per page SLOT, chained across every pool's bytes for that
    slot — one checksum covers a page's K, V and scale sidecars."""
    crcs = []
    for i in range(n_pages):
        c = 0
        for a in arrays:
            c = binascii.crc32(a[i].tobytes(), c)
        crcs.append(c)
    return crcs


def _find_corrupt_page(arrays, crcs):
    """Index of the first page slot whose recomputed CRC mismatches,
    or None when every page verifies."""
    for i, want in enumerate(crcs):
        c = 0
        for a in arrays:
            c = binascii.crc32(a[i].tobytes(), c)
        if c != want:
            return i
    return None


def _tear(arrays):
    """The injected torn copy: flip one byte in the middle of the
    first buffer — the minimal corruption the CRC check must catch."""
    if not arrays:
        return
    flat = arrays[0].reshape(-1).view(np.uint8)
    flat[flat.size // 2] ^= 0xFF


class _HostSeq:
    """One paused sequence's host copy: per-pool page arrays (gather
    order: k layers, v layers, then scale sidecars when present),
    per-page-slot CRCs committed to the SOURCE bytes, and the byte
    account the bounded pool charges."""

    __slots__ = ("key", "n_tokens", "n_pages", "arrays", "crcs",
                 "nbytes")

    def __init__(self, key, n_tokens, n_pages, arrays, crcs, nbytes):
        self.key = key
        self.n_tokens = n_tokens
        self.n_pages = n_pages
        self.arrays = arrays
        self.crcs = crcs
        self.nbytes = nbytes


class _HostPrefixPage:
    """One demoted prefix-cache page: single-page per-pool arrays plus
    the chain linkage (``parent`` hex key) promotion needs to re-pin
    it in chain order."""

    __slots__ = ("key", "parent", "arrays", "crc", "nbytes", "stamp")

    def __init__(self, key, parent, arrays, crc, nbytes, stamp):
        self.key = key
        self.parent = parent
        self.arrays = arrays
        self.crc = crc
        self.nbytes = nbytes
        self.stamp = stamp


class KvPageTier:
    """Bounded host-DRAM pool of paused-sequence pages and demoted
    prefix pages.

    The pool is byte-bounded (``max_bytes``): an export that does not
    fit — after evicting demoted prefix pages, the lowest-value
    tenants — raises :class:`TierCapacityError` and the engine falls
    back to the evict rung. Paused sequences are never evicted by the
    tier itself; their lifecycle (resume / cancel / deadline / drain)
    belongs to the serving engine, which must :meth:`free` every entry
    it parks — ``kv_tier_bytes`` returning to baseline is the leak
    check the chaos tests enforce.
    """

    def __init__(self, max_bytes=256 << 20, prefetch=True):
        self.max_bytes = int(max_bytes)
        self._lock = threading.RLock()
        self._seqs: dict[int, _HostSeq] = {}
        self._prefix: dict[str, _HostPrefixPage] = {}
        self._bytes = 0
        self._next_key = 0
        self._clock = 0
        self._m = _tier_metrics()
        # plain-int stats (always on; the bench/test surface)
        self.exports = 0
        self.restores = 0
        self.export_failures = 0
        self.restore_failures = 0
        self.crc_failures = 0
        self.capacity_rejections = 0
        self.prefix_demotions = 0
        self.prefix_promotions = 0
        # DevicePrefetcher-style async staging: spawned lazily on the
        # first stage() call, fed a bounded queue of resume candidates
        self._prefetch = bool(prefetch)
        self._stage_q: queue.Queue = queue.Queue(maxsize=2)
        self._staged: dict[int, object] = {}
        self._stage_thread = None
        self._closed = False

    # -- accounting ---------------------------------------------------
    @property
    def bytes(self):
        with self._lock:
            return self._bytes

    @property
    def pages(self):
        with self._lock:
            return (sum(e.n_pages for e in self._seqs.values())
                    + len(self._prefix))

    @property
    def seq_count(self):
        with self._lock:
            return len(self._seqs)

    @property
    def prefix_count(self):
        with self._lock:
            return len(self._prefix)

    def _set_gauges_locked(self):
        self._m["bytes"].set(self._bytes)
        self._m["pages"].set(sum(e.n_pages for e in self._seqs.values())
                             + len(self._prefix))

    def _fit_locked(self, nbytes):
        """Make room for ``nbytes`` by evicting demoted prefix pages
        (LRU) — never paused sequences. True when the copy fits."""
        if nbytes > self.max_bytes:
            return False
        while self._bytes + nbytes > self.max_bytes and self._prefix:
            victim = min(self._prefix.values(), key=lambda e: e.stamp)
            del self._prefix[victim.key]
            self._bytes -= victim.nbytes
        return self._bytes + nbytes <= self.max_bytes

    def stats(self):
        with self._lock:
            return {
                "bytes": self._bytes,
                "pages": (sum(e.n_pages for e in self._seqs.values())
                          + len(self._prefix)),
                "seqs": len(self._seqs),
                "prefix_pages": len(self._prefix),
                "exports": self.exports,
                "restores": self.restores,
                "export_failures": self.export_failures,
                "restore_failures": self.restore_failures,
                "crc_failures": self.crc_failures,
                "capacity_rejections": self.capacity_rejections,
                "prefix_demotions": self.prefix_demotions,
                "prefix_promotions": self.prefix_promotions,
            }

    # -- paused sequences ---------------------------------------------
    def export_seq(self, k_pools, v_pools, k_scales, v_scales, table,
                   n_tokens, step=None):
        """D2H-copy one sequence's pages into the host pool; returns
        the tier key the engine parks on the request. Raises
        :class:`TierExportError` (injected/failed copy) or
        :class:`TierCapacityError` (pool full). On any raise nothing
        is charged to the pool."""
        idx = np.asarray(table, np.int64)
        try:
            torn = _faults.fire_copy("tier.d2h", step=step, path="seq")
            arrays = (_gather_host(k_pools, idx)
                      + _gather_host(v_pools, idx)
                      + _gather_host(k_scales or [], idx)
                      + _gather_host(v_scales or [], idx))
        except Exception as e:
            with self._lock:
                self.export_failures += 1
            self._m["errors"].labels("d2h").inc()
            raise TierExportError(f"D2H export failed: {e!r}") from e
        nbytes = sum(a.nbytes for a in arrays)
        # CRCs commit to the SOURCE bytes before any tear lands: a torn
        # DMA corrupts data after the source was checksummed, which is
        # exactly what the restore-side verify must catch
        crcs = _page_crcs(arrays, len(idx))
        if torn:
            _tear(arrays)
        with self._lock:
            if not self._fit_locked(nbytes):
                self.capacity_rejections += 1
                self._m["errors"].labels("capacity").inc()
                raise TierCapacityError(
                    f"host tier full: {self._bytes} + {nbytes} bytes "
                    f"> max_bytes={self.max_bytes}")
            key = self._next_key
            self._next_key += 1
            self._seqs[key] = _HostSeq(key, int(n_tokens), len(idx),
                                       arrays, crcs, nbytes)
            self._bytes += nbytes
            self.exports += 1
            self._set_gauges_locked()
        return key

    def restore_seq(self, key, k_pools, v_pools, k_scales, v_scales,
                    table, step=None):
        """H2D-restore a paused sequence into the freshly admitted
        pages of ``table`` and free the host copy. Returns the new
        ``(k_pools, v_pools, k_scales, v_scales)`` lists (functional
        pool updates, like every other page write). Raises
        :class:`TierRestoreError` / :class:`TierCorruptError` — the
        host copy is freed then too: the fallback re-prefills from
        scratch, so keeping stale bytes would only leak."""
        with self._lock:
            ent = self._seqs.get(key)
        if ent is None:
            raise TierRestoreError(f"unknown tier key {key}")
        t0 = time.perf_counter()
        try:
            torn = _faults.fire_copy("tier.h2d", step=step, path="seq")
        except Exception as e:
            self.free(key)
            with self._lock:
                self.restore_failures += 1
            self._m["errors"].labels("h2d").inc()
            raise TierRestoreError(f"H2D restore failed: {e!r}") from e
        if torn:
            _tear(ent.arrays)
        staged = self._take_staged(key)
        if staged is None:
            # CRC verify per page BEFORE anything lands on device (the
            # staging thread verified already when `staged` is set —
            # and staging is off while a fault plan is active, so a
            # torn buffer always reaches this check)
            bad = _find_corrupt_page(ent.arrays, ent.crcs)
            if bad is not None:
                self.free(key)
                with self._lock:
                    self.crc_failures += 1
                self._m["errors"].labels("crc").inc()
                raise TierCorruptError(
                    f"host copy of tier key {key} failed CRC at page "
                    f"slot {bad}/{ent.n_pages}: torn copy detected")
            devs = [jax.device_put(a) for a in ent.arrays]
        else:
            devs = staged
        idx = jnp.asarray(np.asarray(table, np.int64))
        nk = len(k_pools)
        flat = list(k_pools) + list(v_pools) + list(k_scales or []) \
            + list(v_scales or [])
        out = [_rewrap(p, _data(p).at[idx].set(
                jnp.asarray(d, _data(p).dtype)))
               for p, d in zip(flat, devs)]
        nv = len(v_pools)
        ns = len(k_scales or [])
        result = (out[:nk], out[nk:nk + nv],
                  out[nk + nv:nk + nv + ns] if ns else k_scales,
                  out[nk + nv + ns:] if ns else v_scales)
        self.free(key)
        with self._lock:
            self.restores += 1
        self._m["restore_ms"].observe(
            (time.perf_counter() - t0) * 1e3)
        return result

    def free(self, key):
        """Drop a parked sequence's host copy (resume consumed it, or
        the request cancelled / expired / drained). Idempotent — a
        cancel racing a resume is a counted no-op. Returns True when
        an entry was actually freed."""
        with self._lock:
            self._staged.pop(key, None)
            ent = self._seqs.pop(key, None)
            if ent is None:
                return False
            self._bytes -= ent.nbytes
            self._set_gauges_locked()
            return True

    def seq_tokens(self, key):
        """Token count of a parked copy (None when unknown)."""
        with self._lock:
            ent = self._seqs.get(key)
            return ent.n_tokens if ent is not None else None

    # -- demoted prefix pages -----------------------------------------
    def put_prefix(self, key, parent, k_pools, v_pools, k_scales,
                   v_scales, page, step=None):
        """Demote ONE cold prefix-cache page into the host tier before
        it is dropped. ``key`` / ``parent`` are the chain-hash hex
        strings promotion needs to re-pin the page in chain order.
        Returns True when stored; False when the bounded pool has no
        room (paused sequences are never evicted to make one — demoted
        prefix pages are the tier's lowest-value tenants). Raises
        :class:`TierExportError` on a failed copy."""
        try:
            torn = _faults.fire_copy("tier.d2h", step=step,
                                     path="prefix")
            idx = np.asarray([page], np.int64)
            arrays = (_gather_host(k_pools, idx)
                      + _gather_host(v_pools, idx)
                      + _gather_host(k_scales or [], idx)
                      + _gather_host(v_scales or [], idx))
        except Exception as e:
            with self._lock:
                self.export_failures += 1
            self._m["errors"].labels("d2h").inc()
            raise TierExportError(
                f"prefix D2H export failed: {e!r}") from e
        nbytes = sum(a.nbytes for a in arrays)
        crc = _page_crcs(arrays, 1)[0]
        if torn:
            _tear(arrays)
        with self._lock:
            if key in self._prefix:
                return True                 # first writer wins
            if self._bytes + nbytes > self.max_bytes:
                self.capacity_rejections += 1
                return False
            self._clock += 1
            self._prefix[key] = _HostPrefixPage(
                key, parent, arrays, crc, nbytes, self._clock)
            self._bytes += nbytes
            self.prefix_demotions += 1
            self._set_gauges_locked()
        return True

    def has_prefix(self, key):
        with self._lock:
            return key in self._prefix

    def restore_prefix(self, key, k_pools, v_pools, k_scales, v_scales,
                       page, step=None):
        """H2D-promote one demoted prefix page into allocator page
        ``page`` and drop the host copy (it lives in HBM again).
        Returns the new pool lists, like :meth:`restore_seq`. Raises
        :class:`TierRestoreError` / :class:`TierCorruptError`; the
        entry is freed on failure (the cold path re-prefills it)."""
        with self._lock:
            ent = self._prefix.get(key)
        if ent is None:
            raise TierRestoreError(f"unknown prefix key {key!r}")

        def _drop():
            with self._lock:
                e = self._prefix.pop(key, None)
                if e is not None:
                    self._bytes -= e.nbytes
                    self._set_gauges_locked()

        try:
            torn = _faults.fire_copy("tier.h2d", step=step,
                                     path="prefix")
        except Exception as e:
            _drop()
            with self._lock:
                self.restore_failures += 1
            self._m["errors"].labels("h2d").inc()
            raise TierRestoreError(
                f"prefix H2D restore failed: {e!r}") from e
        if torn:
            _tear(ent.arrays)
        if _find_corrupt_page(ent.arrays, [ent.crc]) is not None:
            _drop()
            with self._lock:
                self.crc_failures += 1
            self._m["errors"].labels("crc").inc()
            raise TierCorruptError(
                f"host copy of prefix page {key!r} failed CRC: torn "
                f"copy detected")
        idx = jnp.asarray([int(page)])
        flat = list(k_pools) + list(v_pools) + list(k_scales or []) \
            + list(v_scales or [])
        out = [_rewrap(p, _data(p).at[idx].set(
                jnp.asarray(a, _data(p).dtype)))
               for p, a in zip(flat, ent.arrays)]
        nk, nv, ns = len(k_pools), len(v_pools), len(k_scales or [])
        _drop()
        with self._lock:
            self.prefix_promotions += 1
        return (out[:nk], out[nk:nk + nv],
                out[nk + nv:nk + nv + ns] if ns else k_scales,
                out[nk + nv + ns:] if ns else v_scales)

    def prefix_parent(self, key):
        with self._lock:
            ent = self._prefix.get(key)
            return ent.parent if ent is not None else None

    # -- async restore staging (DevicePrefetcher-style) ---------------
    def stage(self, key):
        """Hint that ``key`` is the next resume candidate: a daemon
        thread CRC-verifies and ``jax.device_put``\\ s its arrays so the
        boundary restore finds device-resident buffers. Best-effort
        and a no-op while a fault plan is active (chaos runs must hit
        the synchronous verify/restore path deterministically)."""
        if not self._prefetch or self._closed or _faults.active():
            return
        with self._lock:
            if key not in self._seqs or key in self._staged:
                return
            self._staged[key] = None        # queued, not ready
            if self._stage_thread is None:
                self._stage_thread = threading.Thread(
                    target=self._stage_worker, daemon=True,
                    name="kv-tier-stage")
                self._stage_thread.start()
        try:
            self._stage_q.put_nowait(key)
        except queue.Full:
            with self._lock:
                self._staged.pop(key, None)

    def _stage_worker(self):
        while not self._closed:
            try:
                key = self._stage_q.get(timeout=0.2)
            except queue.Empty:
                continue
            with self._lock:
                ent = self._seqs.get(key)
                pending = key in self._staged
            if ent is None or not pending:
                continue
            if _find_corrupt_page(ent.arrays, ent.crcs) is not None:
                # leave it to the synchronous restore path, which
                # types the corruption and falls back
                with self._lock:
                    self._staged.pop(key, None)
                continue
            devs = [jax.device_put(a) for a in ent.arrays]
            with self._lock:
                if key in self._staged and key in self._seqs:
                    self._staged[key] = devs

    def _take_staged(self, key):
        with self._lock:
            devs = self._staged.pop(key, None)
        return devs if devs is not None else None

    def close(self):
        """Stop the staging thread (idempotent; entries stay)."""
        self._closed = True
        t = self._stage_thread
        if t is not None:
            t.join(timeout=1.0)
            self._stage_thread = None
