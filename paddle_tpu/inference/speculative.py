"""Self-speculative decoding: n-gram / prompt-lookup drafting.

Decode is latency-bound by dispatch count: every emitted token costs one
round trip through the compiled decode program (ROADMAP item 3 —
2.34 ms/token at r05). Speculative decoding amortizes that dispatch
over several tokens: a cheap *drafter* proposes ``k`` tokens, the model
verifies all ``k+1`` positions in ONE dispatch (the mixed ragged
program already consumes multi-token rows — the verify step is exactly
a (q_len = k+1) chunk of PR-8's kernel), and the longest
exactly-matching prefix is accepted. Greedy outputs are token-exact by
construction: position ``i`` of the verify row computes the argmax the
sequential engine would have computed, given the identical KV prefix —
acceptance only ever *commits* tokens the non-speculative engine would
have emitted, and rejected draft pages are rolled back via the
allocator (:meth:`~paddle_tpu.inference.paged_cache.PageAllocator
.rollback`) before the next step.

This module is the drafter side. :class:`NGramDrafter` is
*self-speculative*: no extra model, no extra weights — a hashed n-gram
table over the request's own prompt + committed output (prompt-lookup
decoding; cf. the suffix-automaton drafters in the serving literature).
It wins exactly where production traffic repeats itself: code,
few-shot scaffolding, retrieval-stuffed prompts, and the short cycles
greedy decoding settles into. Where the history has no signal it
proposes nothing and the engine degrades to ordinary one-token decode
— speculation never costs a wrong token, only (bounded) wasted verify
compute.

Engine integration lives in :mod:`paddle_tpu.inference.serving`
(``LlamaServingEngine(spec_k=...)``); any object with this class's
``sync(prompt_ids, output_ids)`` / ``propose(k)`` surface can be
plugged in via ``drafter_factory`` (one drafter instance per live
sequence).
"""

from __future__ import annotations

__all__ = ["NGramDrafter"]


class NGramDrafter:
    """Hashed n-gram prompt-lookup drafter for ONE sequence.

    The table maps every context of length ``1..n`` seen in the
    committed history (prompt + emitted output) to the token that
    followed it, most recent occurrence winning. A proposal walks the
    table greedily: look up the longest matching suffix of the current
    history, append the predicted token, repeat — up to ``k`` drafts or
    the first unseen context.

    Args:
        n: max context length (the "n" of the n-gram). Longer contexts
            are tried first, so a bigger ``n`` only ever sharpens
            proposals; 2-4 covers the repetition serving traffic shows.
        max_history: hard cap on indexed tokens (memory bound for
            pathological request lengths). Past it the table stops
            growing and the history keeps only the rolling n-token
            tail proposals need; what was indexed keeps proposing.
    """

    def __init__(self, n=3, max_history=65536):
        self.n = max(1, int(n))
        self.max_history = int(max_history)
        self._table: dict[tuple, int] = {}
        self._hist: list[int] = []
        self._seen = 0
        self._n_prompt = 0
        self._n_out = 0

    def _extend(self, tokens):
        h = self._hist
        for t in tokens:
            t = int(t)
            h.append(t)
            self._seen += 1
            if self._seen > self.max_history:
                # table frozen; only the last n tokens matter for
                # proposals, so the history stays bounded too
                del h[:-self.n]
                continue
            ln = len(h)
            for cl in range(1, self.n + 1):
                if ln - 1 - cl < 0:
                    break
                self._table[tuple(h[ln - 1 - cl:ln - 1])] = t

    def sync(self, prompt_ids, output_ids):
        """Fold the committed history (prompt once, then every output
        token not yet consumed) into the table. Idempotent and
        incremental — the engine calls this before each proposal, so
        the drafter never sees rejected drafts, only committed
        tokens."""
        n_out = len(output_ids)
        if self._n_prompt == 0 and len(prompt_ids):
            self._extend(prompt_ids)
            self._n_prompt = len(prompt_ids)
        if n_out < self._n_out:
            # history rewound under us (a caller reusing one drafter
            # across restarts): rebuild from scratch rather than serve
            # stale continuations
            self._table.clear()
            self._hist = []
            self._seen = 0
            self._n_prompt = 0
            self._n_out = 0
            self.sync(prompt_ids, output_ids)
            return
        if n_out > self._n_out:
            self._extend(output_ids[self._n_out:])
            self._n_out = n_out

    def propose(self, k):
        """Up to ``k`` draft tokens continuing the synced history
        (longest-context match first; stops at the first context the
        table has never seen). The drafts are predictions for the NEXT
        ``k`` engine outputs, in order."""
        sim = list(self._hist[-self.n:])
        out = []
        for _ in range(int(k)):
            t = None
            for cl in range(min(self.n, len(sim)), 0, -1):
                t = self._table.get(tuple(sim[-cl:]))
                if t is not None:
                    break
            if t is None:
                break
            out.append(t)
            sim.append(t)
        return out
