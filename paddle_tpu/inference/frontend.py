"""OpenAI-compatible HTTP front door for the serving stack.

The engine/cluster tiers (PRs 4-11) end at Python objects; real
traffic arrives as HTTP. This module is the network layer:

- ``POST /v1/completions`` and ``POST /v1/chat/completions`` —
  OpenAI-compatible request/response shapes, including SSE streaming
  (``"stream": true`` pushes a chunk per emitted token from a
  per-request emit queue and finishes with ``data: [DONE]``).
- ``GET /v1/models`` plus the standard probes (``/metrics``,
  ``/healthz``, ``/readyz``) — all on ONE
  :class:`~paddle_tpu.observability.export.HttpService`.
- Fronts either a single :class:`LlamaServingEngine` (wrapped in a
  local :class:`~paddle_tpu.inference.cluster.EngineReplica` worker so
  the engine has a driver thread) or a whole
  :class:`~paddle_tpu.inference.cluster.ServingCluster` — request
  fields map onto :class:`ClusterRequest` (``timeout`` -> cluster
  deadline, ``max_tokens``, tenant class -> ladder ``priority``).
- Typed errors map onto proper HTTP codes:

  ==========================  ====================================
  typed error                 HTTP
  ==========================  ====================================
  ``ValueError`` (validation) 400 ``invalid_request_error``
  ``AdmissionError``          429 + ``Retry-After`` (from the
                              error's ``retry_after`` estimate)
  ``DeadlineExceeded``        504 ``timeout``
  replica/transport loss      502 ``upstream_error``
  client disconnect           (no reply possible) — tallied as 499,
                              the in-flight request is cancelled so
                              its KV pages return to the allocator
  anything else               500 ``server_error``
  ==========================  ====================================

- Multi-tenant QoS: give the frontend a
  :class:`~paddle_tpu.inference.qos.QosGate` and every request is
  gated per tenant (``X-Tenant`` header, or the OpenAI ``user``
  field) BEFORE touching the router: rate-exhausted tenants get 429 +
  ``Retry-After``; admitted ones ride the gate's priority class into
  the engine's degradation ladder, and completed tokens settle back
  into the tenant's bucket with TTFT/TPOT SLO accounting.

Strings need a tokenizer (``encode(str) -> ids`` / ``decode(ids) ->
str``); :class:`ByteTokenizer` is the dependency-free default, and
token-id arrays are always accepted for ``prompt`` (the OpenAI
completions API's token-array form).
"""

from __future__ import annotations

import collections
import json
import queue
import threading
import time
import uuid

from ..observability import metrics as _om
from ..observability import tracing as _tracing
from ..observability.export import (ClientDisconnected, HttpService,
                                    add_probe_routes)
from ..observability.trace import span as _span
from .sampling import SamplingParams
from .serving import AdmissionError, DeadlineExceeded

__all__ = ["ServingFrontend", "ByteTokenizer"]

_LAT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _frontend_metrics():
    return {
        "requests": _om.counter(
            "frontend_requests_total",
            "HTTP requests by endpoint and status code (499 = client "
            "disconnected mid-response)",
            labelnames=("endpoint", "code")),
        "latency": _om.histogram(
            "frontend_request_seconds",
            "wall time from request parse to final byte",
            labelnames=("endpoint",), buckets=_LAT_BUCKETS),
        "ttft": _om.histogram(
            "frontend_ttft_seconds",
            "submit -> first token observed at the HTTP layer",
            buckets=_LAT_BUCKETS),
        "streams": _om.counter(
            "frontend_streams_total", "SSE streaming responses opened"),
        "stream_tokens": _om.counter(
            "frontend_streamed_tokens_total",
            "tokens delivered over SSE streams"),
        "disconnects": _om.counter(
            "frontend_client_disconnects_total",
            "client disconnects that cancelled an in-flight request "
            "(the 499 path)"),
    }


class ByteTokenizer:
    """Dependency-free UTF-8 byte-level tokenizer: token id ==
    byte value + ``offset``. Good enough to demo/chat against models
    whose vocab covers the byte range; swap in a real tokenizer object
    (``encode``/``decode``) for production vocabularies."""

    def __init__(self, offset=0, vocab_size=None):
        self.offset = int(offset)
        self.vocab_size = vocab_size

    def encode(self, text):
        ids = [self.offset + b for b in str(text).encode("utf-8")]
        if self.vocab_size is not None:
            bad = [t for t in ids if not 0 <= t < self.vocab_size]
            if bad:
                raise ValueError(
                    f"text encodes to token ids outside the model "
                    f"vocab (first offender {bad[0]}, vocab "
                    f"{self.vocab_size})")
        return ids

    def decode(self, ids):
        bs = bytes(max(0, min(255, int(t) - self.offset)) for t in ids)
        return bs.decode("utf-8", errors="replace")


def _error_payload(status, message, etype):
    return status, {"error": {"message": message, "type": etype,
                              "code": status}}


def _map_error(err):
    """(status, body) for a typed terminal error."""
    if isinstance(err, AdmissionError):
        return _error_payload(
            429, f"capacity: {err}", "rate_limit_exceeded")
    if isinstance(err, DeadlineExceeded):
        return _error_payload(504, str(err), "timeout")
    if isinstance(err, ValueError):
        return _error_payload(400, str(err), "invalid_request_error")
    if isinstance(err, (ConnectionError, OSError)):
        return _error_payload(502, str(err), "upstream_error")
    return _error_payload(
        500, f"{type(err).__name__}: {err}", "server_error")


class ServingFrontend:
    """The HTTP door. Construct over ``engine=`` (a single
    :class:`LlamaServingEngine` — a local worker thread drives it) or
    ``cluster=`` (a started :class:`ServingCluster`), then
    ``start(port=...)``.

    Args:
        engine / cluster: exactly one backend.
        tokenizer: ``encode``/``decode`` object for string prompts and
            text responses (:class:`ByteTokenizer` works for byte-range
            vocabs). Without one, only token-id-array prompts are
            accepted and responses carry ``token_ids`` with empty
            ``text``.
        qos: optional :class:`~paddle_tpu.inference.qos.QosGate`; when
            given, every request is gated per tenant and the grant's
            priority class rides into the engine ladder.
        model_id: the id ``/v1/models`` and responses advertise.
        default_max_tokens: ``max_tokens`` when the request omits it.
        max_tokens_cap: hard ceiling on per-request ``max_tokens``.
        default_timeout: request deadline (seconds) when the request
            carries none (``timeout`` field or ``X-Request-Timeout``
            header). ``None`` = no deadline.
        stream_poll: emit-queue wait quantum; SSE latency is bounded by
            the engine step time, not this.
    """

    def __init__(self, engine=None, cluster=None, tokenizer=None,
                 qos=None, model_id="paddle-tpu-llama",
                 default_max_tokens=64, max_tokens_cap=4096,
                 default_timeout=None, stream_poll=0.005):
        if (engine is None) == (cluster is None):
            raise ValueError(
                "ServingFrontend fronts exactly one backend: pass "
                "engine= OR cluster=")
        self.engine = engine
        self.cluster = cluster
        self.tokenizer = tokenizer
        self.qos = qos
        self.model_id = str(model_id)
        self.default_max_tokens = int(default_max_tokens)
        self.max_tokens_cap = int(max_tokens_cap)
        self.default_timeout = default_timeout
        self.stream_poll = float(stream_poll)
        self._m = _frontend_metrics()
        self._replica = None          # local worker over engine=
        self._svc = None
        self._t0 = time.time()
        # request id -> trace id, bounded: what GET
        # /v1/requests/<id>/trace resolves through
        self._traces = collections.OrderedDict()
        self._traces_cap = 1024
        self._traces_lock = threading.Lock()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self, port=0, addr="127.0.0.1"):
        """Bind and serve. Returns the running
        :class:`~paddle_tpu.observability.export.HttpService`."""
        if self._svc is not None:
            return self._svc
        if self.engine is not None and self._replica is None:
            from .cluster import EngineReplica

            # the frontend owns a worker thread over the bare engine —
            # admission from a backlog, mixed steps, completion reaping
            # — so HTTP handlers never drive dispatches themselves
            self._replica = EngineReplica(
                "frontend-local", lambda: self.engine).start()
        svc = HttpService(addr=addr, port=port, name="frontend")
        svc.route("/v1/completions", self._completions,
                  methods=("POST",))
        svc.route("/v1/chat/completions", self._chat_completions,
                  methods=("POST",))
        svc.route("/v1/models", self._models)
        svc.route_prefix("/v1/requests/", self._request_trace)
        # /debug/profile: cluster backend -> cluster-wide merged
        # capture; bare engine -> this process only (profile_fn=None
        # falls back to perf.capture_bundle)
        profile_fn = (self.cluster.capture_profile
                      if self.cluster is not None else None)
        add_probe_routes(svc, ready=self._ready,
                         health_info=self._health_info,
                         profile_fn=profile_fn)
        self._svc = svc.start()
        return self._svc

    def stop(self):
        if self._svc is not None:
            self._svc.stop()
            self._svc = None
        if self._replica is not None:
            self._replica.stop_worker()
            self._replica = None

    @property
    def port(self):
        return self._svc.port if self._svc else None

    def _ready(self):
        if self.cluster is not None:
            return self.cluster.ready()
        return self._replica is not None and self._replica.ready()

    def _health_info(self):
        info = {"model": self.model_id,
                "backend": "cluster" if self.cluster is not None
                else "engine"}
        if self.cluster is not None:
            info.update(self.cluster.membership_info())
        return info

    # ------------------------------------------------------------------
    # request parsing
    # ------------------------------------------------------------------
    def _encode_prompt(self, prompt):
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError(
                    "string prompts need a tokenizer; this frontend "
                    "has none — send a token-id array instead")
            return self.tokenizer.encode(prompt)
        if isinstance(prompt, (list, tuple)):
            if prompt and all(isinstance(t, int) for t in prompt):
                return [int(t) for t in prompt]
            raise ValueError(
                "prompt must be a string or a non-empty flat array of "
                "token ids (batched prompt arrays are not supported)")
        raise ValueError(f"unsupported prompt type "
                         f"{type(prompt).__name__}")

    def _render_chat(self, messages):
        if not isinstance(messages, list) or not messages:
            raise ValueError("messages must be a non-empty list")
        parts = []
        for m in messages:
            role = m.get("role", "user")
            content = m.get("content", "")
            if not isinstance(content, str):
                raise ValueError("message content must be a string")
            parts.append(f"<|{role}|>\n{content}\n")
        parts.append("<|assistant|>\n")
        return "".join(parts)

    def _stop_ids(self, stop):
        """OpenAI ``stop`` -> engine stop-token ids: ints pass through;
        strings must tokenize to exactly ONE token (the emit-boundary
        check is per token)."""
        if stop is None:
            return ()
        if isinstance(stop, (str, int)):
            stop = [stop]
        out = []
        for s in stop:
            if isinstance(s, int):
                out.append(s)
            elif isinstance(s, str):
                if self.tokenizer is None:
                    raise ValueError(
                        "string stop sequences need a tokenizer")
                ids = self.tokenizer.encode(s)
                if len(ids) != 1:
                    raise ValueError(
                        f"stop sequence {s!r} tokenizes to {len(ids)} "
                        f"tokens; only single-token stops are "
                        f"supported")
                out.append(ids[0])
            else:
                raise ValueError("stop entries must be ints or strings")
        return tuple(out)

    def _sampling_from(self, body):
        bias = body.get("logit_bias") or None
        if bias is not None:
            bias = {int(k): float(v) for k, v in dict(bias).items()}
        return SamplingParams(
            temperature=float(body.get("temperature", 1.0)),
            top_p=float(body.get("top_p", 1.0)),
            top_k=int(body.get("top_k", 0)),
            seed=body.get("seed"),
            logit_bias=bias)

    def _decode(self, ids):
        return self.tokenizer.decode(ids) if self.tokenizer else ""

    # ------------------------------------------------------------------
    # submission + lifecycle against either backend
    # ------------------------------------------------------------------
    def _submit(self, ids, max_tokens, sampling, stop, priority,
                deadline, on_token):
        if self.cluster is not None:
            return self.cluster.submit(
                ids, max_new_tokens=max_tokens, deadline=deadline,
                priority=priority, sampling=sampling, stop=stop,
                on_token=on_token)
        from .cluster import ClusterRequest

        creq = ClusterRequest(
            ids, max_new_tokens=max_tokens, deadline=deadline,
            priority=priority, sampling=sampling, stop=stop,
            on_token=on_token)
        creq._t_submit = time.perf_counter()
        self._replica.submit(creq)
        return creq

    def _cancel(self, creq):
        try:
            if self.cluster is not None:
                self.cluster.cancel(creq)
            else:
                req = creq.cancel()
                if req is not None and self.engine is not None:
                    self.engine.cancel(req)
        except Exception:
            pass            # cancellation is best effort

    def _backend_lost(self):
        """True when the bare-engine deployment's local worker thread
        died: without this check a no-timeout request would poll a
        request that can never finish, forever (the cluster tier has a
        monitor to fail requests over; the local replica does not)."""
        return self._replica is not None and not self._replica.alive()

    # ------------------------------------------------------------------
    # endpoints
    # ------------------------------------------------------------------
    def _models(self, ctx):
        self._m["requests"].labels("models", "200").inc()
        ctx.send_json(200, {"object": "list", "data": [
            {"id": self.model_id, "object": "model",
             "created": int(self._t0), "owned_by": "paddle_tpu"}]})

    def _completions(self, ctx):
        self._handle_generate(ctx, chat=False)

    def _chat_completions(self, ctx):
        self._handle_generate(ctx, chat=True)

    def _handle_generate(self, ctx, chat):
        """Trace-context front door: adopt the caller's W3C
        ``traceparent`` (or mint a fresh root) and activate it for the
        whole handler — every span below (routing, rpc, admission,
        first token, SSE) chains to it, across processes."""
        tctx = _tracing.adopt(ctx.headers.get("traceparent"))
        if tctx is None:        # PADDLE_TPU_METRICS=0: plain dispatch
            return self._generate_impl(ctx, chat, None)
        with _tracing.activate(tctx), \
                _span("frontend.request",
                      endpoint="chat" if chat else "completions"):
            return self._generate_impl(ctx, chat, tctx)

    def _remember_trace(self, rid, trace_id):
        with self._traces_lock:
            self._traces[rid] = trace_id
            while len(self._traces) > self._traces_cap:
                self._traces.popitem(last=False)

    def _request_trace(self, ctx):
        """``GET /v1/requests/<id>/trace`` — one request's merged
        cross-process timeline as a parent-linked span tree."""
        parts = ctx.path.split("/")
        if len(parts) != 5 or parts[4] != "trace":
            self._m["requests"].labels("trace", "404").inc()
            ctx.send_json(404, {"error": {
                "message": f"unknown path {ctx.path!r} (expected "
                           f"/v1/requests/<id>/trace)",
                "type": "invalid_request_error"}})
            return
        rid = parts[3]
        with self._traces_lock:
            trace_id = self._traces.get(rid)
        if trace_id is None:
            self._m["requests"].labels("trace", "404").inc()
            ctx.send_json(404, {"error": {
                "message": f"no trace for request id {rid!r} (evicted, "
                           f"never traced, or tracing disabled)",
                "type": "invalid_request_error"}})
            return
        if self.cluster is not None:
            doc = self.cluster.request_trace(trace_id)
        else:
            from ..observability import trace as _otrace
            doc = {"trace_id": trace_id,
                   "spans": _tracing.span_tree(_otrace.get_events(),
                                               trace_id)}
        doc["request_id"] = rid
        self._m["requests"].labels("trace", "200").inc()
        ctx.send_json(200, doc)

    def _generate_impl(self, ctx, chat, tctx):
        endpoint = "chat" if chat else "completions"
        t_start = time.perf_counter()

        def reply(status, obj, headers=None):
            self._m["requests"].labels(endpoint, str(status)).inc()
            self._m["latency"].labels(endpoint).observe(
                time.perf_counter() - t_start)
            ctx.send_json(status, obj, headers)

        try:
            body = ctx.json()
            if chat:
                ids = self._encode_prompt(
                    self._render_chat(body.get("messages")))
            else:
                ids = self._encode_prompt(body.get("prompt"))
            max_tokens = int(body.get("max_tokens",
                                      self.default_max_tokens))
            if not 1 <= max_tokens <= self.max_tokens_cap:
                raise ValueError(
                    f"max_tokens must be in [1, {self.max_tokens_cap}]"
                    f", got {max_tokens}")
            sampling = self._sampling_from(body)
            stop = self._stop_ids(body.get("stop"))
            stream = bool(body.get("stream", False))
            timeout = body.get("timeout") \
                or ctx.headers.get("X-Request-Timeout") \
                or self.default_timeout
            timeout = None if timeout is None else float(timeout)
            tenant = ctx.headers.get("X-Tenant") \
                or body.get("user") or "default"
        except ValueError as e:
            status, obj = _map_error(e)
            reply(status, obj)
            return

        grant = None
        if self.qos is not None:
            try:
                grant = self.qos.admit(tenant, max_tokens)
            except AdmissionError as e:
                status, obj = _map_error(e)
                reply(status, obj, headers=_retry_headers(e))
                return
        priority = grant.priority if grant is not None \
            else int(body.get("priority", 0))

        emit_q: queue.Queue | None = queue.Queue() if stream else None
        try:
            creq = self._submit(ids, max_tokens, sampling, stop,
                                priority, timeout,
                                on_token=emit_q.put if stream else None)
        except Exception as e:
            # ANY submit failure must settle the grant, or the
            # tenant's inflight slot leaks (AdmissionError and
            # ValueError are the typed cases; a replica rpc timeout is
            # the 502 one)
            if grant is not None:
                self.qos.settle(grant, 0)
            status, obj = _map_error(e)
            reply(status, obj, headers=_retry_headers(e))
            return

        rid = f"{'chatcmpl' if chat else 'cmpl'}-{uuid.uuid4().hex[:24]}"
        if tctx is not None:
            self._remember_trace(rid, tctx.trace_id)
        if stream:
            self._stream_response(ctx, creq, grant, rid, chat,
                                  endpoint, len(ids), timeout, t_start,
                                  emit_q)
        else:
            self._wait_response(reply, creq, grant, rid, chat,
                                len(ids), timeout)

    # ------------------------------------------------------------------
    def _watch(self, creq, timeout, on_first, emit_q=None):
        """Drive one request to terminal: returns (output_ids, err).
        Stamps ``on_first`` at the first observed token. The emit
        queue (fed by the engine's per-token hook) wakes the loop;
        ``partial_output()`` is the source of truth, so subprocess
        replicas (no cross-process hook) stream at poll granularity."""
        t0 = time.perf_counter()
        seen = 0
        while True:
            if creq.done:
                break
            try:
                if emit_q is not None:
                    emit_q.get(timeout=self.stream_poll)
                else:
                    creq.wait(self.stream_poll)
            except queue.Empty:
                pass
            if seen == 0:
                seen = len(creq.partial_output())
                if seen:
                    on_first()
            if self._backend_lost():
                return list(creq.partial_output()), ConnectionError(
                    "serving engine worker died")
            if timeout is not None \
                    and time.perf_counter() - t0 > timeout + 5.0:
                # the deadline should have expired it server-side;
                # +5s of slack then give up client-side too
                self._cancel(creq)
                return list(creq.partial_output()), DeadlineExceeded(
                    f"request not terminal after {timeout}s deadline "
                    f"+ 5s slack")
        return list(creq.output_ids), creq.error

    def _finish_reason(self, creq, n_out, max_tokens):
        if n_out >= max_tokens:
            return "length"
        req = creq.request
        if req is not None and getattr(req, "trimmed", False):
            return "length"         # degradation-ladder trim
        return "stop"               # eos / stop token

    def _usage(self, n_prompt, n_out):
        return {"prompt_tokens": n_prompt, "completion_tokens": n_out,
                "total_tokens": n_prompt + n_out}

    def _wait_response(self, reply, creq, grant, rid, chat, n_prompt,
                       timeout):
        t_submit = time.perf_counter()
        first = {}

        def on_first():
            first["t"] = time.perf_counter() - t_submit
            self._m["ttft"].observe(first["t"])

        out, err = self._watch(creq, timeout, on_first)
        t_done = time.perf_counter()
        n = len(out)
        if grant is not None:
            tpot = None
            if n > 1 and "t" in first:
                tpot = (t_done - t_submit - first["t"]) / (n - 1)
            self.qos.settle(grant, n, ttft=first.get("t"), tpot=tpot)
        if err is not None:
            status, obj = _map_error(err)
            reply(status, obj, headers=_retry_headers(err))
            return
        text = self._decode(out)
        mx = creq.max_new_tokens
        if chat:
            choice = {"index": 0, "message":
                      {"role": "assistant", "content": text},
                      "finish_reason": self._finish_reason(creq, n, mx)}
            obj = {"id": rid, "object": "chat.completion",
                   "created": int(time.time()), "model": self.model_id,
                   "choices": [choice], "usage": self._usage(n_prompt, n)}
        else:
            choice = {"index": 0, "text": text, "token_ids": out,
                      "logprobs": None,
                      "finish_reason": self._finish_reason(creq, n, mx)}
            obj = {"id": rid, "object": "text_completion",
                   "created": int(time.time()), "model": self.model_id,
                   "choices": [choice], "usage": self._usage(n_prompt, n)}
        reply(200, obj)

    # ------------------------------------------------------------------
    def _sse_chunk(self, rid, chat, delta_text, delta_ids,
                   finish_reason, role=None):
        if chat:
            delta = {}
            if role is not None:
                delta["role"] = role
            if delta_text or delta_ids:
                delta["content"] = delta_text
            choice = {"index": 0, "delta": delta,
                      "finish_reason": finish_reason}
            obj = {"id": rid, "object": "chat.completion.chunk",
                   "created": int(time.time()), "model": self.model_id,
                   "choices": [choice]}
        else:
            choice = {"index": 0, "text": delta_text,
                      "token_ids": delta_ids,
                      "finish_reason": finish_reason}
            obj = {"id": rid, "object": "text_completion",
                   "created": int(time.time()), "model": self.model_id,
                   "choices": [choice]}
        return f"data: {json.dumps(obj)}\n\n".encode()

    def _stream_response(self, ctx, creq, grant, rid, chat, endpoint,
                         n_prompt, timeout, t_start, emit_q):
        self._m["streams"].inc()
        w = ctx.stream(200, "text/event-stream")
        t_submit = time.perf_counter()
        sent = 0
        prev_text = ""
        t_first = None
        code = "200"
        try:
            # chat streams open with the role chunk (OpenAI shape)
            if chat:
                w.write(self._sse_chunk(rid, chat, "", [], None,
                                        role="assistant"))
            t0 = time.perf_counter()
            while True:
                done = creq.done
                cur = creq.partial_output()
                if len(cur) < sent:
                    # failover restarted generation behind this stream:
                    # already-sent tokens can't be unsent — fail the
                    # stream honestly instead of splicing sequences
                    raise ConnectionError(
                        "generation restarted behind an active stream "
                        "(replica failover)")
                if len(cur) > sent:
                    if t_first is None:
                        t_first = time.perf_counter() - t_submit
                        self._m["ttft"].observe(t_first)
                    new = cur[sent:]
                    sent = len(cur)
                    full = self._decode(cur)
                    delta, prev_text = full[len(prev_text):], full
                    self._m["stream_tokens"].inc(len(new))
                    w.write(self._sse_chunk(rid, chat, delta, new,
                                            None))
                if not done and self._backend_lost():
                    raise ConnectionError("serving engine worker died")
                if done:
                    err = creq.error
                    if err is not None:
                        status, obj = _map_error(err)
                        code = str(status)
                        w.write(f"data: {json.dumps(obj)}\n\n".encode())
                    else:
                        fr = self._finish_reason(
                            creq, sent, creq.max_new_tokens)
                        final = self._sse_chunk(rid, chat, "", [], fr)
                        w.write(final)
                        w.write(b"data: [DONE]\n\n")
                    break
                if timeout is not None \
                        and time.perf_counter() - t0 > timeout + 5.0:
                    self._cancel(creq)
                    status, obj = _map_error(DeadlineExceeded(
                        f"stream not terminal after {timeout}s + 5s"))
                    code = str(status)
                    w.write(f"data: {json.dumps(obj)}\n\n".encode())
                    break
                try:
                    # the per-request emit queue (fed by the engine's
                    # per-token hook) wakes the loop the moment a step
                    # emits; the poll quantum only bounds subprocess
                    # replicas, whose hook can't cross the process
                    emit_q.get(timeout=self.stream_poll)
                except queue.Empty:
                    pass
        except ClientDisconnected:
            # 499: the client went away — cancel server-side work so
            # KV pages free immediately
            code = "499"
            self._m["disconnects"].inc()
            self._cancel(creq)
        except ConnectionError as e:
            # server-side stream failure (failover restarted
            # generation behind the stream, local worker death): the
            # CLIENT is still connected — tell it, as the error table
            # promises, instead of miscounting a phantom disconnect
            code = "502"
            self._cancel(creq)
            try:
                _, obj = _error_payload(502, str(e), "upstream_error")
                w.write(f"data: {json.dumps(obj)}\n\n".encode())
            except ClientDisconnected:
                pass
        finally:
            n = len(creq.partial_output())
            if grant is not None:
                tpot = None
                if n > 1 and t_first is not None:
                    tpot = (time.perf_counter() - t_submit - t_first) \
                        / (n - 1)
                self.qos.settle(grant, n, ttft=t_first, tpot=tpot)
            self._m["requests"].labels(endpoint, code).inc()
            self._m["latency"].labels(endpoint).observe(
                time.perf_counter() - t_start)


def _retry_headers(err):
    ra = getattr(err, "retry_after", None)
    if ra is None:
        return None
    return {"Retry-After": str(max(1, int(ra + 0.999)))}
