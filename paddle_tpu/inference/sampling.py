"""Per-request sampling for the serving engine (ROADMAP item 4).

Everything the engine served before this module was greedy argmax.
Real traffic wants temperature / nucleus / top-k sampling with
per-request seeds, per-request stop tokens, logit bias, and a
constraint hook for structured decoding — WITHOUT forking the compiled
program per sampler configuration. The design puts every sampler knob
in runtime *data*:

- :class:`SamplingParams` is the per-request spec. The engine packs one
  row of ``[R]``-shaped device arrays per live request (temperature,
  top_p, top_k, seed, bias/constraint slots), so a greedy row, a
  temperature-1.0 row and a top-p row ride the SAME dispatch of the
  SAME executable. Greedy rows (``temperature == 0``) take the argmax
  of the exact same logits the old program argmaxed — token-for-token
  bitwise-identical outputs by construction.
- :func:`sampled_next_tokens` is the vectorized sample step compiled
  into the mixed program (:meth:`LlamaServingEngine._mixed_forward`),
  next to the existing argmax. Randomness is counter-based: each row
  derives ``fold_in(PRNGKey(seed), position)`` — the threefry key is a
  pure function of (request seed, absolute token position), never of
  dispatch shape, batch composition, scan length, or acceptance
  history. That is what makes the speculative engine's outputs
  *sample-exact* against the non-speculative engine (same seed ⇒ same
  sequence, speculation on or off — the distribution-exactness gate).

Speculative verification under sampling (rejection sampling):
  the drafter is deterministic (a point mass ``q = δ(draft)``), so the
  textbook accept rule ``accept w.p. min(1, p(draft)/q(draft)) =
  p(draft)``, resample-from-residual-on-reject, is implemented exactly
  by sampling the target's own token ``t ~ p`` with the position's
  counter key and accepting the draft iff ``draft == t``:
  ``P(accept) = P(t = draft) = p(draft)``, and on reject the emitted
  token IS ``t`` conditioned on ``t ≠ draft`` — precisely the residual
  ``max(0, p - q)`` renormalized. One rule covers greedy (argmax is a
  point-mass target) and sampled rows, and the engine's existing
  longest-matching-prefix accept loop needs no change — ``out[f+j]``
  simply holds the sampled token instead of the argmax.

Structured decoding rides the same row slots: ``logit_bias`` entries
scatter-add into the row's logits, and a ``constraint`` hook narrows
the next token to an explicit allowed set (everything else masked to
-inf) — both bounded by the engine's static ``sample_slots`` width so
compiled shapes never fork per request.
"""

from __future__ import annotations

import math

__all__ = ["SamplingParams", "GREEDY", "sampled_next_tokens"]

#: Sentinel large-negative logit used to mask tokens out of the
#: sampled distribution (finite so softmax/cumsum stay NaN-free).
_MASKED = -1e30


class SamplingParams:
    """Per-request sampling spec. All fields are runtime data — two
    requests with different params share one compiled program.

    Args:
        temperature: 0 (default) = greedy argmax, bitwise-identical to
            the pre-sampling engine. > 0 scales logits before sampling.
        top_p: nucleus mass in (0, 1]; 1.0 disables.
        top_k: keep the k highest-probability tokens; 0 disables.
        seed: per-request RNG seed (int). ``None`` lets the engine
            assign one at admission (recorded on the request so the
            draw is reproducible after the fact). The sampled sequence
            is a pure function of (model, prompt, params, seed) —
            independent of batch composition, scan lengths, and
            speculation.
        stop: iterable of *token ids*; generation retires as
            ``completed`` right before any of them would be appended
            (the stop token is excluded from the output).
        logit_bias: ``{token_id: additive_logit_bias}`` applied every
            step (OpenAI semantics). Bounded by the engine's
            ``sample_slots`` width.
        constraint: optional hook for structured decoding:
            ``fn(prompt_ids, output_ids) -> allowed_token_ids | None``.
            Called at each step's schedule time on the host; a non-None
            return masks every OTHER token to -inf, so the next token
            is sampled (or argmaxed) from the allowed set only. Return
            ``None`` for "unconstrained this step". The allowed set is
            bounded by ``sample_slots``; hooks cannot cross a
            subprocess-replica boundary (in-process engines/replicas
            only).
    """

    __slots__ = ("temperature", "top_p", "top_k", "seed", "stop",
                 "logit_bias", "constraint")

    def __init__(self, temperature=0.0, top_p=1.0, top_k=0, seed=None,
                 stop=(), logit_bias=None, constraint=None):
        temperature = float(temperature)
        if not math.isfinite(temperature) or temperature < 0:
            raise ValueError(
                f"temperature must be finite and >= 0, got {temperature}")
        top_p = float(top_p)
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        top_k = int(top_k)
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0 (0 = off), got {top_k}")
        if seed is not None:
            seed = int(seed)
            if not 0 <= seed < 2 ** 31:
                raise ValueError(
                    f"seed must be in [0, 2**31), got {seed}")
        stop = tuple(int(t) for t in (stop or ()))
        if logit_bias:
            logit_bias = {int(k): float(v)
                          for k, v in dict(logit_bias).items()}
            for v in logit_bias.values():
                if not math.isfinite(v):
                    raise ValueError("logit_bias values must be finite")
        else:
            logit_bias = None
        if constraint is not None and not callable(constraint):
            raise ValueError("constraint must be callable "
                             "(prompt_ids, output_ids) -> ids | None")
        self.temperature = temperature
        self.top_p = top_p
        self.top_k = top_k
        self.seed = seed
        self.stop = stop
        self.logit_bias = logit_bias
        self.constraint = constraint

    @property
    def is_greedy(self):
        return self.temperature == 0.0

    def __repr__(self):
        return (f"SamplingParams(temperature={self.temperature}, "
                f"top_p={self.top_p}, top_k={self.top_k}, "
                f"seed={self.seed}, stop={self.stop}, "
                f"logit_bias={self.logit_bias}, "
                f"constraint={'set' if self.constraint else None})")

    # -- rpc plumbing ---------------------------------------------------
    def to_spec(self):
        """JSON-able dict for the subprocess-replica submit spec.
        Constraint hooks are host callables and cannot cross the
        process boundary — typed error, never a silent drop."""
        if self.constraint is not None:
            raise ValueError(
                "SamplingParams.constraint is a host callable and "
                "cannot cross a subprocess-replica boundary; use an "
                "in-process engine/replica for constrained decoding")
        return {"temperature": self.temperature, "top_p": self.top_p,
                "top_k": self.top_k, "seed": self.seed,
                "stop": list(self.stop),
                "logit_bias": {str(k): v for k, v
                               in (self.logit_bias or {}).items()}}

    @classmethod
    def from_spec(cls, spec):
        if spec is None:
            return None
        return cls(temperature=spec.get("temperature", 0.0),
                   top_p=spec.get("top_p", 1.0),
                   top_k=spec.get("top_k", 0),
                   seed=spec.get("seed"),
                   stop=spec.get("stop") or (),
                   logit_bias={int(k): float(v) for k, v in
                               (spec.get("logit_bias") or {}).items()})


#: Shared default: plain greedy decode, no stops, no bias.
GREEDY = SamplingParams()


def sampled_next_tokens(logits, temps, top_ps, top_ks, seeds, positions,
                        slot_ids, slot_vals, cmodes):
    """Vectorized per-row next-token rule — the pure-jax payload the
    engine wraps in a ``run_op`` inside the compiled mixed program.

    Args (jax arrays):
        logits:    [N, V] model logits (any float dtype).
        temps:     [N] f32, 0 = greedy (bitwise argmax of ``logits``).
        top_ps:    [N] f32 in (0, 1].
        top_ks:    [N] i32, 0 = off.
        seeds:     [N] i32 per-request seeds.
        positions: [N] i32 absolute position of the token being
            sampled — the counter folded into the threefry key, so the
            draw at a position is independent of how it was dispatched
            (per-step, scan tick, or speculative verify row).
        slot_ids:  [N, B] i32 bias/constraint token ids (-1 = empty).
        slot_vals: [N, B] f32 additive logit bias per slot.
        cmodes:    [N] i32; 0 = bias-only, 1 = constraint row (tokens
            outside the row's non-negative slot ids are masked out).

    Returns [N] int64 next-token ids.
    """
    import jax
    import jax.numpy as jnp

    n, v = logits.shape
    l = logits.astype(jnp.float32)
    rows = jnp.arange(n, dtype=jnp.int32)
    # bias scatter-add: empty slots (id -1) clip to token 0 with value
    # 0.0 — adding +0.0 never changes a comparison, so greedy rows
    # with no bias keep the exact argmax of the raw logits
    l = l.at[rows[:, None], jnp.clip(slot_ids, 0, v - 1)].add(slot_vals)
    # constraint rows: only the listed (non-negative) slot ids survive
    tok = jnp.arange(v, dtype=jnp.int32)[None, None, :]
    allowed = jnp.any((slot_ids[:, :, None] == tok)
                      & (slot_ids[:, :, None] >= 0), axis=1)    # [N, V]
    l = jnp.where((cmodes[:, None] == 1) & ~allowed, _MASKED, l)
    greedy = jnp.argmax(l, axis=-1)
    # -- sampled branch (same arrays; rows select at the end) ----------
    ls = l / jnp.maximum(temps, 1e-6)[:, None]
    sl = jnp.sort(ls, axis=-1)[:, ::-1]                  # descending
    kk = jnp.where(top_ks > 0, jnp.minimum(top_ks, v), v)
    kth = jnp.take_along_axis(sl, (kk - 1)[:, None], axis=1)
    sp = jax.nn.softmax(sl, axis=-1)
    cum_before = jnp.cumsum(sp, axis=-1) - sp
    # nucleus: keep the shortest prefix reaching top_p mass (the first
    # token crossing the boundary included); the mask is a prefix of
    # the sort, so its last kept value is a per-row logit cutoff
    n_keep = jnp.maximum(
        jnp.sum(cum_before < top_ps[:, None], axis=-1), 1)
    pth = jnp.take_along_axis(sl, (n_keep - 1)[:, None], axis=1)
    keep = ls >= jnp.maximum(kth, pth)
    # counter-based randomness: key = fold_in(PRNGKey(seed), position)
    # — a pure function of (seed, position), nothing else
    def _gumbel(seed, pos):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
        return jax.random.gumbel(key, (v,), dtype=jnp.float32)

    g = jax.vmap(_gumbel)(seeds, positions)
    z = jnp.where(keep, ls + g, -jnp.inf)
    sampled = jnp.argmax(z, axis=-1)        # gumbel-max ~ softmax(keep)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int64)
