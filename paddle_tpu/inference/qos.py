"""Multi-tenant QoS: fair-share admission ahead of the serving tier.

The degradation ladder (PR 4) already arbitrates *inside* the engine by
per-request ``priority``; what it cannot do is keep one tenant's flood
from consuming the whole admission pipe before priorities ever apply.
This module is that missing front gate:

- Tenants are declared with a **priority class** (mapped onto the
  ladder's integer ``priority``, so under pool pressure the engine
  trims/evicts the flooding low-class tenant first), a **token-rate
  share** (a token bucket refilled at ``rate`` tokens/sec up to
  ``burst``), and optional **TTFT/TPOT SLOs** (tracked per tenant;
  breaches counted, never enforced by killing requests).
- :meth:`QosGate.admit` runs BEFORE the cluster router: a tenant whose
  bucket is empty (it consumed its share and hasn't paid it back) is
  shed with a typed
  :class:`~paddle_tpu.inference.serving.AdmissionError` carrying a
  ``retry_after`` derived from the bucket deficit and refill rate —
  the frontend turns it into ``429 + Retry-After``.
- The bucket is **debited from completed-token counts**
  (:meth:`QosGate.settle`), not reserved up front: admission stays
  optimistic (a request that sheds server-side costs its tenant
  nothing), the flood pays for what it actually burned, and a bucket
  driven negative keeps the tenant shed until the refill catches up.
- Everything is exported per tenant label:
  ``serving_tenant_admitted_total`` / ``serving_tenant_shed_total`` /
  ``serving_tenant_completed_tokens_total`` /
  ``serving_tenant_inflight`` / ``serving_tenant_ttft_seconds`` /
  ``serving_tenant_tpot_seconds`` /
  ``serving_tenant_slo_breaches_total{tenant,slo}``.
"""

from __future__ import annotations

import math
import threading
import time

from ..observability import metrics as _om
from .serving import AdmissionError

__all__ = ["Tenant", "QosGate", "CLASS_PRIORITY"]

#: Priority classes -> the engine ladder's integer ``priority``. The
#: ladder only ever trims/evicts strictly LOWER priorities, so a
#: premium request can displace standard/batch work but never the
#: other way around — degradation evicts the flooding tenant first.
CLASS_PRIORITY = {"batch": 0, "standard": 1, "premium": 2}

_LAT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _qos_metrics():
    return {
        "admitted": _om.counter(
            "serving_tenant_admitted_total",
            "requests admitted through the QoS gate",
            labelnames=("tenant",)),
        "shed": _om.counter(
            "serving_tenant_shed_total",
            "requests shed by the QoS gate (token bucket empty or "
            "tenant concurrency cap)", labelnames=("tenant",)),
        "tokens": _om.counter(
            "serving_tenant_completed_tokens_total",
            "tokens completed and debited against the tenant's bucket",
            labelnames=("tenant",)),
        "inflight": _om.gauge(
            "serving_tenant_inflight",
            "requests admitted through the gate and not yet settled",
            labelnames=("tenant",)),
        "bucket": _om.gauge(
            "serving_tenant_bucket_tokens",
            "current token-bucket balance (negative = in debt, shed "
            "until refill catches up)", labelnames=("tenant",)),
        "ttft": _om.histogram(
            "serving_tenant_ttft_seconds",
            "admission -> first token, per tenant",
            labelnames=("tenant",), buckets=_LAT_BUCKETS),
        "tpot": _om.histogram(
            "serving_tenant_tpot_seconds",
            "mean per-token latency of a settled request, per tenant",
            labelnames=("tenant",), buckets=_LAT_BUCKETS),
        "breaches": _om.counter(
            "serving_tenant_slo_breaches_total",
            "settled requests whose TTFT/TPOT exceeded the tenant's "
            "declared SLO", labelnames=("tenant", "slo")),
    }


class Tenant:
    """One tenant's declared share and service objectives.

    Args:
        name: label value on every per-tenant metric.
        tier: priority class (``"batch"`` / ``"standard"`` /
            ``"premium"``) mapped onto the engine ladder via
            :data:`CLASS_PRIORITY`; or pass ``priority`` explicitly.
        rate: token-bucket refill in completed tokens/second
            (``None`` = unmetered).
        burst: bucket capacity (default: 4 seconds of ``rate``).
        max_inflight: optional concurrency cap at the gate.
        ttft_slo / tpot_slo: optional latency objectives in seconds;
            settled requests past them count
            ``serving_tenant_slo_breaches_total{tenant,slo}``.
    """

    def __init__(self, name, tier="standard", priority=None, rate=None,
                 burst=None, max_inflight=None, ttft_slo=None,
                 tpot_slo=None):
        if priority is None:
            if tier not in CLASS_PRIORITY:
                raise ValueError(
                    f"unknown tier {tier!r}; pick one of "
                    f"{sorted(CLASS_PRIORITY)} or pass priority=")
            priority = CLASS_PRIORITY[tier]
        self.name = str(name)
        self.tier = tier
        self.priority = int(priority)
        self.rate = None if rate is None else float(rate)
        if self.rate is not None and self.rate <= 0:
            raise ValueError(f"rate must be > 0 tokens/sec, got {rate}")
        if burst is None:
            burst = 4.0 * self.rate if self.rate is not None \
                else float("inf")
        self.burst = float(burst)
        self.max_inflight = None if max_inflight is None \
            else int(max_inflight)
        self.ttft_slo = None if ttft_slo is None else float(ttft_slo)
        self.tpot_slo = None if tpot_slo is None else float(tpot_slo)
        # bucket state (guarded by the gate's lock)
        self._level = self.burst if math.isfinite(self.burst) else 0.0
        self._last_refill = None
        self._inflight = 0


class QosGate:
    """Fair-share admission gate ahead of the cluster router.

    Usage::

        gate = QosGate([Tenant("api", tier="premium", rate=500,
                               ttft_slo=0.5),
                        Tenant("batch", tier="batch", rate=100)])
        grant = gate.admit("api", max_tokens=64)   # AdmissionError: shed
        creq = cluster.submit(ids, priority=grant.priority, ...)
        ...
        gate.settle(grant, completed_tokens=len(out), ttft=t1, tpot=tp)

    Unknown tenant names get a lazily-created default-spec tenant, so
    the gate never turns a typo into a crash — give ``default_spec``
    a restrictive rate to make "unknown tenant" mean "tiny share".
    """

    class Grant:
        __slots__ = ("tenant", "priority", "t_admit", "settled")

        def __init__(self, tenant, t_admit):
            self.tenant = tenant
            self.priority = tenant.priority
            self.t_admit = t_admit
            self.settled = False

    def __init__(self, tenants=(), default_spec=None,
                 clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: dict[str, Tenant] = {}
        self._default_spec = dict(default_spec or {})
        self._m = _qos_metrics()
        for t in tenants:
            self.add_tenant(t)

    def add_tenant(self, tenant):
        with self._lock:
            self._tenants[tenant.name] = tenant
        return tenant

    def tenant(self, name):
        """Get-or-create (default spec) the named tenant."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                t = self._tenants[name] = Tenant(
                    name, **self._default_spec)
            return t

    def _refill(self, t, now):
        """Advance the bucket to ``now`` (caller holds the lock)."""
        if t.rate is None:
            return
        if t._last_refill is None:
            t._last_refill = now
            return
        dt = max(0.0, now - t._last_refill)
        t._last_refill = now
        t._level = min(t.burst, t._level + dt * t.rate)

    def admit(self, name, max_tokens=0):
        """One admission decision. Returns a :class:`Grant` (carrying
        the ladder ``priority`` to submit with) or raises a typed
        :class:`AdmissionError` whose ``retry_after`` estimates when
        the bucket climbs back above zero."""
        t = self.tenant(name)
        now = self._clock()
        with self._lock:
            self._refill(t, now)
            if t.max_inflight is not None \
                    and t._inflight >= t.max_inflight:
                self._m["shed"].labels(t.name).inc()
                raise AdmissionError(
                    f"tenant {t.name!r} at its concurrency cap "
                    f"({t.max_inflight})", live=t._inflight,
                    max_batch=t.max_inflight, free_pages=0, num_pages=0,
                    retries=0, retry_after=0.05)
            if t.rate is not None and t._level <= 0:
                # in debt: shed until the refill pays it back (plus
                # one step of headroom so a retry isn't instantly shed)
                retry_after = round((-t._level + 1.0) / t.rate, 4)
                self._m["shed"].labels(t.name).inc()
                self._m["bucket"].labels(t.name).set(t._level)
                raise AdmissionError(
                    f"tenant {t.name!r} exhausted its token-rate share",
                    live=t._inflight, max_batch=0, free_pages=0,
                    num_pages=0, retries=0, retry_after=retry_after)
            t._inflight += 1
            self._m["admitted"].labels(t.name).inc()
            self._m["inflight"].labels(t.name).set(t._inflight)
            if t.rate is not None:
                self._m["bucket"].labels(t.name).set(t._level)
        return self.Grant(t, now)

    def settle(self, grant, completed_tokens=0, ttft=None, tpot=None):
        """Close out one granted request: debit the bucket by what the
        request actually completed, drop the in-flight slot, record
        latency + SLO accounting. Idempotent per grant; safe for shed/
        errored requests (``completed_tokens=0``)."""
        t = grant.tenant
        now = self._clock()
        with self._lock:
            if grant.settled:
                return
            grant.settled = True
            self._refill(t, now)
            t._inflight = max(0, t._inflight - 1)
            if t.rate is not None and completed_tokens:
                t._level -= float(completed_tokens)
            self._m["inflight"].labels(t.name).set(t._inflight)
            if t.rate is not None:
                self._m["bucket"].labels(t.name).set(t._level)
        if completed_tokens:
            self._m["tokens"].labels(t.name).inc(int(completed_tokens))
        if ttft is not None:
            self._m["ttft"].labels(t.name).observe(float(ttft))
            if t.ttft_slo is not None and ttft > t.ttft_slo:
                self._m["breaches"].labels(t.name, "ttft").inc()
        if tpot is not None:
            self._m["tpot"].labels(t.name).observe(float(tpot))
            if t.tpot_slo is not None and tpot > t.tpot_slo:
                self._m["breaches"].labels(t.name, "tpot").inc()

    def snapshot(self):
        """Per-tenant state dump for tests/benches/dashboards."""
        now = self._clock()
        out = {}
        with self._lock:
            for name, t in self._tenants.items():
                self._refill(t, now)
                out[name] = {
                    "tier": t.tier, "priority": t.priority,
                    "rate": t.rate,
                    "bucket": t._level if t.rate is not None else None,
                    "inflight": t._inflight,
                }
        return out
