"""Continuous-batching serving engine for the Llama family.

Reference capability: the reference's serving path — AnalysisPredictor +
paged `block_multi_head_attention` / `masked_multihead_attention`
kernels (`fluid/inference/api/analysis_predictor.h:100`,
`phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu`). The
reference has no in-tree continuous-batching scheduler; this engine goes
beyond it (vLLM-style): requests are admitted and retired on the fly,
every live sequence decodes one token per engine step in a single
batched program, and KV lives in a shared paged pool so ragged contexts
waste no HBM.

Design (TPU-first, chunked prefill over ONE mixed program):
- ONE :class:`PageAllocator` shared by all layers (page structure is
  identical per layer); per-layer K/V pools are device arrays updated
  functionally.
- EVERY engine step is one dispatch of a single **mixed program** over
  a token-packed batch: variable-length prefill chunks and single-token
  decode rows ride in the same static-shape dispatch, attention served
  by the Pallas ``ragged_paged_attention`` kernel (per-row
  ``(q_start, q_len, kv_len)`` metadata over the shared block tables —
  the *Ragged Paged Attention* design, arXiv 2604.15464). There is no
  separate prefill program, no per-bucket compilation, and no
  wave-then-burst phase split: a long prompt is split into
  ``chunk_block``-sized chunks that interleave with live decodes under
  a per-step ``chunk_budget`` token budget, so admitting a 10k-token
  prompt never stalls a live decode for more than one chunk.
- The program packs real tokens [T = chunk_budget] (embed → per layer:
  rms_norm → qkv → rope at per-token positions → page write → ragged
  paged attention → o_proj → swiglu MLP → logits at each row's last
  token → greedy argmax); pad tokens scatter to a reserved trash page
  and inactive rows carry ``kv_len 0``, so shapes never change and two
  executables (the ``chunk_budget``-token mixed shape and the
  [max_batch]-token decode-only shape) cover the engine's lifetime.
- Sustained decode amortizes the host round trip with ``lax.scan``
  over the SAME mixed step (``decode_ticks`` tokens per sequence per
  dispatch, pages reserved up front, lengths advancing on device as
  the scan carry) — the scan body is the one mixed-program function,
  not a separate decode path.

Speculative decoding (latency layer, ROADMAP item 3a):
- With ``spec_k > 0`` every fully-prefilled decoder may carry up to k
  draft tokens from a per-sequence self-speculative drafter
  (:mod:`paddle_tpu.inference.speculative` — an n-gram prompt-lookup
  table over the request's own prompt+output; no extra weights). The
  scheduler packs the row into the mixed step as a (q_len = k+1)
  chunk over pages the drafts were tentatively written to; batched
  verification reads the argmax at EVERY position and accepts the
  longest exactly-matching draft prefix, so greedy outputs are
  token-exact vs the non-speculative engine by construction. Rejected
  draft pages roll back via :meth:`PageAllocator.rollback` before the
  next step, and when the drafter has nothing to propose the engine
  falls back to ordinary decode (scans included) — speculation never
  costs more than not speculating.

Int8 KV pages (capacity layer, ROADMAP item 3b):
- ``kv_dtype="int8"`` (or ``PADDLE_TPU_KV_DTYPE=int8``) stores the
  page pools as int8 with per-head per-slot f32 scale sidecars,
  quantizing on write and dequantizing inside the ragged kernel's kv
  loop — half (bf16) to a quarter (f32) of the HBM bytes per cached
  token (``kv_page_bytes_per_token``), so the same pool admits ~2x
  the batch/context before the degradation ladder fires. Sidecars
  are indexed by page id, so prefix-shared pages carry their scales
  and a copy-on-write copies both.

Shared-prefix KV cache (scale-out layer):
- Page-aligned prompt prefixes are content-addressed
  (:mod:`paddle_tpu.inference.prefix_cache`): a cold prompt's full
  pages are pinned once its prefill completes, and a later prompt
  sharing that prefix admits directly against the cached pages
  (refcounted in :class:`PageAllocator`, copy-on-write on any write
  into a shared page). Only the un-cached suffix runs through the
  model — as ordinary prefill chunks of the mixed program, typically
  ONE dispatch — so a 1k-token system prompt is prefilled once per
  replica, not once per request.
  ``serving_prefix_cache_hit_total`` /
  ``serving_prefix_saved_prefill_tokens_total`` make the win visible;
  under pool pressure cached pages are evicted (LRU, chain tails
  first) before the degradation ladder touches live requests.

Request lifecycle (robustness layer):
- Every request moves through ``status``: ``pending`` → ``live`` →
  one of ``completed`` / ``deadline_exceeded`` / ``cancelled`` /
  ``requeued`` (evicted under pressure, will retry) / ``paused``
  (pages parked in the host-DRAM KV tier —
  :mod:`paddle_tpu.inference.kv_tier` — resumes without re-prefill) /
  ``evicted`` (retry budget exhausted). Terminal failures carry a
  typed exception in ``req.error`` — never a silently truncated
  output.
- **Deadlines**: ``Request(deadline=...)`` (wall-clock TTL from
  admission) and ``Request(token_budget=...)`` (seconds per generated
  token) are enforced at step/scan boundaries; an expired request's
  pages go back to the :class:`PageAllocator` and the next admission
  can use them.
- **Cancellation**: :meth:`LlamaServingEngine.cancel` is thread-safe
  and idempotent — safe to fire from a client-abandon callback while
  another thread drives ``step()``; page release is deferred past any
  in-flight dispatch so compiled batch shapes are never disturbed.
- **Degradation ladder**: under admission pressure the engine first
  *trims* (truncate a lower-priority request's ``max_new_tokens`` to
  what it already produced, retiring it with partial output), then
  *evicts* (reclaim the lowest-priority request's pages and re-queue
  it against its ``retry_budget``), then *sheds* with a typed
  :class:`AdmissionError` carrying a ``retry_after`` hint.
- **Graceful drain**: :meth:`LlamaServingEngine.drain` stops admission
  and finishes or expires the in-flight set within a grace window;
  :meth:`install_drain_handler` wires that to SIGTERM (the preemption
  notice) for a clean exit — the serving analog of the checkpoint
  manager's preemption handler.
- **Stuck-dispatch watchdog**: a warm decode dispatch exceeding
  ``stuck_factor`` × its observed P99 trips a
  :class:`~paddle_tpu.distributed.watchdog.StepWatchdog`, which dumps
  a flight-recorder post-mortem.
Fault points ``serve.admit`` / ``serve.decode`` / ``serve.drain``
(:mod:`paddle_tpu.testing.faults`) make each path reproducibly
testable.
"""

from __future__ import annotations

import collections
import contextlib
import hashlib
import math
import os
import signal as _signal
import threading
import time

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, no_grad, run_op
from ..incubate.nn import functional as FI
from ..observability import compile_watch as _cw
from ..observability import flight_recorder as _fr
from ..observability import metrics as _om
from ..observability import tracing as _tracing
from ..observability.trace import span as _span
from ..ops.ragged_paged_attention import (fused_ragged_paged_attention,
                                          fused_rope_geometry_ok,
                                          ragged_paged_attention,
                                          rope_tables)
from ..testing import faults as _faults
from .kv_tier import KvPageTier, TierError
from .paged_cache import PageAllocator, quantize_kv_int8
from .sampling import SamplingParams, sampled_next_tokens
from .speculative import NGramDrafter

__all__ = ["LlamaServingEngine", "Request", "AdmissionError",
           "DeadlineExceeded"]


class AdmissionError(MemoryError):
    """Typed admission rejection carrying queue/pool stats so callers
    can shed load (429, redirect, re-queue) instead of crashing.

    Subclasses :class:`MemoryError` for backward compatibility with
    callers catching the engine's old bare raise; the serving
    ``_fatal_guard`` likewise treats it as a routine rejection, not a
    crash worth a flight-recorder dump.

    ``retry_after`` (seconds, may be None) estimates when capacity
    frees up — derived from the live set's shortest remaining token
    budget and recent per-token latency — so a frontend can answer
    with ``Retry-After`` instead of guessing.
    """

    def __init__(self, reason, live, max_batch, free_pages, num_pages,
                 retries, retry_after=None):
        msg = (f"{reason} (live={live}/{max_batch}, "
               f"free_pages={free_pages}/{num_pages}, "
               f"retries={retries})")
        if retry_after is not None:
            msg += f" — retry after {retry_after:.3f}s"
        super().__init__(msg)
        self.reason = reason
        self.live = live
        self.max_batch = max_batch
        self.free_pages = free_pages
        self.num_pages = num_pages
        self.retries = retries
        self.retry_after = retry_after

    def __reduce__(self):
        # default exception pickling replays type(self)(*args) with
        # args=(formatted msg,) — a TypeError at unpickle time, which
        # would turn a typed shed (retry_after and all) into an opaque
        # rpc failure on the error-reply round trip; rebuild from the
        # typed fields instead (mirrors RpcTimeoutError.__reduce__)
        return (type(self), (self.reason, self.live, self.max_batch,
                             self.free_pages, self.num_pages,
                             self.retries, self.retry_after))


class DeadlineExceeded(TimeoutError):
    """Typed terminal result of a request that ran out of wall-clock
    budget (TTL, per-token budget, or the drain grace window). The
    partial output stays on ``request.output_ids``; this error on
    ``request.error`` says *why* it is partial — never a silent
    truncation."""

    def __init__(self, msg, seq_id=None, elapsed=None, tokens_emitted=0,
                 reason="deadline"):
        super().__init__(msg)
        self.seq_id = seq_id
        self.elapsed = elapsed
        self.tokens_emitted = tokens_emitted
        self.reason = reason

    def __reduce__(self):
        # keep the carried fields (seq_id, tokens_emitted, ...) across a
        # pickle round trip — a subprocess replica reports deadline
        # expiry through the rpc error reply
        return (type(self), (self.args[0] if self.args else "",
                             self.seq_id, self.elapsed,
                             self.tokens_emitted, self.reason))

#: latency buckets tuned for serving (TTFT / per-token): 1ms .. 10s
_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: Cross-ENGINE dispatch serializer. Framework mode state (grad mode,
#: AMP state, trace stacks, the compile watcher) is per-process, so two
#: engine INSTANCES tracing/dispatching from different threads (an
#: in-process multi-replica cluster) would interleave no_grad sections
#: and leak tracers. Each dispatch body takes this lock INSIDE its own
#: per-instance ``_dispatch_lock`` (consistent order: own lock first,
#: global second — no cycle), and it is released between a drain's
#: steps, so one replica draining never starves its peers. Re-entrant
#: because a step's requeue pump may prefill.
_CROSS_ENGINE_LOCK = threading.RLock()


def _serving_metrics():
    """Standard serving metric set on the default registry (no-ops when
    ``PADDLE_TPU_METRICS=0``). Counters aggregate across engines in the
    process; gauges reflect the engine that last updated them."""
    return {
        "admitted": _om.counter(
            "serving_requests_admitted_total",
            "requests admitted into the continuous batch"),
        "completed": _om.counter(
            "serving_requests_completed_total",
            "requests retired (EOS or max_new_tokens)"),
        "evicted": _om.counter(
            "serving_requests_evicted_total",
            "admission rejections (engine full / KV pages exhausted)"),
        "admit_retries": _om.counter(
            "serving_admission_retries_total",
            "admission attempts retried after backoff while waiting "
            "for capacity"),
        "deadline_exceeded": _om.counter(
            "serving_deadline_exceeded_total",
            "requests expired by TTL / token budget / drain grace"),
        "cancelled": _om.counter(
            "serving_cancelled_total",
            "requests cancelled by the client before completion"),
        "degraded": _om.counter(
            "serving_degraded_total",
            "degradation-ladder actions under admission pressure",
            labelnames=("rung",)),
        "paused": _om.counter(
            "serving_paused_total",
            "requests paused into the host-DRAM KV tier under pool "
            "pressure (pages D2H-copied, request parked)"),
        "resumed": _om.counter(
            "serving_resumed_total",
            "paused requests resumed by H2D page restore (no "
            "re-prefill)"),
        "postponed": _om.counter(
            "serving_pressure_postponed_total",
            "decode rows dropped from ONE dispatch because victim "
            "page releases were deferred (cross-thread entry in "
            "flight); no state change — the rows rejoin at the next "
            "boundary"),
        "drain_seconds": _om.gauge(
            "serving_drain_seconds",
            "duration of the last graceful drain"),
        "queue_depth": _om.gauge(
            "serving_queue_depth", "live requests in the engine"),
        "kv_util": _om.gauge(
            "serving_kv_page_utilization",
            "fraction of KV-cache pages in use (0 when idle)"),
        "ttft": _om.histogram(
            "serving_ttft_seconds",
            "admission -> first emitted token", buckets=_LATENCY_BUCKETS),
        "tpot": _om.histogram(
            "serving_token_latency_seconds",
            "per-token decode latency (scan dispatches amortized)",
            buckets=_LATENCY_BUCKETS),
        "prefill_tokens": _om.counter(
            "serving_prefill_tokens_total", "prompt tokens prefilled"),
        "generated": _om.counter(
            "serving_generated_tokens_total", "tokens emitted by decode"),
        "prefix_lookups": _om.counter(
            "serving_prefix_cache_lookup_total",
            "admissions that consulted the shared-prefix cache"),
        "prefix_hits": _om.counter(
            "serving_prefix_cache_hit_total",
            "admissions that reused cached prefix pages"),
        "prefix_saved": _om.counter(
            "serving_prefix_saved_prefill_tokens_total",
            "prompt tokens NOT prefilled because their pages were "
            "served from the shared-prefix cache"),
        "prefix_pages": _om.gauge(
            "serving_prefix_cache_pages",
            "KV pages currently pinned by the shared-prefix cache"),
        "prefill_backlog": _om.gauge(
            "serving_prefill_backlog_tokens",
            "prompt tokens admitted but not yet prefilled (the "
            "chunked-prefill queue; load-routing signal)"),
        "spec_proposed": _om.counter(
            "serving_spec_proposed_tokens_total",
            "draft tokens proposed by the speculative drafter"),
        "spec_accepted": _om.counter(
            "serving_spec_accepted_tokens_total",
            "draft tokens accepted by batched verification"),
        "spec_rate": _om.gauge(
            "serving_spec_accept_rate",
            "cumulative fraction of proposed draft tokens accepted"),
        "spec_tpd": _om.gauge(
            "serving_spec_tokens_per_dispatch",
            "decode tokens emitted per speculative dispatch, averaged "
            "over its decode rows (1.0 = speculation gaining nothing)"),
        "kv_bytes": _om.gauge(
            "kv_page_bytes_per_token",
            "HBM bytes one cached token costs across all layers (K+V "
            "data plus any int8 scale sidecars)"),
        "weight_bytes": _om.gauge(
            "serving_weight_bytes_per_param",
            "bytes per model weight element as served (int8 weights + "
            "f32 scale sidecars land near 1; bf16 weights at 2; f32 "
            "at 4)"),
        "stop_hits": _om.counter(
            "serving_stop_token_hits_total",
            "requests retired by a per-request stop token (the stop "
            "token itself is excluded from the output)"),
        "constraint_truncated": _om.counter(
            "serving_constraint_truncated_total",
            "constraint-hook allowed sets truncated to the engine's "
            "sample_slots width"),
        "constraint_errors": _om.counter(
            "serving_constraint_errors_total",
            "constraint hooks that raised (the step proceeds "
            "unconstrained)"),
        "mixed_hbm": _om.gauge(
            "serving_mixed_hbm_bytes",
            "static cost_analysis bytes accessed of the mixed-program "
            "executable most recently dispatched (fused KV writes show "
            "as a strict decrease vs PADDLE_TPU_FUSED_KV=0)"),
    }


def _fatal_guard(origin):
    """Decorator: a crash inside an engine entry point dumps a
    flight-recorder post-mortem (when one is installed) before the
    exception reaches the caller — the serving analog of a rank dying
    under the elastic watchdog. Each exception dumps at most once."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            try:
                return fn(*args, **kwargs)
            except MemoryError:
                # admission control (engine full / KV pages exhausted)
                # raises MemoryError as a ROUTINE rejection — already
                # counted by the evicted metric; it must not burn the
                # recorder's bounded dump budget. A real device OOM
                # surfaces as XlaRuntimeError and still dumps.
                raise
            except Exception as e:
                _fr.on_fatal(origin, e)
                raise
        return wrapper

    return deco


def _last_writer_values(new, page_ids, offs, page_slots):
    """Pin LAST-WRITER-WINS semantics for a scatter whose (page, slot)
    targets may repeat within one dispatch (padding tokens all aim at
    the trash page; a chunk-boundary replay may legally re-write a
    slot): XLA's scatter leaves duplicate-index ordering
    implementation-defined, so instead of trusting it every duplicate's
    update VALUE is replaced by the last writer's — identical updates
    are order-independent by construction. The fused kernel pins the
    same semantics (the sequence's last row owns the page write), so
    both paths leave bitwise-identical slots. O(T^2) int compare on the
    packed token axis — noise next to the model math."""
    t = page_ids.shape[0]
    key = page_ids.astype(jnp.int32) * page_slots + offs.astype(jnp.int32)
    eq = key[:, None] == key[None, :]
    idx_last = jnp.argmax(
        jnp.where(eq, jnp.arange(t, dtype=jnp.int32)[None, :], -1),
        axis=1)
    return new[idx_last]


def _page_write(pages, new, page_ids, offs):
    """Functional scatter of ``new [B, Hk, D]`` into head-major ``pages
    [P, Hk, page, D]`` at (page_ids[b], h, offs[b]) — one token per live
    sequence. Duplicate targets resolve last-writer-wins (see
    `_last_writer_values`)."""
    def fn(pages, new, page_ids, offs):
        new = _last_writer_values(new, page_ids, offs, pages.shape[2])
        hidx = jnp.arange(pages.shape[1])[None, :]
        return pages.at[page_ids[:, None], hidx, offs[:, None]].set(
            new.astype(pages.dtype))

    return run_op("paged_kv_write", fn, (pages, new, page_ids, offs),
                  differentiable=False)


def _page_write_q8(pages, scales, new, page_ids, offs):
    """Quantizing scatter for int8 pools: ``new [B, Hk, D]`` float K/V
    is int8-quantized per head (symmetric, absmax) and scattered into
    ``pages [P, Hk, page, D]`` int8, with the per-head scale landing in
    the ``scales [P, Hk, page, 1]`` sidecar at the same (page, head,
    slot). A slot's (int8, scale) pair is always the LAST writer's —
    duplicates are rewritten to the last value before the scatter (see
    `_last_writer_values`), so a twice-written slot's sidecar can never
    mix one write's int8 with another's scale."""
    def fn(pages, scales, new, page_ids, offs):
        new = _last_writer_values(new, page_ids, offs, pages.shape[2])
        q, s = quantize_kv_int8(new)             # [B, Hk, D], [B, Hk]
        hidx = jnp.arange(pages.shape[1])[None, :]
        pages = pages.at[page_ids[:, None], hidx, offs[:, None]].set(q)
        scales = scales.at[
            page_ids[:, None], hidx, offs[:, None], 0].set(s)
        return pages, scales

    return run_op("paged_kv_write_q8", fn,
                  (pages, scales, new, page_ids, offs),
                  differentiable=False)


def _token_gather(x, idx):
    """Gather rows of ``x`` by an integer index array — the mixed
    program's pack/unpack between the flat token axis [T, ...] and the
    ragged kernel's row-blocked layout [R, QB, ...]."""
    def fn(x, idx):
        return x[idx.astype(jnp.int32)]

    return run_op("serving_token_gather", fn, (x, idx),
                  differentiable=False)


class Request:
    """One generation request (seq_id is assigned by the engine).

    Args:
        prompt_ids: non-empty 1-D sequence of prompt token ids.
        max_new_tokens: generation budget, >= 1.
        eos_token_id: optional early-stop token.
        deadline: wall-clock TTL in seconds, measured from admission.
            Past it the request is expired at the next step/scan
            boundary: its pages are released and ``error`` is set to a
            :class:`DeadlineExceeded` (partial output preserved).
        token_budget: seconds allowed per generated token — an
            alternative deadline of ``token_budget * max_new_tokens``
            from admission; the tighter of the two wins.
        priority: higher values win under pressure — the degradation
            ladder only trims/evicts strictly lower-priority requests.
        retry_budget: how many times the request may be evicted and
            re-queued before it fails permanently (status ``evicted``).
        sampling: :class:`~paddle_tpu.inference.sampling.SamplingParams`
            (None = greedy, bitwise-identical to the pre-sampling
            engine). The params' ``stop`` list merges with ``stop``.
        stop: iterable of token ids checked at the emit boundary —
            generation retires as ``completed`` right before any of
            them would be appended (the stop token is excluded).
        on_token: optional ``fn(request, token)`` fired after each
            appended token (the streaming hook). Runs on the engine's
            dispatch thread — must be fast and must not raise (raises
            are swallowed).
    """

    def __init__(self, prompt_ids, max_new_tokens=16, eos_token_id=None,
                 deadline=None, token_budget=None, priority=0,
                 retry_budget=1, sampling=None, stop=(), on_token=None):
        self.prompt_ids = np.asarray(prompt_ids, np.int64).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError(
                "prompt_ids is empty: a request needs at least one "
                "prompt token")
        if int(max_new_tokens) <= 0:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if deadline is not None and float(deadline) <= 0:
            raise ValueError(f"deadline must be > 0 seconds, "
                             f"got {deadline}")
        if token_budget is not None and float(token_budget) <= 0:
            raise ValueError(f"token_budget must be > 0 seconds/token, "
                             f"got {token_budget}")
        if int(retry_budget) < 0:
            raise ValueError(
                f"retry_budget must be >= 0, got {retry_budget}")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.deadline = None if deadline is None else float(deadline)
        self.token_budget = None if token_budget is None \
            else float(token_budget)
        self.priority = int(priority)
        self.retry_budget = int(retry_budget)
        if sampling is not None and not isinstance(sampling,
                                                  SamplingParams):
            raise ValueError(
                f"sampling must be a SamplingParams, got "
                f"{type(sampling).__name__}")
        self.sampling = sampling
        self.stop_set = frozenset(int(t) for t in (stop or ())) \
            | frozenset(sampling.stop if sampling else ())
        self.on_token = on_token
        self._seed = None             # resolved at first admission
        self.output_ids: list[int] = []
        self.seq_id = None
        self.done = False
        self.status = "pending"
        self.error = None             # typed terminal failure, or None
        self.trimmed = False          # budget cut by the ladder
        self._t_admit = None          # set at admission; drives TTFT
        self._expires_at = None       # perf_counter stamp, or None
        self._cancel_requested = False  # honored at (re-)admission
        self._cached_tokens = 0       # prefix tokens served from cache
        self._prefilled = 0           # prompt tokens written to pages
        self._tier_key = None         # host-tier handle while paused
        self._tier_tokens = 0         # context length of the parked KV


class LlamaServingEngine:
    #: default scanned decode run — one dispatch of the mixed program
    #: scanned over this many ticks serves that many tokens/sequence
    DECODE_TICKS = 16

    def __init__(self, model, max_batch=16, page_size=16, num_pages=None,
                 max_pages_per_seq=None, chunk_budget=None,
                 chunk_block=None, decode_ticks=None, burst=None,
                 admit_retries=0, admit_backoff=0.005, stuck_factor=8.0,
                 stuck_min_timeout=30.0, prefix_cache=True,
                 prefix_cache_pages=None, prewarm=None, kv_dtype=None,
                 spec_k=None, spec_ngram=3, drafter_factory=None,
                 sampling=None, sample_slots=8, fused_kv=None,
                 fused_rope=None, weight_dtype=None, weight_block=None,
                 kv_tier=None, kv_tier_bytes=None):
        if num_pages is None:
            num_pages = max_batch * 24 + 8
        self.model = model
        cfg = model.config
        self.max_batch = max_batch
        self.page_size = page_size
        # Keep block tables as narrow as the workload allows: the Pallas
        # ragged grid is (R, Hk, width), so a table sized to the whole
        # pool pays a grid step (and an HBM->VMEM page fetch) per UNUSED
        # table slot. max_pages_per_seq is the knob.
        #
        # Chunked-prefill scheduler knobs:
        # - chunk_budget: token budget per mixed dispatch — the sum of
        #   query tokens (decode rows count 1, prefill chunks their
        #   length) packed into one step. Floored at 2*max_batch so a
        #   full decode batch always leaves prefill headroom.
        # - chunk_block: the ragged kernel's per-row query block — the
        #   largest single prefill chunk. Rounded up so the kernel's
        #   [QB*group] query tile stays sublane-aligned.
        # - decode_ticks: scan length of the all-decode dispatch (the
        #   host-round-trip amortizer). ``burst=`` is accepted as a
        #   legacy alias.
        group = max(1, cfg.num_attention_heads
                    // max(1, cfg.num_key_value_heads))
        align = 8 // math.gcd(group, 8)
        qb = int(chunk_block) if chunk_block else min(
            32, max(8, 2 * page_size))
        self.chunk_block = -(-qb // align) * align
        budget = int(chunk_budget) if chunk_budget \
            else max(64, 4 * max_batch)
        self.chunk_budget = max(budget, 2 * max_batch, self.chunk_block)
        if decode_ticks is None and burst is not None:
            decode_ticks = burst
        self.decode_ticks = int(decode_ticks) if decode_ticks \
            else self.DECODE_TICKS
        # mixed-program row capacity: every live sequence may hold one
        # decode row, and the remaining budget splits into chunk rows
        self.rows_cap = max_batch + -(-self.chunk_budget
                                      // self.chunk_block)
        # admission backpressure: retry this many times (exponential
        # backoff from admit_backoff seconds) before a typed rejection.
        # Default 0 (instant rejection): retries only help when another
        # thread drives step()/scans and can retire a request
        # mid-backoff — opt in for such multithreaded deployments.
        self.admit_retries = int(admit_retries)
        self.admit_backoff = float(admit_backoff)
        # stuck-dispatch watchdog: a WARM dispatch exceeding
        # stuck_factor x the observed P99 (floored at stuck_min_timeout
        # so legitimate recompiles never trip it) dumps a flight
        # recorder post-mortem. stuck_factor=0/None disables it.
        self.stuck_factor = stuck_factor
        self.stuck_min_timeout = float(stuck_min_timeout)
        # page num_pages-1 is the trash page for inactive batch slots
        self.alloc = PageAllocator(num_pages - 1, page_size,
                                   max_pages_per_seq)
        self.width = self.alloc.max_pages_per_seq
        self.trash_page = num_pages - 1
        # shared-prefix KV cache: page-aligned prompt prefixes are
        # prefilled once and later admissions reference the cached
        # pages (refcounted in the allocator; see prefix_cache.py)
        from .prefix_cache import PrefixCache
        self.prefix = PrefixCache(self.alloc, page_size,
                                  max_pages=prefix_cache_pages) \
            if prefix_cache else None
        # weight-only int8 serving (ROADMAP item 3, weight side): every
        # decode-side projection stores int8 + per-block f32 scale
        # sidecars and dequantizes in VMEM on use — about half the HBM
        # bytes a decode step streams. PADDLE_TPU_WEIGHT_DTYPE=int8 is
        # the fleet knob; the engine arg wins when given; "bf16" (the
        # default) leaves the model untouched — the old path byte for
        # byte. Quantization is in place: a pre-quantized model (e.g.
        # load_quantized / the QAT bridge) is honored as-is.
        if weight_dtype is None:
            weight_dtype = os.environ.get(
                "PADDLE_TPU_WEIGHT_DTYPE", "") or None
        if weight_dtype == "bf16":
            weight_dtype = None
        if weight_dtype not in (None, "int8"):
            raise ValueError(
                f"weight_dtype must be 'bf16' (model dtype) or 'int8', "
                f"got {weight_dtype!r}")
        from ..quant.format import (is_quantized, model_weight_block,
                                    quantize_model, serving_weight_bytes)
        if weight_dtype == "int8" and not is_quantized(model):
            quantize_model(model, block=weight_block)
        self.weight_quant = bool(weight_dtype == "int8"
                                 or is_quantized(model))
        self.weight_block = model_weight_block(model) or 0
        wbytes, _, welems = serving_weight_bytes(model)
        self.weight_bytes_per_param = wbytes / max(welems, 1)
        dt = model.parameters()[0].dtype
        hk, d = cfg.num_key_value_heads, cfg.head_dim
        # int8 KV pages (ROADMAP item 3b): quantize on write, dequantize
        # inside the ragged kernel's kv loop. Halves (bf16) / quarters
        # (f32) the HBM bytes a cached token costs, so the same pool
        # admits ~2x the batch/context before the degradation ladder
        # ever trims or evicts. PADDLE_TPU_KV_DTYPE=int8 is the fleet
        # knob; the engine arg wins when given.
        if kv_dtype is None:
            kv_dtype = os.environ.get("PADDLE_TPU_KV_DTYPE", "") or None
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None (model dtype) or 'int8', "
                f"got {kv_dtype!r}")
        self.kv_quant = kv_dtype == "int8"
        pool_dt = jnp.int8 if self.kv_quant else jnp.dtype(str(dt))
        # head-major [P, Hk, page, D] — the Pallas kernel's tiling layout
        shape = (num_pages, hk, page_size, d)
        self.k_pools = [Tensor(jnp.zeros(shape, pool_dt))
                        for _ in range(cfg.num_hidden_layers)]
        self.v_pools = [Tensor(jnp.zeros(shape, pool_dt))
                        for _ in range(cfg.num_hidden_layers)]
        # per-head per-slot dequant scales ride sidecar arrays indexed
        # by the SAME page ids, so prefix-shared pages carry their
        # scales for free and a COW page copy copies both
        sshape = (num_pages, hk, page_size, 1)
        self.k_scales = [Tensor(jnp.zeros(sshape, jnp.float32))
                         for _ in range(cfg.num_hidden_layers)] \
            if self.kv_quant else []
        self.v_scales = [Tensor(jnp.zeros(sshape, jnp.float32))
                         for _ in range(cfg.num_hidden_layers)] \
            if self.kv_quant else []
        # self-speculative decoding (ROADMAP item 3a): an n-gram /
        # prompt-lookup drafter proposes up to spec_k tokens per live
        # decoder; the scheduler packs each speculating row into the
        # mixed step as a (q_len = k+1) chunk and batched verification
        # accepts the longest exactly-matching prefix — greedy outputs
        # stay token-exact, rejected draft pages roll back via the
        # allocator. spec_k=0 (default) disables.
        if spec_k is None:
            spec_k = int(os.environ.get("PADDLE_TPU_SPEC_K", "0") or 0)
        self.spec_k = max(0, min(int(spec_k), self.chunk_block - 1))
        # fused KV page write (ROADMAP item 2, first stage): the mixed
        # program writes each token's post-rope K/V into its page
        # INSIDE the ragged attention kernel instead of a separate
        # scatter op per layer — one HBM round trip less per layer.
        # PADDLE_TPU_FUSED_KV=0 restores the two-op path byte for byte
        # (the fallback runbook lives in the README); both paths are
        # greedy token-exact by construction.
        if fused_kv is None:
            fused_kv = os.environ.get(
                "PADDLE_TPU_FUSED_KV", "1").lower() \
                not in ("0", "false", "off")
        self.fused_kv = bool(fused_kv)
        # fused rotary embedding (ROADMAP item 2, second stage): the
        # mixed program feeds PRE-rope packed q/k straight into the
        # rope-fused kernel — rope happens in VMEM next to the page
        # write and attention, deleting the per-layer rope elementwise
        # op (2 HBM round trips per layer) AND the per-layer host-side
        # q row-block gather. Requires the fused KV write (the rope
        # rides its replay metadata); PADDLE_TPU_FUSED_ROPE=0 restores
        # the PR-13 fused-KV path byte for byte. Geometry the rope
        # kernel can't serve (odd head_dim, Pallas unavailable)
        # demotes to the fused-KV path instead of crashing or crawling
        # through an unsupported interpret lowering.
        if fused_rope is None:
            fused_rope = os.environ.get(
                "PADDLE_TPU_FUSED_ROPE", "1").lower() \
                not in ("0", "false", "off")
        self.fused_rope = bool(fused_rope) and self.fused_kv \
            and fused_rope_geometry_ok(cfg.head_dim)
        # per-request sampling (ROADMAP item 4): the mixed program
        # grows a vectorized per-row sample step next to the argmax —
        # every sampler knob is runtime data ([R]-shaped arrays), so
        # compiled shapes never fork per request config and greedy
        # rows stay bitwise-exact. sampling=False restores the exact
        # pre-sampling program (no vocab sort on the hot path) for
        # greedy-only deployments; PADDLE_TPU_SAMPLING=0 is the fleet
        # knob.
        if sampling is None:
            sampling = os.environ.get(
                "PADDLE_TPU_SAMPLING", "1").lower() \
                not in ("0", "false", "off")
        self.sample_enabled = bool(sampling)
        # static width of the per-row logit-bias / constraint slots —
        # part of the compiled signature, hence an ENGINE knob, never a
        # request one
        self.sample_slots = max(1, int(sample_slots))
        # auto-seed LCG for sampled requests that didn't pin a seed
        # (recorded on the request so the draw stays reproducible)
        self._auto_seed = int.from_bytes(os.urandom(4), "little") \
            % (2 ** 31)
        self._drafter_factory = drafter_factory or \
            (lambda: NGramDrafter(n=spec_ngram))
        self._spec_state: dict[int, object] = {}   # seq_id -> drafter
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_idle = 0     # consecutive no-proposal probes
        self._live: dict[int, Request] = {}
        self._m = _serving_metrics()
        n_layers = cfg.num_hidden_layers
        tok_bytes = 2 * hk * d * jnp.dtype(pool_dt).itemsize * n_layers
        if self.kv_quant:
            tok_bytes += 2 * hk * 4 * n_layers     # f32 scale sidecars
        self.kv_bytes_per_token = tok_bytes
        self._m["kv_bytes"].set(tok_bytes)
        self._m["weight_bytes"].set(self.weight_bytes_per_param)
        # host-DRAM KV page tier (ROADMAP item 5a): under pool pressure
        # the ladder PAUSES victims — pages D2H-copied into a bounded
        # host pool, the request parked ``paused``, resumed by an H2D
        # restore when capacity returns — instead of destroying their
        # work via evict. Opt-in (kv_tier=True / PADDLE_TPU_KV_TIER=1)
        # because pause changes the ladder's observable semantics;
        # kv_tier_bytes bounds the host pool (PADDLE_TPU_KV_TIER_BYTES,
        # default 256 MiB). Cold prefix-cache pages demote into the
        # same pool before being dropped and promote back on a match.
        if kv_tier is None:
            kv_tier = os.environ.get(
                "PADDLE_TPU_KV_TIER", "0").lower() in ("1", "true", "on")
        if kv_tier_bytes is None:
            kv_tier_bytes = int(os.environ.get(
                "PADDLE_TPU_KV_TIER_BYTES", str(256 << 20)))
        self.tier = KvPageTier(max_bytes=kv_tier_bytes) \
            if kv_tier else None
        if self.tier is not None and self.prefix is not None:
            self.prefix.demote = self._demote_prefix_page
        self._next_id = 0
        # ONE traced mixed-program function covers every dispatch; its
        # per-signature cache holds the chunk_budget-token shape and the
        # [max_batch]-token decode-only shape. Scanned multi-tick
        # variants (lax.scan over the same function) key by tick count.
        self._mixed_static = None
        self._scan_static: dict[int, object] = {}   # ticks -> program
        self._warmed_keys: set = set()  # ("mixed", T) / ("scan", k)
        self._mixed_bytes: dict[int, float] = {}  # t_cap -> hbm bytes
        self._warm_dispatches = 0       # dummy compile-warm dispatches
        # lifecycle state: one re-entrant lock guards _live, the
        # requeue, deferred releases and entry-depth accounting so
        # cancel()/drain handlers may fire from any thread
        self._lock = threading.RLock()
        # dispatch mutex: step()/_decode_scan() bodies are
        # serialized — two driver threads (or a drain racing an
        # external driver loop) must never interleave allocator extends
        # and pool reassignments for the same sequences. Re-entrant so
        # a step's own requeue pump may prefill.
        self._dispatch_lock = threading.RLock()
        self._requeue: collections.deque[Request] = collections.deque()
        self._deferred_release: list[int] = []
        self._in_dispatch = False
        self._entry_depth = 0
        self._entry_threads: dict[object, int] = {}   # thread -> depth
        self._flushing = False
        self._draining = False
        self._drain_active = False
        self._pending_drain = None    # (grace, exit_code, on_drained)
        self._dispatch_count = 0
        self._dispatch_times: collections.deque[float] = \
            collections.deque(maxlen=256)
        self._token_times: collections.deque[float] = \
            collections.deque(maxlen=512)
        self._wd = None
        self._closed = False
        # -- warm restart (ROADMAP item 5) -----------------------------
        # persistent XLA compile cache on by default (kill switch:
        # PADDLE_TPU_COMPILE_CACHE=0): a restarted replica re-compiling
        # the same serving programs gets executables from disk in
        # seconds instead of ~19 s of backend compile. The shape
        # registry records which programs THIS engine geometry actually
        # dispatches (mixed token shapes, scan tick counts) so the
        # next process can pre-warm them before traffic arrives.
        self._cache_dir = _cw.enable_persistent_cache()
        self._recorded_shapes: set = set()
        self._shape_key = self._compute_shape_key()
        self.prewarmed = None         # prewarm() summary, or None
        if prewarm is None:
            prewarm = os.environ.get(
                "PADDLE_TPU_SERVING_PREWARM", "0").lower() \
                in ("1", "true", "on", "auto")
        if prewarm:
            self.prewarm()

    def __state_tensors__(self):
        """State-discovery override for ``to_static``: the KV pools are
        explicit inputs/outputs of every compiled program (donated for
        in-place page writes) and must NOT also be captured as closure state —
        that would donate the same buffers twice. Model params enter via
        ``state=[self.model]``."""
        return []

    # ------------------------------------------------------------------
    # lifecycle plumbing
    # ------------------------------------------------------------------
    @contextlib.contextmanager
    def _entry(self):
        """Depth accounting around public entry points. Two jobs: a
        SIGTERM that lands while an entry is in flight defers its drain
        to the moment the outermost entry returns (state is
        boundary-consistent there), mirroring the checkpoint callback's
        deferred emergency save; and every thread inside an entry is
        recorded so page releases requested while a DIFFERENT thread is
        mid-entry (cancel, a concurrent _admit's eviction) are deferred
        past the whole entry — the in-flight step may still be reading
        the allocator's tables for those sequences."""
        me = threading.current_thread()
        with self._lock:
            self._entry_depth += 1
            self._entry_threads[me] = self._entry_threads.get(me, 0) + 1
        try:
            yield
        finally:
            with self._lock:
                self._entry_depth -= 1
                c = self._entry_threads.get(me, 1) - 1
                if c:
                    self._entry_threads[me] = c
                else:
                    self._entry_threads.pop(me, None)
                at_boundary = self._entry_depth == 0
                if at_boundary:
                    # the flush below releases pages outside the entry
                    # count; this flag keeps the SIGTERM handler
                    # deferring its drain past it (drain -> step ->
                    # alloc.extend would deadlock on the allocator's
                    # non-reentrant lock mid-release)
                    self._flushing = True
            if at_boundary:
                try:
                    self._flush_deferred()
                finally:
                    with self._lock:
                        self._flushing = False
                        pending = None
                        # leave _pending_drain for drain()'s epilogue
                        # when a manual drain is mid-flight — popping
                        # it here would run a second (no-op) drain and
                        # exit mid-grace-window
                        if self._entry_depth == 0 \
                                and not self._drain_active:
                            pending = self._pending_drain
                            if pending is not None:
                                self._pending_drain = None
                    if pending is not None:
                        grace, exit_code, on_drained = pending
                        self._run_drain_and_exit(grace, exit_code,
                                                 on_drained)

    def _release_pages(self, seq_id):
        """Release a sequence's pages — deferred while a dispatch is in
        flight (the program may still be writing K/V into them) and
        while ANOTHER thread is inside an engine entry (its setup/emit
        code may still be reading the allocator for this sequence), so
        a concurrent admission can never be handed dirty pages and the
        driving thread never sees tables vanish mid-step."""
        if seq_id is None:
            return
        me = threading.current_thread()
        with self._lock:
            others_in_entry = any(t is not me for t in self._entry_threads)
            if self._in_dispatch or others_in_entry:
                self._deferred_release.append(seq_id)
            else:
                self.alloc.release(seq_id)

    def _flush_deferred(self):
        with self._lock:
            if self._in_dispatch:
                return      # the dispatch's own epilogue will flush
            pending, self._deferred_release = self._deferred_release, []
        for sid in pending:
            # idempotent: racing a natural completion is a no-op
            self.alloc.release(sid)

    def _retire(self, req, status, error=None):
        """Terminal transition: remove from the live set, free pages,
        stamp status/error. Idempotent under the engine lock."""
        with self._lock:
            if req.done:
                return False
            req.done = True
            req.status = status
            req.error = error
            self._spec_state.pop(req.seq_id, None)
            if req.seq_id in self._live:
                del self._live[req.seq_id]
                self._release_pages(req.seq_id)
            return True

    def _expire(self, req, reason="deadline", now=None):
        now = time.perf_counter() if now is None else now
        elapsed = None if req._t_admit is None else now - req._t_admit
        err = DeadlineExceeded(
            f"request {req.seq_id} exceeded its {reason} after "
            f"{0.0 if elapsed is None else elapsed:.3f}s "
            f"({len(req.output_ids)}/{req.max_new_tokens} tokens "
            f"emitted)", seq_id=req.seq_id, elapsed=elapsed,
            tokens_emitted=len(req.output_ids), reason=reason)
        if self._retire(req, "deadline_exceeded", err):
            self._m["deadline_exceeded"].inc()

    def _expire_deadlines(self):
        """Expire every live request past its deadline — called at
        step/scan boundaries (the granularity that exists once a
        dispatch is on device)."""
        now = time.perf_counter()
        with self._lock:
            expired = [r for r in self._live.values()
                       if not r.done and r._expires_at is not None
                       and now >= r._expires_at]
            # paused requests park on the requeue with their deadline
            # clock still TICKING (their work is preserved, their SLA
            # is not suspended); an expired one frees its host-tier
            # copy too, not just its — already released — pages
            parked = [r for r in self._requeue
                      if not r.done and r._tier_key is not None
                      and r._expires_at is not None
                      and now >= r._expires_at]
            for r in parked:
                self._requeue.remove(r)
        for r in expired:
            self._expire(r, now=now)
        for r in parked:
            self._expire(r, now=now)
            self._tier_discard(r)

    def cancel(self, req):
        """Cancel a live request (by :class:`Request` or seq_id).

        Thread-safe and idempotent — wire it directly to a client-abandon
        callback. The request retires with status ``"cancelled"`` and
        its partial output intact; its pages return to the allocator
        (deferred past any in-flight dispatch, so compiled batch shapes
        are never disturbed mid-flight). Reaches both live requests and
        requests parked on the eviction requeue (an abandoned request
        must not be pumped back in and decoded for nobody). Returns
        True if this call did the cancellation, False if the request
        was already terminal or unknown."""
        with self._entry():
            with self._lock:
                if isinstance(req, Request):
                    r = req
                    if r.done:
                        return False
                    # sticky: even if the request is momentarily
                    # unreachable (popped by the requeue pump, mid
                    # re-admission), the admission path honors this
                    r._cancel_requested = True
                    if r in self._requeue:
                        self._requeue.remove(r)
                        r.done = True
                        r.status = "cancelled"
                        self._m["cancelled"].inc()
                        # a paused request's host copy dies with it
                        self._tier_discard(r)
                        return True
                    if r.seq_id is None \
                            or self._live.get(r.seq_id) is not r:
                        if r.status == "pending":
                            # never admitted: terminal right away, not
                            # a dangling flag the caller must poll
                            r.done = True
                            r.status = "cancelled"
                            self._m["cancelled"].inc()
                        # else: popped by the requeue pump mid
                        # re-admission — the flag is honored there
                        return True
                else:
                    r = self._live.get(req)
                    if r is None or r.done:
                        return False
                if self._retire(r, "cancelled"):
                    self._m["cancelled"].inc()
                    return True
                return False

    # ------------------------------------------------------------------
    # the mixed program: prefill chunks + decode rows, one dispatch
    # ------------------------------------------------------------------
    def _rope_tables(self, pos):
        """Per-dispatch rotary sin/cos tables ``[T, D]`` f32, one row
        per packed token — computed ONCE per dispatch (inside the
        traced program, from the packed positions) and shared across
        every layer. Bitwise the values
        `fused_rotary_position_embedding` derives from
        ``position_ids``, so swapping the per-layer derivation for
        this shared table never moves an output bit."""
        cfg = self.model.config
        d = cfg.head_dim
        base = float(cfg.rope_theta)

        def fn(p):
            return rope_tables(p, d, base)

        return run_op("serving_rope_tables", fn, (pos,),
                      differentiable=False)

    def _mixed_forward(self, tokens, pos, page_ids, offs, row_tok,
                       flat_idx, last_idx, tables, kv_lens, q_starts,
                       q_lens, w_starts, w_flats, w_ends, temps, top_ps,
                       top_ks, seeds, slot_ids, slot_vals, cmodes,
                       k_pools, v_pools, k_scales, v_scales):
        """ONE token-packed model step: embed [1, T] real tokens (a mix
        of prefill-chunk tokens, speculative verify tokens and decode
        tokens, back to back with no inter-row padding), scatter every
        token's post-rope K/V into the page pools (int8-quantized with
        scale sidecars when ``kv_quant``), run the Pallas
        ragged-paged-attention kernel over the per-row ``(q_start,
        q_len, kv_len)`` metadata, and read the greedy next token:
        a speculative engine (``spec_k > 0``) takes the argmax at
        EVERY packed position — position ``t`` of the [T] return is
        the argmax continuation after token ``t``, what verification
        compares drafts against — while a plain engine gathers each
        row's last valid position first (an [R]-sized lm-head, not a
        [T]-sized one; mixed dispatches with a big ``chunk_budget``
        would otherwise pay T/R times the vocab projection for argmax
        values nobody reads). Pure in its inputs so ``to_static``
        compiles it once per token-count signature; the decode-only
        shape (T == max_batch, QB == 1) and the chunk-budget shape
        share this function.

        With ``sample_enabled`` the argmax generalizes to the
        per-row sample step (:func:`sampled_next_tokens`): temperature
        / top-p / top-k / seed / bias-constraint slots ride as
        ``[R]``-shaped runtime arrays, greedy rows (temperature 0)
        still take the bitwise argmax of the same logits, and the
        threefry key folds the request seed with the token's absolute
        position — so the draw at a position never depends on how it
        was dispatched (step, scan tick, or speculative verify row).

        With ``fused_kv`` (the default) the per-layer scatter + read
        pair collapses into ONE `fused_ragged_paged_attention` call:
        the kernel writes each row's K/V into its pages in-grid (the
        sequence's last row owns the write-back; every reader row
        replays this dispatch's writes from the packed rows, so later
        chunks of one prompt attend earlier chunks of the SAME
        dispatch without an HBM round trip). ``w_starts``/``w_flats``/
        ``w_ends`` [R] carry the write-span metadata; ``page_ids``/
        ``offs`` still enter the program for the unfused path (and are
        inert, never touched, under fusion).

        With ``fused_rope`` on top (the default when ``fused_kv`` is
        on) the separate rope op disappears too: the kernel takes
        PRE-rope q (still packed ``[T, H, D]`` — no host-side
        ``_token_gather`` pack; each row's tokens are contiguous at
        its write offset, so the kernel slices them via the
        scalar-prefetched metadata) and pre-rope packed k, plus
        per-dispatch sin/cos tables computed once and shared across
        all layers, and applies the rotation in VMEM before the
        write/attention math — rope + write + attention in one Pallas
        program, bitwise the fallback chain. ``row_tok`` stays an
        input for the fallback paths (inert under rope fusion).

        tokens/pos [1, T]; page_ids/offs/flat_idx [T]; row_tok [R, QB];
        last_idx/kv_lens/q_starts/q_lens/w_starts/w_flats/w_ends/
        temps/top_ps/top_ks/seeds/cmodes [R]; slot_ids/slot_vals
        [R, B]; tables [R, W]; k/v_scales are empty lists for float
        pools.
        Returns (next token ids — 1-D [T] when speculative, 1-D [R]
        otherwise — new k_pools, new v_pools, new k_scales,
        new v_scales)."""
        from ..tensor import search

        m = self.model.model
        cfg = self.model.config
        t = tokens.shape[1]
        r_rows, qb = row_tok.shape[0], row_tok.shape[1]
        x = m.embed_tokens(tokens)                       # [1, T, H]
        # per-dispatch rotary sin/cos tables [T, D], computed ONCE and
        # shared by every layer: the rope-fused kernel consumes them
        # directly (no transcendentals in-kernel — Mosaic and XLA then
        # agree bit for bit), and the fallback paths feed them to
        # fused_rotary_position_embedding via sin=/cos= instead of
        # re-deriving the trig tables from the positions in every
        # layer (2 x n_layers redundant elementwise chains per trace)
        rsin, rcos = self._rope_tables(pos)
        new_k, new_v, new_ks, new_vs = [], [], [], []
        for li, layer in enumerate(m.layers):
            h = layer.input_layernorm(x)
            att = layer.self_attn
            q = att.q_proj(h).reshape([1, t, att.num_heads, att.head_dim])
            k = att.k_proj(h).reshape([1, t, att.num_kv_heads,
                                       att.head_dim])
            v = att.v_proj(h).reshape([1, t, att.num_kv_heads,
                                       att.head_dim])
            if not self.fused_rope:
                # fallback paths apply rope as a separate elementwise
                # op, from the shared per-dispatch tables
                q, k, v = FI.fused_rotary_position_embedding(
                    q, k, v, sin=rsin, cos=rcos)
            k2 = k.reshape([t, att.num_kv_heads, att.head_dim])
            v2 = v.reshape([t, att.num_kv_heads, att.head_dim])
            if self.fused_rope:
                # rope + page write + attention in ONE kernel: q stays
                # PRE-rope in the packed token layout — the kernel
                # slices each row's contiguous tokens through the
                # scalar-prefetched write metadata, so the host-side
                # _token_gather q pack is gone along with the
                # per-layer rope round trip for q AND k
                q3 = q.reshape([t, att.num_heads, att.head_dim])
                if self.kv_quant:
                    attn4, kp, vp, ksc, vsc = \
                        fused_ragged_paged_attention(
                            q3, k2, v2, k_pools[li], v_pools[li],
                            tables, kv_lens, q_starts, q_lens,
                            w_starts, w_flats, w_ends, self.trash_page,
                            k_scale=k_scales[li],
                            v_scale=v_scales[li], rope_sin=rsin,
                            rope_cos=rcos, qblock=qb)
                    new_ks.append(ksc)
                    new_vs.append(vsc)
                else:
                    attn4, kp, vp = fused_ragged_paged_attention(
                        q3, k2, v2, k_pools[li], v_pools[li], tables,
                        kv_lens, q_starts, q_lens, w_starts, w_flats,
                        w_ends, self.trash_page, rope_sin=rsin,
                        rope_cos=rcos, qblock=qb)
                new_k.append(kp)
                new_v.append(vp)
                attn = _token_gather(
                    attn4.reshape([r_rows * qb, att.num_heads,
                                   att.head_dim]), flat_idx)
                x = x + att.o_proj(attn.reshape([1, t, -1]))
                x = x + layer.mlp(layer.post_attention_layernorm(x))
                continue
            # pack the flat token axis into the kernel's [R, QB] row
            # blocks
            q4 = _token_gather(
                q.reshape([t, att.num_heads, att.head_dim]), row_tok)
            if self.fused_kv:
                # ONE kernel writes this dispatch's K/V into the pages
                # AND attends through them (in-grid replay keeps later
                # chunks of one prompt coherent with earlier rows of
                # the same dispatch) — no separate scatter, no HBM
                # round trip between producer and consumer
                if self.kv_quant:
                    attn4, kp, vp, ksc, vsc = \
                        fused_ragged_paged_attention(
                            q4, k2, v2, k_pools[li], v_pools[li],
                            tables, kv_lens, q_starts, q_lens,
                            w_starts, w_flats, w_ends, self.trash_page,
                            k_scale=k_scales[li], v_scale=v_scales[li])
                    new_ks.append(ksc)
                    new_vs.append(vsc)
                else:
                    attn4, kp, vp = fused_ragged_paged_attention(
                        q4, k2, v2, k_pools[li], v_pools[li], tables,
                        kv_lens, q_starts, q_lens, w_starts, w_flats,
                        w_ends, self.trash_page)
                new_k.append(kp)
                new_v.append(vp)
            else:
                # unfused reference path (PADDLE_TPU_FUSED_KV=0):
                # scatter every row's K/V first, then attend — a later
                # chunk of the same sequence attends what the scatter
                # just wrote
                if self.kv_quant:
                    kp, ksc = _page_write_q8(k_pools[li], k_scales[li],
                                             k2, page_ids, offs)
                    vp, vsc = _page_write_q8(v_pools[li], v_scales[li],
                                             v2, page_ids, offs)
                    new_ks.append(ksc)
                    new_vs.append(vsc)
                else:
                    kp = _page_write(k_pools[li], k2, page_ids, offs)
                    vp = _page_write(v_pools[li], v2, page_ids, offs)
                    ksc = vsc = None
                new_k.append(kp)
                new_v.append(vp)
                attn4 = ragged_paged_attention(q4, kp, vp, tables,
                                               kv_lens, q_starts,
                                               q_lens, k_scale=ksc,
                                               v_scale=vsc)
            attn = _token_gather(
                attn4.reshape([r_rows * qb, att.num_heads,
                               att.head_dim]), flat_idx)
            x = x + att.o_proj(attn.reshape([1, t, -1]))
            x = x + layer.mlp(layer.post_attention_layernorm(x))
        x = m.norm(x)
        # returned 1-D ([T] or [R]): a 2-D [1, T] int64 output would
        # exactly match the donated ``tokens`` input's aval and XLA
        # would alias the output into it — but that buffer is
        # zero-copy-backed by the caller's host array, so the alias is
        # a use-after-free. No input carries a 1-D int64 aval, so
        # these shapes always get a fresh buffer.
        if self.spec_k:
            logits = self.model._logits(x)               # [1, T, V]
            if self.sample_enabled:
                # sample at EVERY packed position: row params gather
                # token-wise through flat_idx (token t belongs to row
                # flat_idx[t] // qb), the fold position is the sampled
                # token's absolute position (input pos + 1)
                def fn(lg, tp, pp, kp_, sd, ps, sid, sva, cm, fi):
                    vv = lg.shape[-1]
                    row = jnp.clip(fi.astype(jnp.int32) // qb, 0,
                                   tp.shape[0] - 1)
                    return sampled_next_tokens(
                        lg.reshape(t, vv), tp[row], pp[row], kp_[row],
                        sd[row],
                        ps.reshape(t).astype(jnp.int32) + 1,
                        sid[row], sva[row], cm[row])

                nxt = run_op("serving_sample", fn,
                             (logits, temps, top_ps, top_ks, seeds,
                              pos, slot_ids, slot_vals, cmodes,
                              flat_idx), differentiable=False) \
                    .reshape([t])
            else:
                nxt = search.argmax(logits, axis=-1).astype("int64") \
                    .reshape([t])
        else:
            h_last = _token_gather(x.reshape([t, x.shape[-1]]),
                                   last_idx)
            logits = self.model._logits(
                h_last.reshape([r_rows, 1, h_last.shape[-1]]))
            if self.sample_enabled:
                def fn(lg, tp, pp, kp_, sd, ps, sid, sva, cm, li):
                    vv = lg.shape[-1]
                    p = ps.reshape(-1)[li.astype(jnp.int32)] \
                        .astype(jnp.int32) + 1
                    return sampled_next_tokens(
                        lg.reshape(r_rows, vv), tp, pp, kp_, sd, p,
                        sid, sva, cm)

                nxt = run_op("serving_sample", fn,
                             (logits, temps, top_ps, top_ks, seeds,
                              pos, slot_ids, slot_vals, cmodes,
                              last_idx), differentiable=False) \
                    .reshape([r_rows])
            else:
                nxt = search.argmax(logits, axis=-1).astype("int64") \
                    .reshape([r_rows])
        return nxt, new_k, new_v, new_ks, new_vs

    def _ensure_mixed_compiled(self):
        if self._mixed_static is None:
            from ..jit import StaticFunction

            # no lazy state (params exist, no optimizer): skip the eager
            # warmup and compile directly; donate pools for in-place
            # page writes. donate=False: serving state is read-only
            # pass-through (weights are never updated), so donating it
            # saves nothing — and with many same-aval state slots (e.g.
            # int8 weights + per-block scale sidecars) XLA's aval-based
            # alias assignment scrambles the pass-through outputs across
            # the donated buffers, corrupting the model in place.
            self._mixed_static = StaticFunction(
                self._mixed_forward, state=[self.model], warmup="once",
                donate=False, donate_inputs=True,
                name="serving.mixed_step")
            self._mixed_static._warmed_any = True
        return self._mixed_static

    def _note_mixed_bytes(self, t_cap):
        """Refresh the ``serving_mixed_hbm_bytes`` gauge with the
        static cost_analysis bytes of the mixed program just
        dispatched. The analysis runs ONCE per token shape (cached);
        every later dispatch is a dict lookup + gauge set. Under
        PADDLE_TPU_METRICS=0 the AOT executables don't exist and this
        is a no-op — the zero-cost mandate holds."""
        if not _om.enabled():
            return
        nbytes = self._mixed_bytes.get(t_cap)
        if nbytes is None:
            sf = self._mixed_static
            if sf is None:
                return
            compiled = None
            # match the executable by its signature: the FIRST leaf of
            # a mixed-program signature is the [1, T] token input, so
            # its shape identifies the dispatch's t_cap exactly. A
            # signature whose AOT slot is None (aot unsupported /
            # AOT_MISMATCH demotion) is skipped — misattributing some
            # OTHER shape's bytes here would poison the exact
            # fused-vs-unfused comparison the gauge exists for.
            for sig, c in sf._aot.items():
                if c is None:
                    continue
                shapes = sig[0]
                if shapes and shapes[0][0] == (1, t_cap):
                    compiled = c
                    break
            if compiled is None:
                return
            _, nbytes, _ = _cw.CompileWatch._analyze(compiled)
            if nbytes is None:
                return
            self._mixed_bytes[t_cap] = nbytes
        self._m["mixed_hbm"].set(nbytes)

    def _prefix_insert(self, reqs, sids):
        """Pin freshly written full prompt pages in the prefix cache
        (one allocator reference each) so they outlive the requests."""
        with self._lock:
            for r, sid in zip(reqs, sids):
                if r.done or r.seq_id != sid:
                    continue
                table = self.alloc._tables.get(sid)
                if table:
                    self.prefix.insert(r.prompt_ids, table)
            self._m["prefix_pages"].set(self.prefix.pages)

    def _copy_page(self, old, new):
        """Device-copy one page's K/V across every layer — the payload
        of a :meth:`PageAllocator.ensure_writable` copy-on-write. Int8
        pools copy the scale sidecars WITH the page: a copied page that
        kept stale scales would dequantize to garbage for its new
        owner."""
        for li in range(len(self.k_pools)):
            kd = self.k_pools[li]._data
            vd = self.v_pools[li]._data
            self.k_pools[li] = Tensor(kd.at[new].set(kd[old]))
            self.v_pools[li] = Tensor(vd.at[new].set(vd[old]))
            if self.kv_quant:
                ks = self.k_scales[li]._data
                vs = self.v_scales[li]._data
                self.k_scales[li] = Tensor(ks.at[new].set(ks[old]))
                self.v_scales[li] = Tensor(vs.at[new].set(vs[old]))

    # ------------------------------------------------------------------
    # chunked-prefill scheduler: rows -> one mixed dispatch
    # ------------------------------------------------------------------
    def _draft(self, r, kcap):
        """Draft up to ``kcap`` speculative tokens for a live decoder
        from its per-sequence drafter (created lazily; synced to the
        committed prompt + output only — never to rejected drafts).
        Out-of-vocab proposals from a custom drafter are dropped at the
        first offender. Constrained requests never draft: the
        constraint hook is host code evaluated once per scheduled
        position, so mid-dispatch draft positions can't consult it."""
        if r.sampling is not None and r.sampling.constraint is not None:
            return ()
        st = self._spec_state.get(r.seq_id)
        if st is None:
            st = self._spec_state[r.seq_id] = self._drafter_factory()
        st.sync(r.prompt_ids, r.output_ids)
        v = self.model.config.vocab_size
        out = []
        for t in st.propose(kcap):
            t = int(t)
            if not 0 <= t < v:
                break
            out.append(t)
        return tuple(out[:int(kcap)])

    def _spec_worth(self, live):
        """Probe (caller holds the engine lock): does any live decoder
        have at least one draft to verify? Proposals are pure (sync
        folds only committed tokens), so probing costs a dict lookup
        per row and never skews the drafter. When nothing proposes, a
        mixed spec step would be a plain one-token step paying the
        chunk-shaped program — the scan is strictly better, so
        :meth:`decode_many` falls back to it until the history gives
        the drafter something to say."""
        for r in live:
            if r.max_new_tokens - len(r.output_ids) <= 1:
                continue
            if self._draft(r, 1):
                return True
        return False

    def spec_stats(self):
        """Cumulative speculative-decoding counters: proposed/accepted
        draft tokens and the acceptance rate (also exported as
        ``serving_spec_accept_rate``)."""
        with self._lock:
            p, a = self._spec_proposed, self._spec_accepted
        return {"k": self.spec_k, "proposed": p, "accepted": a,
                "accept_rate": a / p if p else 0.0}

    def _schedule_rows(self):
        """Build one mixed step's row list (caller holds the engine
        lock): every fully-prefilled live sequence gets a decode row
        (one guaranteed token plus up to ``spec_k`` speculative draft
        tokens when the drafter has proposals and pages/budget allow —
        the row becomes a (q_len = 1+k) verify chunk over pages the
        drafts are tentatively written to), then the remaining
        ``chunk_budget`` fills with prefill chunks of at most
        ``chunk_block`` tokens each, FIFO by admission — a long prompt
        may take several chunk rows of ONE dispatch when the budget
        allows, and what doesn't fit waits for the next step, so a
        10k-token prompt never stalls a live decode for more than one
        budget. Returns (rows, cow) where each row is
        ``(req, sid, start, n, toks, is_decode)``."""
        live = [r for r in self._live.values() if not r.done]
        decode = [r for r in live if r._prefilled >= len(r.prompt_ids)]
        prefill = [r for r in live if r._prefilled < len(r.prompt_ids)]
        decode = self._relieve_pressure(decode, 1)
        rows, cow = [], []
        budget = self.chunk_budget
        page = self.page_size
        # speculative page headroom: _relieve_pressure proved ONE token
        # per decode row fits; drafts may only spend what is left after
        # that guarantee, so speculation can never evict or shed
        spare = 0
        if self.spec_k:
            reserved = sum(
                max(0, -(-(self.alloc._lens[r.seq_id] + 1) // page)
                    - len(self.alloc._tables[r.seq_id]))
                for r in decode)
            spare = self.alloc.free_pages - reserved
        n_dec = len(decode)
        # drafts must never starve pending prefill: with prompts
        # waiting, a chunk_block of budget is reserved for them, so
        # the chunked-prefill invariant (concurrent TTFT bounded by
        # one budget) survives sustained high acceptance — speculation
        # throttles while prompts chunk in, not the other way around
        reserve = self.chunk_block if prefill else 0
        for i, r in enumerate(decode):
            sid = r.seq_id
            drafts = ()
            if self.spec_k:
                # leave one budget token for every remaining decode row
                # and never draft past the request's own budget
                kcap = min(self.spec_k, self.chunk_block - 1,
                           budget - reserve - (n_dec - i),
                           r.max_new_tokens - len(r.output_ids) - 1)
                if kcap > 0:
                    drafts = self._draft(r, kcap)
                if drafts:
                    ln = self.alloc._lens[sid]
                    cur = len(self.alloc._tables[sid])
                    base = max(0, -(-(ln + 1) // page) - cur)
                    while drafts:
                        need = max(0, -(-(ln + 1 + len(drafts)) // page)
                                   - cur)
                        if need - base <= spare and cur + need \
                                <= self.alloc.max_pages_per_seq:
                            spare -= need - base
                            break
                        drafts = drafts[:-1]
            n = 1 + len(drafts)
            prev = self.alloc.extend(sid, n)
            # copy-on-write backstop: the write position must never
            # land in a page shared with the prefix cache (positions
            # past ``prev`` sit in the same now-private page or in
            # pages the extend just allocated)
            cp = self.alloc.ensure_writable(sid, prev)
            if cp is not None:
                cow.append(cp)
            tok = r.output_ids[-1] if r.output_ids \
                else int(r.prompt_ids[-1])
            rows.append((r, sid, prev, n, (tok,) + drafts, True))
            budget -= n
        for r in prefill:
            if budget <= 0 or len(rows) >= self.rows_cap:
                break
            off = int(r._prefilled)
            n_total = len(r.prompt_ids)
            # defensive copy-on-write for the chunk's first position:
            # page-aligned prefix matches always continue into pages
            # this sequence owns, but a shared page must stay immutable
            # regardless
            cp = self.alloc.ensure_writable(r.seq_id, off)
            if cp is not None:
                cow.append(cp)
            while off < n_total and budget > 0 \
                    and len(rows) < self.rows_cap:
                n = min(self.chunk_block, n_total - off, budget)
                toks = tuple(int(x) for x in r.prompt_ids[off:off + n])
                rows.append((r, r.seq_id, off, n, toks, False))
                off += n
                budget -= n
        return rows, cow

    def _sample_arrays(self, reqs, r_cap):
        """Host-built per-row sampler metadata for one dispatch:
        ``reqs`` is a <= r_cap list of requests (None entries and the
        padding tail stay inert greedy rows). Constraint hooks run
        HERE, once per scheduled dispatch — a raising hook degrades to
        unconstrained (counted), an oversized allowed set truncates to
        the engine's static ``sample_slots`` width (counted)."""
        b = self.sample_slots
        temps = np.zeros((r_cap,), np.float32)
        top_ps = np.ones((r_cap,), np.float32)
        top_ks = np.zeros((r_cap,), np.int32)
        seeds = np.zeros((r_cap,), np.int32)
        slot_ids = np.full((r_cap, b), -1, np.int32)
        slot_vals = np.zeros((r_cap, b), np.float32)
        cmodes = np.zeros((r_cap,), np.int32)
        if not self.sample_enabled:
            return (temps, top_ps, top_ks, seeds, slot_ids, slot_vals,
                    cmodes)
        for i, r in enumerate(reqs):
            sp = r.sampling if r is not None else None
            if sp is None:
                continue
            temps[i] = sp.temperature
            top_ps[i] = sp.top_p
            top_ks[i] = sp.top_k
            seeds[i] = r._seed or 0
            bias = sp.logit_bias or {}
            allowed = None
            if sp.constraint is not None:
                try:
                    allowed = sp.constraint(r.prompt_ids,
                                            tuple(r.output_ids))
                except Exception:
                    self._m["constraint_errors"].inc()
                    allowed = None
            if allowed is not None:
                ids = [int(tk) for tk in allowed]
                if not ids:
                    # an empty allowed set has no valid continuation;
                    # degrade to unconstrained rather than emit the
                    # arbitrary all-masked argmax
                    self._m["constraint_errors"].inc()
                elif len(ids) > b:
                    self._m["constraint_truncated"].inc()
                    ids = ids[:b]
                if ids:
                    cmodes[i] = 1
                    for j, tk in enumerate(ids):
                        slot_ids[i, j] = tk
                        slot_vals[i, j] = bias.get(tk, 0.0)
                    continue
            if bias:
                for j, (tk, v) in enumerate(list(bias.items())[:b]):
                    slot_ids[i, j] = int(tk)
                    slot_vals[i, j] = v
        return temps, top_ps, top_ks, seeds, slot_ids, slot_vals, cmodes

    def _dispatch_rows(self, rows, cow):
        """Dispatch ONE mixed program over an already-scheduled row
        list (caller holds the dispatch locks) and apply the results:
        prefill progress, prefix-cache pins, speculative verification
        (accept the longest exactly-matching draft prefix, roll back
        rejected draft pages), emitted tokens. Returns tokens
        emitted."""
        # speculative verify rows are multi-token decode rows: they
        # need the chunk-shaped program exactly like prefill chunks do
        needs_mixed = any(n > 1 or not is_dec
                          for _, _, _, n, _, is_dec in rows)
        if needs_mixed:
            t_cap, r_cap, qb = (self.chunk_budget, self.rows_cap,
                                self.chunk_block)
        else:
            t_cap, r_cap, qb = self.max_batch, self.max_batch, 1
        for old, new in cow:
            self._copy_page(old, new)
        key = ("mixed", t_cap)
        cold = key not in self._warmed_keys
        if cold and self._m["ttft"] is not _om.NULL:
            # compile this token shape OUTSIDE the TTFT window: a dummy
            # dispatch (all page writes land in the trash page, emitted
            # tokens discarded) triggers the one-time trace + compile,
            # and the affected clocks shift past it so TTFT keeps one
            # honest sample per request without the multi-second
            # compile skewing the histogram's +Inf bucket forever.
            # Under PADDLE_TPU_METRICS=0 this is skipped (zero-cost
            # mandate) and the cold dispatch just skips tpot.
            t_w = time.perf_counter()
            self._warm_mixed(t_cap)
            warm_dur = time.perf_counter() - t_w
            with self._lock:
                for r in {row[0] for row in rows}:
                    if r._t_admit is not None:
                        r._t_admit += warm_dur
                    if r._expires_at is not None:
                        # the deadline clock starts at admission;
                        # compile warmup is engine overhead, not
                        # request time
                        r._expires_at += warm_dur
            cold = False
        # host-built metadata: reads of the allocator's tables are safe
        # here — cross-thread releases defer past the whole _entry
        tokens = np.zeros((1, t_cap), np.int64)
        pos = np.zeros((1, t_cap), np.int32)
        page_ids = np.full((t_cap,), self.trash_page, np.int32)
        offs = np.zeros((t_cap,), np.int32)
        row_tok = np.zeros((r_cap, qb), np.int32)
        flat_idx = np.full((t_cap,), r_cap * qb - 1, np.int32)
        last_idx = np.zeros((r_cap,), np.int32)
        tables = np.full((r_cap, self.width), self.trash_page, np.int32)
        kv_lens = np.zeros((r_cap,), np.int32)
        q_starts = np.zeros((r_cap,), np.int32)
        q_lens = np.zeros((r_cap,), np.int32)
        # fused-write metadata: per row, the first position of its
        # sequence written by THIS dispatch, that position's packed
        # index, and the sequence's final kv_len (rows of one sequence
        # are consecutive, so one forward pass collects all three)
        w_starts = np.zeros((r_cap,), np.int32)
        w_flats = np.zeros((r_cap,), np.int32)
        w_ends = np.zeros((r_cap,), np.int32)
        seq_first: dict[int, tuple] = {}     # sid -> (w_start, w_flat)
        seq_last: dict[int, int] = {}        # sid -> w_end
        t = 0
        flat_start = []         # each row's first index in the T axis
        for i, (r, sid, start, n, toks, is_dec) in enumerate(rows):
            tb = self.alloc._tables[sid]
            tables[i, :len(tb)] = tb
            kv_lens[i] = start + n
            q_starts[i] = start
            q_lens[i] = n
            pg, of = self.alloc.page_positions(sid, start, n)
            tokens[0, t:t + n] = toks
            pos[0, t:t + n] = start + np.arange(n)
            page_ids[t:t + n] = pg
            offs[t:t + n] = of
            row_tok[i, :n] = np.arange(t, t + n)
            flat_idx[t:t + n] = i * qb + np.arange(n)
            flat_start.append(t)
            if sid not in seq_first:
                seq_first[sid] = (start, t)
            seq_last[sid] = start + n
            t += n
            last_idx[i] = t - 1
        for i, (r, sid, start, n, toks, is_dec) in enumerate(rows):
            w_starts[i], w_flats[i] = seq_first[sid]
            w_ends[i] = seq_last[sid]
        (temps, top_ps, top_ks, seeds, slot_ids, slot_vals,
         cmodes) = self._sample_arrays([row[0] for row in rows], r_cap)
        self._record_shape("mixed", t_cap)
        sf = self._ensure_mixed_compiled()
        self._arm_watchdog(cold)
        with self._lock:
            self._in_dispatch = True
        t0 = time.perf_counter()
        try:
            with no_grad(), _span("serving.mixed_step", rows=len(rows),
                                  tokens=int(t), prefill=needs_mixed):
                nxt, new_k, new_v, new_ks, new_vs = sf(
                    Tensor(jnp.asarray(tokens)),
                    Tensor(jnp.asarray(pos)),
                    Tensor(jnp.asarray(page_ids)),
                    Tensor(jnp.asarray(offs)),
                    Tensor(jnp.asarray(row_tok)),
                    Tensor(jnp.asarray(flat_idx)),
                    Tensor(jnp.asarray(last_idx)),
                    Tensor(jnp.asarray(tables)),
                    Tensor(jnp.asarray(kv_lens)),
                    Tensor(jnp.asarray(q_starts)),
                    Tensor(jnp.asarray(q_lens)),
                    Tensor(jnp.asarray(w_starts)),
                    Tensor(jnp.asarray(w_flats)),
                    Tensor(jnp.asarray(w_ends)),
                    Tensor(jnp.asarray(temps)),
                    Tensor(jnp.asarray(top_ps)),
                    Tensor(jnp.asarray(top_ks)),
                    Tensor(jnp.asarray(seeds)),
                    Tensor(jnp.asarray(slot_ids)),
                    Tensor(jnp.asarray(slot_vals)),
                    Tensor(jnp.asarray(cmodes)),
                    self.k_pools, self.v_pools,
                    self.k_scales, self.v_scales)
        finally:
            with self._lock:
                self._in_dispatch = False
            dur = time.perf_counter() - t0
            self._disarm_watchdog(dur, cold=cold)
            self._warmed_keys.add(key)
        self._note_mixed_bytes(t_cap)
        self._flush_deferred()
        self.k_pools, self.v_pools = list(new_k), list(new_v)
        if self.kv_quant:
            self.k_scales, self.v_scales = list(new_ks), list(new_vs)
        out = np.asarray(nxt._data).reshape(-1)          # [t_cap]
        if not cold and not needs_mixed:
            # a pure-decode dispatch is one token per live row: honest
            # per-token latency. Mixed dispatches carry prefill work
            # and would skew the histogram.
            self._m["tpot"].observe(dur)
            self._token_times.append(dur)
        finished, fin_sids = [], []
        with self._lock:
            for (r, sid, start, n, toks, is_dec) in rows:
                if is_dec or r.done or r.seq_id != sid:
                    continue
                # the seq_id check drops rows whose request was evicted
                # and requeued mid-dispatch — its reset progress must
                # not be advanced by this stale chunk
                self._m["prefill_tokens"].inc(n)
                r._prefilled = max(r._prefilled, start + n)
                if r._prefilled >= len(r.prompt_ids) \
                        and r not in finished:
                    finished.append(r)
                    fin_sids.append(sid)
        # pin finished prompts' pages in the prefix cache BEFORE
        # emitting: a max_new_tokens=1 request retires (and releases)
        # at emit, and its prefix must still make it into the cache
        if finished and self.prefix is not None:
            self._prefix_insert(finished, fin_sids)
        # speculative verification BEFORE any emission: out[t] is the
        # target continuation after packed token t — argmax for greedy
        # rows, the position-keyed SAMPLE for sampled rows — so a
        # verify row's window out[f .. f+n-1] holds exactly the token
        # the sequential engine would emit after the pending token and
        # after each draft. Accepting the longest matching prefix IS
        # rejection sampling for our point-mass drafter (accept w.p.
        # p(draft), reject resamples the residual — see sampling.py),
        # and keeps sampled outputs seed-stable with speculation on or
        # off. Accept the longest prefix where draft i+1 equals out i;
        # rejected drafts' pages roll back NOW, while the sequence is
        # still live (an emission below may retire it and release
        # everything — rollback after that would touch a freed table)
        accepted: dict[int, int] = {}
        if any(is_dec and n > 1 for *_, n, _, is_dec in rows):
            with self._lock:
                for i, (r, sid, start, n, toks, is_dec) \
                        in enumerate(rows):
                    if not is_dec or n <= 1:
                        continue
                    f = flat_start[i]
                    acc = 0
                    while acc < n - 1 \
                            and int(toks[1 + acc]) == int(out[f + acc]):
                        acc += 1
                    accepted[i] = acc
                    self._spec_proposed += n - 1
                    self._spec_accepted += acc
                    self._m["spec_proposed"].inc(n - 1)
                    if acc:
                        self._m["spec_accepted"].inc(acc)
                    rejected = (n - 1) - acc
                    if rejected and not r.done and r.seq_id == sid:
                        # deadline/cancel/evict mid-speculation: a row
                        # whose request turned terminal (or was
                        # requeued under a fresh seq_id) mid-dispatch
                        # skips rollback — release/re-admission owns
                        # its pages wholesale
                        self.alloc.rollback(sid, rejected)
                if self._spec_proposed:
                    self._m["spec_rate"].set(
                        self._spec_accepted / self._spec_proposed)
        emitted = 0
        dec_rows = dec_tokens = 0
        # spec engines index `out` by flat token position ([T] argmax);
        # plain engines by row ([R] last-position argmax)
        by_pos = bool(self.spec_k)
        for i, (r, sid, start, n, toks, is_dec) in enumerate(rows):
            if r.done or r.seq_id != sid:
                continue
            f = flat_start[i]
            if is_dec:
                # the guaranteed decode token plus every accepted draft
                # (greedy-exact by construction); _emit retires at EOS
                # or max_new_tokens, discarding the accepted tail
                dec_rows += 1
                for j in range(accepted.get(i, 0) + 1):
                    if r.done:
                        break
                    self._emit(r, int(out[f + j] if by_pos else out[i]))
                    emitted += 1
                    dec_tokens += 1
            elif (start + n) >= len(r.prompt_ids):
                # FINAL prompt chunks emit their last position; a mid-
                # prompt chunk's argmax is meaningless and discarded
                self._emit(r, int(out[f + n - 1] if by_pos else out[i]))
                emitted += 1
        if accepted and dec_rows:
            self._m["spec_tpd"].set(dec_tokens / dec_rows)
        if not cold and needs_mixed and dec_tokens \
                and all(is_dec for *_, is_dec in rows):
            # a pure decode+verify dispatch (no prefill rows): the
            # per-token latency is the dispatch amortized over what it
            # committed — same accounting as the decode scan — so tpot
            # and _retry_after() stay live while speculation runs
            per = dur / dec_tokens
            self._token_times.append(per)
            for _ in range(dec_tokens):
                self._m["tpot"].observe(per)
        return emitted

    # ------------------------------------------------------------------
    # stuck-dispatch watchdog
    # ------------------------------------------------------------------
    def _arm_watchdog(self, cold):
        """Arm the shared StepWatchdog for one dispatch: timeout =
        max(stuck_min_timeout, stuck_factor x P99 of warm dispatches).
        Cold dispatches (trace + compile, legitimately multi-second)
        never arm; with < 8 samples there is no P99 worth trusting."""
        if cold or not self.stuck_factor or self._closed:
            return
        times = self._dispatch_times
        if len(times) < 8:
            return
        s = sorted(times)
        p99 = s[min(len(s) - 1, int(math.ceil(0.99 * len(s))) - 1)]
        if self._wd is None:
            from ..distributed.watchdog import StepWatchdog
            self._wd = StepWatchdog(timeout=float("inf"),
                                    name="serving.decode").start()
        self._wd.arm(max(self.stuck_min_timeout, self.stuck_factor * p99))

    def _disarm_watchdog(self, duration=None, cold=False):
        if duration is not None and not cold:
            self._dispatch_times.append(duration)
        if self._wd is not None:
            self._wd.disarm()

    def close(self):
        """Release engine-owned background resources (the stuck-dispatch
        watchdog thread). Idempotent; the engine stays usable but
        unwatched — later dispatches will NOT respawn the watchdog."""
        self._closed = True
        if self._wd is not None:
            self._wd.stop()
            self._wd = None
        if self.tier is not None:
            self.tier.close()

    # ------------------------------------------------------------------
    # warm restart: shape registry + prewarm (ROADMAP item 5)
    # ------------------------------------------------------------------
    def _compute_shape_key(self):
        """Stable identity of this engine's compile surface: every
        dimension that shapes a serving program (model dims + batch
        geometry + pool layout + dtype). Two engines with the same key
        compile byte-identical programs, so one's recorded shape buckets
        are the other's valid warm-up recipe."""
        cfg = self.model.config
        dt = str(self.model.parameters()[0].dtype)
        parts = (cfg.vocab_size, cfg.hidden_size, cfg.intermediate_size,
                 cfg.num_hidden_layers, cfg.num_attention_heads,
                 cfg.num_key_value_heads, cfg.head_dim,
                 # MoE dims shape the FFN programs (router + stacked
                 # expert weights + grouped-GEMM grids): an MoE engine
                 # and a dense engine of otherwise equal geometry must
                 # not share prewarm recipes
                 getattr(cfg, "moe_num_experts", 0),
                 getattr(cfg, "moe_top_k", 0),
                 getattr(cfg, "moe_intermediate_size", None) or 0,
                 float(cfg.rope_theta), self.max_batch, self.page_size,
                 self.width, self.chunk_budget, self.chunk_block,
                 len(self.k_pools) and
                 tuple(self.k_pools[0]._data.shape), dt,
                 # the pool dtype shapes every serving program (int8
                 # pages add scale-sidecar inputs) and speculation
                 # changes the mixed program's lm-head ([T] vs [R]
                 # argmax) AND which scan lengths get dispatched — two
                 # engines that differ in either must not share
                 # warm-up recipes
                 str(self.k_pools[0]._data.dtype)
                 if self.k_pools else dt, bool(self.spec_k),
                 # the sample step adds inputs + a vocab sort to every
                 # serving program, and the slot width shapes the bias
                 # arrays — both fork the compiled surface
                 bool(self.sample_enabled), self.sample_slots,
                 # fused vs unfused engines compile different mixed
                 # programs (in-kernel write vs scatter + read): a
                 # prewarm recipe must never cross the two; same for
                 # the rope-fused program (pre-rope packed operands +
                 # in-kernel rotation vs the separate rope op)
                 bool(self.fused_kv), bool(self.fused_rope),
                 # weight-only int8 forks every serving program: the
                 # projections trade one bf16 weight input for an int8
                 # weight + scale-sidecar pair (and the block size
                 # shapes the sidecars), so a prewarm recipe recorded
                 # by a bf16 engine must never drive an int8 one (or
                 # vice versa, or across block sizes)
                 bool(self.weight_quant), int(self.weight_block))
        return "llama:" + hashlib.sha1(
            repr(parts).encode()).hexdigest()[:16]

    def _record_shape(self, kind, value):
        """Record one dispatched shape bucket in the persistent
        signature registry (one file write per distinct value per
        process; a no-op when the compile cache is disabled — without
        the cache a prewarm would re-PAY every compile, not skip it)."""
        if self._cache_dir is None:
            return
        k = (kind, value)
        if k in self._recorded_shapes:
            return
        self._recorded_shapes.add(k)
        try:
            _cw.shape_registry().record(self._shape_key, kind, value)
        except Exception:
            pass            # registry IO must never fail a dispatch

    def _warm_mixed(self, t_cap):
        """Compile one mixed-program token shape via a dummy dispatch:
        every row is inactive (kv_len 0 — the ragged kernel emits
        zeros), every page write lands in the trash page and the
        emitted tokens are discarded, so no request state is touched.
        The program donates its pool inputs — the returned pools must
        replace ours. Returns False for a token count that doesn't
        match this engine's geometry (a stale registry entry)."""
        t_cap = int(t_cap)
        if t_cap == self.chunk_budget:
            r_cap, qb = self.rows_cap, self.chunk_block
        elif t_cap == self.max_batch:
            r_cap, qb = self.max_batch, 1
        else:
            return False
        sf = self._ensure_mixed_compiled()
        samp = self._sample_arrays([], r_cap)
        with no_grad():
            _, wk, wv, wks, wvs = sf(
                Tensor(jnp.asarray(np.zeros((1, t_cap), np.int64))),
                Tensor(jnp.asarray(np.zeros((1, t_cap), np.int32))),
                Tensor(jnp.asarray(np.full((t_cap,), self.trash_page,
                                           np.int32))),
                Tensor(jnp.asarray(np.zeros((t_cap,), np.int32))),
                Tensor(jnp.asarray(np.zeros((r_cap, qb), np.int32))),
                Tensor(jnp.asarray(np.zeros((t_cap,), np.int32))),
                Tensor(jnp.asarray(np.zeros((r_cap,), np.int32))),
                Tensor(jnp.asarray(np.full((r_cap, self.width),
                                           self.trash_page, np.int32))),
                Tensor(jnp.asarray(np.zeros((r_cap,), np.int32))),
                Tensor(jnp.asarray(np.zeros((r_cap,), np.int32))),
                Tensor(jnp.asarray(np.zeros((r_cap,), np.int32))),
                Tensor(jnp.asarray(np.zeros((r_cap,), np.int32))),
                Tensor(jnp.asarray(np.zeros((r_cap,), np.int32))),
                Tensor(jnp.asarray(np.zeros((r_cap,), np.int32))),
                *[Tensor(jnp.asarray(a)) for a in samp],
                self.k_pools, self.v_pools,
                self.k_scales, self.v_scales)
        self.k_pools, self.v_pools = list(wk), list(wv)
        if self.kv_quant:
            self.k_scales, self.v_scales = list(wks), list(wvs)
        self._warmed_keys.add(("mixed", t_cap))
        self._warm_dispatches += 1
        self._record_shape("mixed", t_cap)
        self._note_mixed_bytes(t_cap)
        return True

    def _warm_scan(self, n):
        """Compile the n-tick decode-scan program via a dummy dispatch
        (trash tables, lens 1). The scan donates its pool inputs —
        reassign from the outputs."""
        b = self.max_batch
        sf = self._ensure_scan_compiled(int(n))
        samp = self._sample_arrays([], b)
        with no_grad():
            out = sf(Tensor(jnp.asarray(np.zeros((b, 1), np.int64))),
                     Tensor(jnp.asarray(np.full(
                         (b, self.width), self.trash_page, np.int32))),
                     Tensor(jnp.asarray(np.ones((b,), np.int32))),
                     *[Tensor(jnp.asarray(a)) for a in samp],
                     self.k_pools, self.v_pools,
                     self.k_scales, self.v_scales)
        self._adopt_scan_pools(out)
        self._warmed_keys.add(("scan", int(n)))
        self._warm_dispatches += 1

    def _adopt_scan_pools(self, out):
        """Reassign the donated pool (and scale-sidecar) arrays a scan
        dispatch returned after its token block."""
        nl = len(self.k_pools)
        self.k_pools = list(out[1:1 + nl])
        self.v_pools = list(out[1 + nl:1 + 2 * nl])
        if self.kv_quant:
            self.k_scales = list(out[1 + 2 * nl:1 + 3 * nl])
            self.v_scales = list(out[1 + 3 * nl:1 + 4 * nl])

    def prewarm(self, mixed=None, scans=None):
        """Compile this engine's serving programs BEFORE traffic
        arrives, so a replacement replica's first request pays
        milliseconds, not the full compile bill. With no arguments the
        recipe comes from the persistent shape registry — the
        mixed-program token shapes and decode-scan tick counts an
        engine of identical geometry actually dispatched (recorded as
        they compiled). Combined with the persistent compilation cache
        these compiles are disk hits on a warm host
        (``compile_cache_hit_total``), which is what turns an ~19 s
        restart into seconds.

        Returns ``{"mixed": [...], "scan": [...]}`` — what was warmed
        (also kept on ``self.prewarmed``)."""
        if mixed is None and scans is None:
            recipe = {}
            try:
                recipe = _cw.shape_registry().lookup(self._shape_key) \
                    if self._cache_dir is not None else {}
            except Exception:
                recipe = {}
            mixed = recipe.get("mixed", ())
            scans = recipe.get("scan", ())
        done = {"mixed": [], "scan": []}
        with self._dispatch_lock, _CROSS_ENGINE_LOCK, \
                _span("serving.prewarm", mixed=len(mixed or ()),
                      scan=len(scans or ())):
            for t_cap in sorted(set(mixed or ())):
                if self._warm_mixed(int(t_cap)):
                    done["mixed"].append(int(t_cap))
            for n in sorted(set(scans or ())):
                self._warm_scan(int(n))
                done["scan"].append(int(n))
        self.prewarmed = done
        return done

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def prefill_backlog(self):
        """Prompt tokens admitted but not yet written to pages — the
        chunked scheduler's pending prefill work. A routing signal for
        the cluster's load-aware router: a replica chewing through a
        long prompt is busier than its live count suggests."""
        with self._lock:
            return sum(max(0, len(r.prompt_ids) - r._prefilled)
                       for r in self._live.values() if not r.done)

    def _set_pool_gauges(self):
        self._m["queue_depth"].set(len(self._live))
        self._m["prefill_backlog"].set(self.prefill_backlog())
        self._m["kv_util"].set(
            1.0 - self.alloc.free_pages / self.alloc.num_pages)
        if _om.enabled():
            # per-dispatch device-memory accounting (host metadata walks
            # only, no sync), throttled so the live-array enumeration
            # never rides the per-token decode path, + a rate-limited
            # flight-recorder snapshot
            _cw.sample_device_memory(min_interval=1.0)
            _fr.periodic_snapshot()

    def _validate(self, req):
        cap_pages = min(self.alloc.max_pages_per_seq, self.alloc.num_pages)
        max_prompt = cap_pages * self.page_size
        n = len(req.prompt_ids)
        if n > max_prompt:
            raise ValueError(
                f"prompt of {n} tokens exceeds this engine's KV capacity "
                f"of {max_prompt} tokens ({cap_pages} pages x "
                f"{self.page_size} slots); split the prompt or size the "
                f"pool up (num_pages/max_pages_per_seq)")
        sp = req.sampling
        if sp is not None:
            if not sp.is_greedy and not self.sample_enabled:
                raise ValueError(
                    "request asks for sampled decoding but this engine "
                    "was built with sampling=False; rebuild with "
                    "sampling=True (or unset PADDLE_TPU_SAMPLING=0)")
            if sp.logit_bias and len(sp.logit_bias) > self.sample_slots:
                raise ValueError(
                    f"logit_bias has {len(sp.logit_bias)} entries but "
                    f"this engine packs sample_slots={self.sample_slots}"
                    f" per row; raise sample_slots or trim the bias")

    def _retry_after(self):
        """Seconds until capacity plausibly frees: the live set's
        shortest remaining token budget x recent median per-token
        latency. Falls back to one backoff quantum without history."""
        with self._lock:
            live = [r for r in self._live.values() if not r.done]
            times = sorted(self._token_times)
        if not live or not times:
            return max(self.admit_backoff, 0.005)
        remaining = min(max(1, r.max_new_tokens - len(r.output_ids))
                        for r in live)
        return round(remaining * times[len(times) // 2], 4)

    def _try_reserve(self, req):
        """One admission attempt: capacity check, page reservation and
        live-set insertion are ONE atomic transition under the engine
        lock, so two admitting threads can never push the live set past
        ``max_batch`` between a check and an insert. Returns a failure
        reason or None."""
        try:
            # outside the lock: a hang/sleep fault must not wedge the
            # engine lock, and an injected MemoryError rides the same
            # pool-exhausted path the real allocator raises
            _faults.fire("serve.admit", step=self._dispatch_count)
        except MemoryError:
            return "KV page pool exhausted"
        with self._lock:
            if self._draining:
                return "draining"
            if len(self._live) >= self.max_batch:
                return "engine full"
            n = len(req.prompt_ids)
            if self.tier is not None and self.prefix is not None:
                # demoted prefix pages promote back BEFORE the match,
                # so a system prompt that rode out pressure in host
                # DRAM is a cache hit, not a re-prefill
                self._promote_prefix(req.prompt_ids, n)
            cached = 0
            val_retries = 0
            evicted_cache = False
            recorded = False
            while True:
                shared, cached = ([], 0)
                if self.prefix is not None:
                    # stats recorded once per admission, not per retry
                    shared, cached = self.prefix.match(
                        req.prompt_ids, record=not recorded)
                    recorded = True
                try:
                    self.alloc.admit(req.seq_id, n, shared_pages=shared)
                    break
                except ValueError:
                    # a concurrent prefix.clear()/eviction freed the
                    # matched pages between match and admit: re-match
                    # and retry (a ValueError with NO shared pages is
                    # a genuine validation error and propagates)
                    if shared and val_retries < 2:
                        val_retries += 1
                        continue
                    raise
                except MemoryError:
                    # cached prefixes are an optimization, never a
                    # reason to shed load: give cold cache pages back
                    # to the pool and retry once (the retry re-matches
                    # — eviction may have taken this prompt's chain)
                    if evicted_cache or self.prefix is None:
                        return "KV page pool exhausted"
                    evicted_cache = True
                    need = max(1, math.ceil(n / self.page_size))
                    while self.alloc.free_pages < need \
                            and self.prefix.pages:
                        self.prefix.evict_pages(need
                                                - self.alloc.free_pages)
                    if self.alloc.free_pages < need:
                        return "KV page pool exhausted"
            req._cached_tokens = cached
            # stamp the prefill cursor BEFORE the request becomes
            # visible in _live: a concurrent dispatch thread must never
            # see a warm request at _prefilled 0 and schedule chunks
            # over its still-shared cached-prefix pages
            req._prefilled = cached
            self._live[req.seq_id] = req
            req.status = "live"
            if self.prefix is not None:
                self._m["prefix_lookups"].inc()
                if cached:
                    self._m["prefix_hits"].inc()
                    self._m["prefix_saved"].inc(cached)
                self._m["prefix_pages"].set(self.prefix.pages)
        return None

    def _degrade_trim(self, req, tried):
        """Ladder rung 1: truncate the lowest-priority victim's
        ``max_new_tokens`` to what it already produced, retiring it NOW
        with partial output (status ``completed``, ``trimmed=True``) —
        frees its batch slot and pages without discarding work."""
        with self._lock:
            victims = [r for r in self._live.values()
                       if not r.done and r.priority < req.priority
                       and r.output_ids and r.seq_id not in tried]
            if not victims:
                return False
            v = min(victims, key=lambda r: (r.priority, len(r.output_ids)))
            tried.add(v.seq_id)
            self._trim(v)
        return True

    def _trim(self, v):
        """Shared trim bookkeeping: truncate the victim's budget to what
        it already produced and retire it NOW (partial output kept,
        ``trimmed=True``). Caller holds the engine lock."""
        v.max_new_tokens = max(1, len(v.output_ids))
        v.trimmed = True
        if self._retire(v, "completed"):
            self._m["completed"].inc()
            self._m["degraded"].labels("trim").inc()

    def _evict(self, v):
        """Shared eviction bookkeeping: reclaim the victim's pages and
        re-queue it against its ``retry_budget`` (a re-admission
        restarts generation from scratch — its KV is gone) or fail it
        typed when the budget is spent. A victim that turned terminal
        (or was already requeued) since selection is left alone."""
        with self._lock:
            if v.done or v.seq_id is None:
                return
            if v.seq_id in self._live:
                del self._live[v.seq_id]
            self._spec_state.pop(v.seq_id, None)
            self._release_pages(v.seq_id)
            self._requeue_or_fail(v)

    def _requeue_or_fail(self, v):
        """Shared evict epilogue (the ladder's evict rung AND the host
        tier's failed-restore fallback): park the victim for a
        from-scratch retry against its ``retry_budget``, or fail it
        typed when the budget is spent. Caller holds the engine lock
        and has already released/returned the victim's pages."""
        if v.retry_budget > 0:
            v.retry_budget -= 1
            v.output_ids = []
            v.status = "requeued"
            v._t_admit = None
            v._expires_at = None
            v._cached_tokens = 0    # re-matched at re-admission
            v._prefilled = 0        # KV is gone; prefill restarts
            # a fresh seq_id on re-admission: the old id may still
            # have a deferred page release in flight
            v.seq_id = None
            self._requeue.append(v)
        else:
            v.done = True
            v.status = "evicted"
            v.error = AdmissionError(
                "evicted under pressure; retry budget exhausted",
                live=len(self._live), max_batch=self.max_batch,
                free_pages=self.alloc.free_pages,
                num_pages=self.alloc.num_pages, retries=0)
        self._m["degraded"].labels("evict").inc()

    def _degrade_evict(self, req):
        """Ladder rung 2: evict the lowest-priority victim — pages
        reclaimed; the victim restarts from scratch via the requeue
        (``retry_budget`` permitting) or fails typed."""
        with self._lock:
            victims = [r for r in self._live.values()
                       if not r.done and r.priority < req.priority]
            if not victims:
                return False
            v = min(victims, key=lambda r: (r.priority, len(r.output_ids)))
            self._evict(v)
        return True

    # ------------------------------------------------------------------
    # host-DRAM KV page tier: the pause rung (ROADMAP item 5a)
    # ------------------------------------------------------------------
    def _pause(self, v):
        """The ladder's pause rung: D2H-export the victim's pages into
        the host tier, release the HBM pages, and park the request
        ``paused`` on the requeue — the evict rung minus the destroyed
        work (output, prefill progress, seed and retry budget all
        survive; the deadline clock keeps ticking while parked). Any
        tier failure is typed and degrades to :meth:`_evict` — never a
        wedge, never a leak. Caller holds the engine lock."""
        if self.tier is None:
            self._evict(v)
            return
        with self._lock:
            if v.done or v.seq_id is None:
                return
            try:
                table, n_tokens = self.alloc.export_table(v.seq_id)
            except KeyError:
                self._evict(v)
                return
            try:
                key = self.tier.export_seq(
                    self.k_pools, self.v_pools, self.k_scales,
                    self.v_scales, table, n_tokens,
                    step=self._dispatch_count)
            except TierError:
                self._evict(v)
                return
            if v.seq_id in self._live:
                del self._live[v.seq_id]
            self._spec_state.pop(v.seq_id, None)
            self._release_pages(v.seq_id)
            v._tier_key = key
            v._tier_tokens = n_tokens
            v.status = "paused"
            # a fresh seq_id at resume: the old id may still have a
            # deferred page release in flight (same rule as _evict)
            v.seq_id = None
            self._requeue.append(v)
            self._m["paused"].inc()
            self._m["degraded"].labels("pause").inc()

    def _degrade_pause(self, req):
        """Ladder rung between cache-reclaim and trim (requires the
        host tier): pause the lowest-priority victim — frees its batch
        slot and pages WITHOUT destroying its work. Returns True when
        a victim left the live set (even if its export failed and the
        pause degraded to an evict: capacity was freed either way)."""
        if self.tier is None:
            return False
        with self._lock:
            victims = [r for r in self._live.values()
                       if not r.done and r.priority < req.priority]
            if not victims:
                return False
            v = min(victims,
                    key=lambda r: (r.priority, len(r.output_ids)))
            self._pause(v)
        return True

    def _tier_discard(self, req):
        """Free a parked request's host-tier copy (a cancel, deadline
        expiry, or drain ended its pause). Idempotent — racing a
        resume that already consumed the entry is a no-op."""
        key = req._tier_key
        if key is None or self.tier is None:
            return
        req._tier_key = None
        req._tier_tokens = 0
        self.tier.free(key)

    def _try_resume(self, req):
        """Resume one paused request at a boundary: fresh exclusively
        owned pages via :meth:`PageAllocator.import_table`, H2D
        restore (CRC-verified per page) into them, rejoin the live set
        with output/prefill progress intact — the remaining tokens are
        bitwise what an uninterrupted run produces. Returns False when
        capacity is short: the request is re-parked at the FRONT and
        the pump stops for this boundary. A failed or torn restore
        falls back to the evict→requeue path (host copy freed,
        from-scratch retry against the retry budget) — typed, never
        wedged, never leaked."""
        with self._lock:
            if req._cancel_requested and not req.done:
                req.done = True
                req.status = "cancelled"
                self._m["cancelled"].inc()
            if req.done:
                self._tier_discard(req)
                return True
            expired = (req._expires_at is not None
                       and time.perf_counter() >= req._expires_at)
        if expired:
            self._expire(req)
            self._tier_discard(req)
            return True
        with self._lock:
            if len(self._live) >= self.max_batch:
                self._requeue.appendleft(req)
                return False
            sid = self._next_id
            try:
                self.alloc.import_table(sid, req._tier_tokens)
            except MemoryError:
                self._requeue.appendleft(req)
                return False
            self._next_id += 1
            table = list(self.alloc._tables[sid])
            try:
                (self.k_pools, self.v_pools, self.k_scales,
                 self.v_scales) = self.tier.restore_seq(
                    req._tier_key, self.k_pools, self.v_pools,
                    self.k_scales, self.v_scales, table,
                    step=self._dispatch_count)
            except TierError:
                # the pre-tier behavior: fresh pages back to the pool,
                # from-scratch retry (or a typed terminal failure)
                self._release_pages(sid)
                req._tier_key = None
                req._tier_tokens = 0
                self._requeue_or_fail(req)
                return True
            req._tier_key = None
            req._tier_tokens = 0
            req.seq_id = sid
            req.status = "live"
            self._live[sid] = req
            self._m["resumed"].inc()
        return True

    def _demote_prefix_page(self, key, parent, page):
        """Prefix-cache evict hook: D2H-copy ONE cold cached page into
        the host tier before its last reference drops, so a hot system
        prompt survives pool pressure without re-prefill. Raises
        :class:`TierError` on a failed copy — the cache swallows it
        (demotion is best-effort; the old behavior IS dropping the
        page)."""
        self.tier.put_prefix(
            key.hex(), parent.hex() if parent is not None else None,
            self.k_pools, self.v_pools, self.k_scales, self.v_scales,
            page, step=self._dispatch_count)

    def _promote_prefix(self, prompt_ids, n_tokens):
        """Host-tier prefix promotion: extend this prompt's in-HBM
        cached chain with demoted pages the host tier still holds.
        Best-effort — promotion only spends SURPLUS pages (the
        admission's own page need plus one stays untouched) and any
        tier failure just leaves the cold path (the chain re-prefills).
        Caller holds the engine lock."""
        tier = self.tier
        if tier is None or self.prefix is None:
            return
        from .prefix_cache import chain_keys
        keys = chain_keys(prompt_ids, self.page_size)
        if not keys:
            return
        cached_pages, _ = self.prefix.match(prompt_ids, record=False)
        j = len(cached_pages)
        need = max(1, math.ceil(n_tokens / self.page_size))
        while j < len(keys):
            key = keys[j]
            if not tier.has_prefix(key.hex()):
                break
            if self.alloc.free_pages <= need + 1:
                break
            try:
                page = self.alloc.take_pages(1)[0]
            except MemoryError:
                break
            try:
                (self.k_pools, self.v_pools, self.k_scales,
                 self.v_scales) = tier.restore_prefix(
                    key.hex(), self.k_pools, self.v_pools,
                    self.k_scales, self.v_scales, page,
                    step=self._dispatch_count)
            except TierError:
                self.alloc.decref(page)
                break
            if not self.prefix.pin(key, page, parent=keys[j - 1]
                                   if j > 0 else None, depth=j):
                # someone re-cached this link meanwhile: give the
                # promoted page back (the cached one wins)
                self.alloc.decref(page)
            j += 1

    def _relieve_pressure(self, live, n):
        """Decode-boundary rung of the degradation ladder: when the
        pool cannot hold the next ``n`` tokens for every live sequence,
        pause (host tier on) or evict the lowest-priority (then
        least-progressed) victim until the rest fit — shed or degrade,
        never crash mid-step with a torn allocator. Returns the
        surviving live list. Caller holds the engine lock."""
        page = self.page_size
        live = list(live)
        # a sequence about to cross its per-seq table cap can NEVER
        # take this step, and a retry would deterministically hit the
        # same wall — trim it (retire with the output it produced,
        # ``trimmed=True``) rather than burn its retry budget on full
        # regenerations or let alloc.extend raise mid-loop
        for r in list(live):
            need_pages = -(-(self.alloc._lens[r.seq_id] + n) // page)
            if need_pages > self.alloc.max_pages_per_seq:
                live.remove(r)
                self._trim(r)
        # while another thread is mid-entry, victim releases would be
        # DEFERRED — evicting could not free a single page, so victims
        # are merely POSTPONED from this dispatch (no state change;
        # they rejoin at the next boundary, after the flush)
        me = threading.current_thread()
        deferrals_blocked = self._in_dispatch \
            or any(t is not me for t in self._entry_threads)
        while live:
            need = sum(
                max(0, -(-(self.alloc._lens[r.seq_id] + n) // page)
                    - len(self.alloc._tables[r.seq_id]))
                for r in live)
            if need <= self.alloc.free_pages:
                break
            # cold prefix-cache pages go back to the pool BEFORE any
            # live request is destroyed — same contract as admission
            if self.prefix is not None and self.prefix.pages \
                    and self.prefix.evict_pages(
                        need - self.alloc.free_pages):
                continue
            v = min(live, key=lambda r: (r.priority, len(r.output_ids)))
            live.remove(v)
            if not deferrals_blocked:
                if self.tier is not None:
                    self._pause(v)
                else:
                    self._evict(v)
            else:
                # POSTPONE: no state change — the row sits this
                # dispatch out and rejoins at the next boundary
                self._m["postponed"].inc()
        return live

    def _pump_requeue(self):
        """Continuous-batching re-admission at step boundaries:
        requests the ladder parked on the requeue rejoin the batch as
        capacity allows, so plain ``add_request()`` + ``step()``
        drivers (no :meth:`generate` loop) never strand an evicted
        request in limbo. Re-admitted prompts prefill as ordinary
        chunks of the very next mixed dispatch — no separate wave."""
        while True:
            with self._lock:
                if self._draining or not self._requeue \
                        or len(self._live) >= self.max_batch:
                    break
                nxt = self._requeue.popleft()
            if nxt.done:
                self._tier_discard(nxt)
                continue
            if nxt._tier_key is not None:
                # paused: resume is an H2D restore into fresh pages,
                # not a re-admission — no prefill, no ladder walk
                if not self._try_resume(nxt):
                    break
                continue
            try:
                # quiet probe: no backoff sleeps inside the dispatch
                # lock (the pump retries at the next boundary anyway)
                # and a re-park is not a shed for the metrics
                self._admit_locked(nxt, quiet_retry=True)
            except AdmissionError:
                with self._lock:
                    self._requeue.appendleft(nxt)
                break
        # hint the tier at the NEXT resume candidate so its CRC verify
        # + device put overlap the coming decode dispatches
        if self.tier is not None:
            with self._lock:
                head = next((r for r in self._requeue
                             if not r.done and r._tier_key is not None),
                            None)
            if head is not None:
                self.tier.stage(head._tier_key)

    def _admit(self, req):
        """Admit one request, walking the degradation ladder under
        pressure: trim -> evict -> (bounded backoff) -> shed with a
        ``retry_after`` hint. Raises :class:`ValueError` for requests
        that can NEVER fit (prompt longer than the pool) and
        :class:`AdmissionError` for transient pressure."""
        with self._entry():
            return self._admit_locked(req)

    def _admit_locked(self, req, quiet_retry=False):
        with self._lock:
            if req._cancel_requested and not req.done:
                # a client abandon raced an eviction/re-admission:
                # honor it here instead of decoding for nobody
                req.done = True
                req.status = "cancelled"
                self._m["cancelled"].inc()
                return req.seq_id
        if req.done:
            return req.seq_id
        self._validate(req)
        self._expire_deadlines()      # expired requests free capacity
        with self._lock:
            if req.seq_id is None:
                req.seq_id = self._next_id
                self._next_id += 1
            if req._seed is None:
                sp = req.sampling
                if sp is not None and sp.seed is not None:
                    req._seed = sp.seed
                else:
                    # auto-seed once per request (stable across ladder
                    # evictions/re-admissions so a regenerated request
                    # redraws the same sequence) and record it for
                    # after-the-fact reproducibility
                    self._auto_seed = (self._auto_seed * 1103515245
                                       + 12345) % (2 ** 31)
                    req._seed = self._auto_seed
        attempt = 0
        trim_tried: set[int] = set()
        while True:
            reason = self._try_reserve(req)
            if reason is None:
                break
            # while a dispatch is in flight — or any other thread is
            # mid-entry — victim page releases are DEFERRED, so
            # trimming/evicting cannot free pages yet and destroying
            # lower-priority work would gain nothing; fall through to
            # backoff (which can observe the post-entry flush) or shed
            me = threading.current_thread()
            with self._lock:
                pages_blocked = (
                    reason == "KV page pool exhausted"
                    and (self._in_dispatch
                         or any(t is not me
                                for t in self._entry_threads)))
            if reason != "draining" and not pages_blocked:
                # rung order: cache-reclaim (inside _try_reserve) →
                # pause → trim → evict → backoff → shed
                if self._degrade_pause(req):
                    continue
                if self._degrade_trim(req, trim_tried):
                    continue
                if self._degrade_evict(req):
                    continue
            if reason != "draining":
                if not quiet_retry and attempt < self.admit_retries:
                    # bounded backoff: a concurrent step()/scan may
                    # retire a request and release its pages before the
                    # retry
                    attempt += 1
                    self._m["admit_retries"].inc()
                    time.sleep(self.admit_backoff * (2 ** (attempt - 1)))
                    continue
                if not quiet_retry:
                    # drain gating and the requeue pump's boundary
                    # probes are not capacity pressure: only real
                    # pressure rejections feed the evicted/shed metrics
                    self._m["evicted"].inc()
                    self._m["degraded"].labels("shed").inc()
            raise AdmissionError(
                reason, live=len(self._live),
                max_batch=self.max_batch,
                free_pages=self.alloc.free_pages,
                num_pages=self.alloc.num_pages, retries=attempt,
                retry_after=self._retry_after())
        # _try_reserve already made the request live; stamp the clocks
        now = time.perf_counter()
        with self._lock:
            req._t_admit = now
            ttl = None
            if req.deadline is not None:
                ttl = req.deadline
            if req.token_budget is not None:
                budget = req.token_budget * req.max_new_tokens
                ttl = budget if ttl is None else min(ttl, budget)
            req._expires_at = None if ttl is None else now + ttl
        self._m["admitted"].inc()
        # prefill_tokens counts per APPLIED chunk in _dispatch_rows —
        # under chunked prefill, admission no longer implies the work
        self._set_pool_gauges()
        return req.seq_id

    def add_request(self, req):
        """Admit a request and drive the chunked prefill through to its
        first emitted token (the admission-prefills-immediately
        contract; live decodes ride along in the same mixed dispatches,
        chunk by chunk). Returns its seq_id."""
        sid = self._admit(req)
        while not req.done and req._prefilled < len(req.prompt_ids):
            if self.step() == 0:
                break       # nothing dispatchable (drained/expired)
        return sid

    def _emit(self, req, token):
        first = not req.output_ids
        if first and req._t_admit is not None:
            ttft = time.perf_counter() - req._t_admit
            self._m["ttft"].observe(ttft)
            # a zero-width marker node in the request's distributed
            # trace: where the first token landed, on which pid
            rctx = getattr(req, "_trace", None)
            if rctx is not None:
                with _tracing.activate(rctx), \
                        _span("serving.first_token",
                              ttft_seconds=round(ttft, 6)):
                    pass
        # stop tokens are checked BEFORE the append: the request
        # retires ``completed`` with the stop token excluded from its
        # output (the chat-endpoint contract; eos keeps its legacy
        # include-then-stop behavior)
        if req.stop_set and token in req.stop_set:
            if self._retire(req, "completed"):
                self._m["completed"].inc()
                self._m["stop_hits"].inc()
            return
        req.output_ids.append(token)
        self._m["generated"].inc()
        cb = req.on_token
        if cb is not None:
            try:
                cb(req, token)
            except Exception:
                pass        # streaming hooks must never kill a dispatch
        if (req.eos_token_id is not None and token == req.eos_token_id) \
                or len(req.output_ids) >= req.max_new_tokens:
            if self._retire(req, "completed"):
                self._m["completed"].inc()
        # pool gauges are refreshed once per dispatch by the
        # caller, not per emitted token — only the post-loop value is
        # observable anyway

    def step(self):
        """Advance the engine by ONE mixed dispatch: every live
        fully-prefilled sequence decodes one token and pending prompt
        chunks pack into the remaining ``chunk_budget``. Returns the
        number of rows dispatched (0 = nothing live)."""
        return self._mixed_step()[0]

    @_fatal_guard("serving.step")
    def _mixed_step(self):
        """One mixed dispatch. Returns (rows dispatched, tokens
        emitted) — a dispatch that only advanced mid-prompt chunks
        reports rows > 0 with emitted == 0."""
        with self._entry(), self._dispatch_lock, _CROSS_ENGINE_LOCK:
            self._expire_deadlines()
            self._pump_requeue()
            with self._lock:
                if not any(not r.done for r in self._live.values()):
                    return 0, 0
            # before any allocator mutation: an injected raise aborts
            # the dispatch cleanly instead of leaving lens advanced
            # with no K/V written
            _faults.fire("serve.decode", step=self._dispatch_count)
            self._dispatch_count += 1
            with self._lock:
                # rows are snapshotted under the lock: a concurrent
                # cancel/evict may null seq_id or swap output_ids
                # mid-setup, but this dispatch keeps reading its own
                # consistent view (the pages stay reserved —
                # cross-thread releases defer past _entry); the decode
                # extends happen while still holding the lock, so a
                # concurrent admission can't consume the pages between
                # _relieve_pressure's proof and the extend
                rows, cow = self._schedule_rows()
            if not rows:
                return 0, 0
            emitted = self._dispatch_rows(rows, cow)
            self._expire_deadlines()
            self._set_pool_gauges()
            return len(rows), emitted

    # ------------------------------------------------------------------
    # decode scan: n all-decode ticks = ONE compiled program (lax.scan)
    # ------------------------------------------------------------------
    def _decode_scan_fn(self, n):
        """Build the n-tick decode scan: ``lax.scan`` whose body is the
        SAME Tensor-level :meth:`_mixed_forward` specialized to the
        decode-only shape (T == R == max_batch, QB == 1) — parity with
        the per-step program is by construction, and the dispatch path
        stays singular. The carry is (tokens, lens, pools); tables are
        scan-invariant because pages for the whole run are reserved
        before launch; per-tick write positions derive from the length
        carry on device."""
        import jax

        page = self.page_size

        def fn(tokens, tables, lens, temps, top_ps, top_ks, seeds,
               slot_ids, slot_vals, cmodes, k_pools, v_pools, k_scales,
               v_scales):
            tab = tables._data
            b = tab.shape[0]
            kp = [x._data for x in k_pools]
            vp = [x._data for x in v_pools]
            ksp = [x._data for x in k_scales]
            vsp = [x._data for x in v_scales]
            rows = jnp.arange(b, dtype=jnp.int32)
            row_tok = rows.reshape(b, 1)
            ones = jnp.ones((b,), jnp.int32)
            # sampler params are scan-invariant per row; the fold
            # position advances with the length carry, so tick i of a
            # scan draws the SAME randomness the per-step path would
            samp = (temps, top_ps, top_ks, seeds, slot_ids, slot_vals,
                    cmodes)

            def body(carry, _):
                tok, lc, kc, vc, ksc, vsc = carry
                start = (lc - 1).astype(jnp.int32)
                pids = tab[rows, jnp.clip(start // page, 0,
                                          tab.shape[1] - 1)]
                offs = (start % page).astype(jnp.int32)
                nxt, nk, nv, nks, nvs = self._mixed_forward(
                    Tensor(tok.reshape(1, b)),
                    Tensor(start.reshape(1, b)),
                    Tensor(pids), Tensor(offs), Tensor(row_tok),
                    Tensor(rows), Tensor(rows), Tensor(tab),
                    Tensor(lc.astype(jnp.int32)), Tensor(start),
                    Tensor(ones),
                    # fused-write metadata for a decode tick: each row
                    # writes exactly its own one token, so the write
                    # span starts at the token's position, its packed
                    # index is the row index, and every row is its
                    # sequence's last (w_end == kv_len)
                    Tensor(start), Tensor(rows),
                    Tensor(lc.astype(jnp.int32)), *samp,
                    [Tensor(a) for a in kc], [Tensor(a) for a in vc],
                    [Tensor(a) for a in ksc], [Tensor(a) for a in vsc])
                nxt_arr = nxt._data.reshape(tok.shape).astype(tok.dtype)
                return ((nxt_arr, lc + 1,
                         [x._data for x in nk], [x._data for x in nv],
                         [x._data for x in nks], [x._data for x in nvs]),
                        nxt_arr[:, 0])

            (_, _, kf, vf, ksf, vsf), toks = jax.lax.scan(
                body, (tokens._data, lens._data, kp, vp, ksp, vsp),
                None, length=n)
            return (jnp.swapaxes(toks, 0, 1), *kf, *vf, *ksf, *vsf)

        return fn

    def _ensure_scan_compiled(self, n):
        sf = self._scan_static.get(n)
        if sf is None:
            from ..jit import StaticFunction

            # donate=False for the same reason as the mixed step: model
            # state is pass-through here, and donating same-aval weight
            # slots lets XLA alias them across each other
            sf = StaticFunction(self._decode_scan_fn(n),
                                state=[self.model], warmup="once",
                                donate=False, donate_inputs=True,
                                name=f"serving.mixed_scan[{n}]")
            # no lazy state to materialize (params exist; no optimizer):
            # skip the eager warmup — n scanned steps of per-op dispatch
            # would cost more than the compile it avoids
            sf._warmed_any = True
            self._scan_static[n] = sf
            self._record_shape("scan", n)
        return sf

    @_fatal_guard("serving.decode_scan")
    def _decode_scan(self, n):
        """Decode ``n`` tokens for every live (fully-prefilled) request
        in one dispatch. Pages for all n tokens are reserved up front;
        requests that retire mid-scan (EOS / max_new_tokens / expired
        deadline) have their tail tokens discarded at emit time —
        bounded waste, no correctness impact."""
        with self._entry(), self._dispatch_lock, _CROSS_ENGINE_LOCK:
            self._expire_deadlines()
            self._pump_requeue()
            with self._lock:
                if n <= 0 or not any(not r.done
                                     for r in self._live.values()):
                    return 0
            # as in step(): fire before any allocator mutation
            _faults.fire("serve.decode", step=self._dispatch_count)
            self._dispatch_count += 1
            with self._lock:
                live = [r for r in self._live.values() if not r.done
                        and r._prefilled >= len(r.prompt_ids)]
                live = self._relieve_pressure(live, n)
                sids = [r.seq_id for r in live]
                last_tok = [r.output_ids[-1] if r.output_ids
                            else int(r.prompt_ids[-1]) for r in live]
                # reserve the whole scan under the lock (see step())
                start_lens = {sid: self.alloc._lens[sid] for sid in sids}
                cow = []
                for sid in sids:
                    self.alloc.extend(sid, n)
                    # only the scan's FIRST write position can sit in
                    # a pre-existing (possibly shared) page; the rest
                    # land in pages this extend just allocated
                    cp = self.alloc.ensure_writable(sid, start_lens[sid])
                    if cp is not None:
                        cow.append(cp)
            if not live:
                return 0
            for old, new in cow:
                self._copy_page(old, new)
            # as in step(): each new scan length compiles on its first
            # call — don't let that land n inflated samples in tpot
            key = ("scan", n)
            cold = key not in self._warmed_keys
            t0 = time.perf_counter()
            b = self.max_batch
            tables = np.full((b, self.width), self.trash_page, np.int32)
            lens = np.ones((b,), np.int32)
            tokens = np.zeros((b, 1), np.int64)
            for i, sid in enumerate(sids):
                t = self.alloc._tables[sid]
                tables[i, :len(t)] = t
                lens[i] = start_lens[sid] + 1       # first new token incl.
                tokens[i, 0] = last_tok[i]
            (temps, top_ps, top_ks, seeds, slot_ids, slot_vals,
             cmodes) = self._sample_arrays(live, b)
            sf = self._ensure_scan_compiled(n)
            self._arm_watchdog(cold)
            with self._lock:
                self._in_dispatch = True
            try:
                with no_grad(), _span("serving.decode_scan",
                                      live=len(live), ticks=n):
                    out = sf(
                        Tensor(jnp.asarray(tokens)),
                        Tensor(jnp.asarray(tables)),
                        Tensor(jnp.asarray(lens)),
                        Tensor(jnp.asarray(temps)),
                        Tensor(jnp.asarray(top_ps)),
                        Tensor(jnp.asarray(top_ks)),
                        Tensor(jnp.asarray(seeds)),
                        Tensor(jnp.asarray(slot_ids)),
                        Tensor(jnp.asarray(slot_vals)),
                        Tensor(jnp.asarray(cmodes)),
                        self.k_pools, self.v_pools,
                        self.k_scales, self.v_scales)
            finally:
                with self._lock:
                    self._in_dispatch = False
                dur = time.perf_counter() - t0
                self._disarm_watchdog(dur, cold=cold)
                self._warmed_keys.add(key)
            self._flush_deferred()
            toks = out[0]
            self._adopt_scan_pools(out)
            all_tokens = np.asarray(toks._data)          # one D2H
            # one scan tick serves every live row: per-token latency is
            # the dispatch wall time amortized over the n ticks
            if not cold:
                tick = dur / n
                self._token_times.append(tick)
                for _ in range(n):
                    self._m["tpot"].observe(tick)
            served = 0
            for i, r in enumerate(live):
                for t in range(n):
                    # done: retired mid-scan (EOS / budget); seq_id
                    # mismatch: evicted + requeued mid-dispatch — the
                    # stale tail must not land in its cleared output
                    if r.done or r.seq_id != sids[i]:
                        break
                    self._emit(r, int(all_tokens[i, t]))
                    served += 1
            self._expire_deadlines()
            self._set_pool_gauges()
            return served

    def _scan_fits(self, live, n):
        """Largest scan <= n whose page reservations fit the pool and
        no sequence's per-seq table cap."""
        page = self.page_size
        for r in live:
            headroom = self.alloc.max_pages_per_seq * page \
                - self.alloc._lens[r.seq_id]
            if headroom < n:
                # shrink to the tightest per-seq headroom; a fully
                # capped sequence (headroom <= 0) is trimmed at the
                # next step boundary by _relieve_pressure
                n = max(1, headroom)
        while n > 1:
            need = sum(
                max(0, -(-(self.alloc._lens[r.seq_id] + n) // page)
                    - len(self.alloc._tables[r.seq_id]))
                for r in live)
            if need <= self.alloc.free_pages:
                break
            n //= 2
        return n

    def decode_many(self, n, exact=True):
        """``n`` decode steps for the current live set. While any live
        prompt still has unprefilled chunks the engine takes single
        mixed steps (chunks + decodes together); once the batch is all
        decode it switches to compiled scans — full
        :attr:`decode_ticks` runs, then ticks/4 runs, then single
        steps. With ``exact=False`` the tail may overshoot by up to
        ticks/4 - 1 — callers use this when every live request retires
        by step ``n`` (the overshot ticks are discarded at emit time),
        trading a few idle ticks for never paying the per-step dispatch
        round trip. Returns tokens served."""
        served = 0
        small = max(self.decode_ticks // 4, 2)
        while n > 0:
            with self._lock:
                # _scan_fits reads the allocator's per-seq state: hold
                # the lock so a concurrent evict can't null a seq_id
                # between the snapshot and the fit computation
                live = [r for r in self._live.values() if not r.done]
                if not live and not self._requeue:
                    break
                prefilling = any(r._prefilled < len(r.prompt_ids)
                                 for r in live)
                # constraint hooks are per-step host work: a scan's n
                # on-device ticks can't re-consult them, so constrained
                # traffic pins the engine to single mixed steps (static
                # logit_bias is scan-invariant and scans fine)
                constrained = any(
                    r.sampling is not None
                    and r.sampling.constraint is not None for r in live)
                spec_now = False
                if self.spec_k and not prefilling and live:
                    spec_now = self._spec_worth(live)
                    # the probe result paces scan escalation below: a
                    # drafter with nothing to say should not hold the
                    # engine at short scans forever
                    self._spec_idle = 0 if spec_now \
                        else self._spec_idle + 1
                if not live:
                    chunk = 1       # pump parked requests via a step
                elif prefilling or constrained:
                    chunk = 1
                elif spec_now:
                    # speculation rides the mixed step: one dispatch
                    # verifies k+1 tokens per row, which is the scan's
                    # amortization and more — the fixed-tick scan would
                    # force every row back to one token per tick. When
                    # the drafter has NOTHING (cold history, no
                    # repetition), fall through to scans and re-probe
                    # at their boundaries: speculation must never cost
                    # more than not speculating.
                    chunk = 1
                elif n >= self.decode_ticks and (not self.spec_k
                                                 or self._spec_idle >= 2):
                    # a speculative engine starts with SHORT scans so a
                    # repetition onset is caught within ticks/4 tokens,
                    # but repeated empty probes escalate to full scans
                    # — non-draftable traffic converges to the plain
                    # engine's dispatch amortization (probes still run
                    # at every scan boundary, so speculation resumes at
                    # most one scan after the history turns repetitive)
                    chunk = self._scan_fits(live, self.decode_ticks)
                elif n >= small or not exact:
                    chunk = self._scan_fits(live, small)
                else:
                    chunk = 1
            if chunk > 1:
                served += self._decode_scan(chunk)
                n -= chunk
            else:
                rows, emitted = self._mixed_step()
                if rows == 0:
                    break
                served += emitted
                n -= 1
        return served

    @_fatal_guard("serving.generate")
    def generate(self, prompts, max_new_tokens=16, eos_token_id=None):
        """Convenience batch API: admit all prompts (continuous batching
        handles ragged finish times), run to completion, return output id
        lists in order. Every pending request that fits is admitted and
        its prompt chunks pack into the shared mixed dispatches.
        Requests the ladder re-queued are re-admitted ahead of new ones."""
        reqs = [Request(p, max_new_tokens, eos_token_id) for p in prompts]
        pending = list(reqs)
        while pending or any(not r.done for r in reqs):
            while True:
                with self._lock:
                    if len(self._live) >= self.max_batch:
                        break
                    # requeue pops race _pump_requeue in a second driver
                    # thread: decide AND pop under the lock
                    from_requeue = bool(self._requeue)
                    nxt = self._requeue.popleft() if from_requeue \
                        else (pending.pop(0) if pending else None)
                if nxt is None:
                    break
                if nxt.done:
                    continue
                try:
                    self._admit(nxt)
                except AdmissionError:
                    if from_requeue:
                        # still under pressure: park it again (keeps the
                        # typed-terminal contract — never strand a
                        # popped request in non-terminal 'requeued')
                        with self._lock:
                            self._requeue.appendleft(nxt)
                        break
                    raise
            with self._lock:
                live = [r for r in self._live.values() if not r.done]
                prefilling = any(r._prefilled < len(r.prompt_ids)
                                 for r in live)
            if live:
                if prefilling:
                    # mixed steps until every admitted prompt is in:
                    # prefill chunks and live decodes share dispatches
                    self.step()
                    continue
                # scan until the earliest possible retirement; with EOS
                # or pending admissions cap at decode_ticks so a
                # retirement (and the admission it unblocks) is never
                # far away. The tail may overshoot (exact=False): every
                # live request retires by then, so overshot ticks are
                # discarded, never mis-emitted.
                run = min(r.max_new_tokens - len(r.output_ids)
                          for r in live)
                if pending or eos_token_id is not None:
                    run = min(run, self.decode_ticks)
                self.decode_many(max(1, run), exact=False)
                continue
            if not pending and all(r.done for r in reqs):
                break
        return [r.output_ids for r in reqs]

    # ------------------------------------------------------------------
    # graceful drain
    # ------------------------------------------------------------------
    @_fatal_guard("serving.drain")
    def drain(self, timeout=30.0):
        """Stop admission and retire the in-flight set: decode until
        every live request completes (EOS / max_new_tokens) or the
        grace ``timeout`` elapses, then expire the stragglers with a
        :class:`DeadlineExceeded` and release their pages. Admission
        stays closed afterwards (:class:`AdmissionError` reason
        ``"draining"``); call :meth:`resume_admission` to reopen.

        Returns ``{"seconds", "completed", "expired"}`` — requests that
        finished during the drain vs. those cut off at the window.
        """
        with self._lock:
            self._draining = True
            already = self._drain_active
            if not already:
                self._drain_active = True
        if already:
            # another thread's drain is mid-flight: wait it out within
            # our own budget rather than returning a misleading no-op
            # (a preemption exit riding on this return must not cut the
            # active drain's grace window short)
            t0 = time.perf_counter()
            while self._drain_active \
                    and time.perf_counter() - t0 < timeout:
                time.sleep(0.01)
            return {"seconds": time.perf_counter() - t0,
                    "completed": 0, "expired": 0}
        t0 = time.perf_counter()
        try:
            _faults.fire("serve.drain")
            with self._lock:
                start = [r for r in self._live.values() if not r.done]
            while True:
                self._expire_deadlines()
                with self._lock:
                    live = [r for r in self._live.values() if not r.done]
                if not live:
                    break
                if time.perf_counter() - t0 >= timeout:
                    for r in live:
                        self._expire(r, reason="drain grace window")
                    break
                self.step()
            # admission is closed, so requests parked on the requeue
            # (evicted under decode-boundary pressure) can never run
            # again — expire them typed rather than stranding them
            with self._lock:
                requeued = list(self._requeue)
                self._requeue.clear()
            for r in requeued:
                if not r.done:
                    self._expire(r, reason="drain grace window")
                # paused requests drain typed AND leak-free: the host
                # copy goes with them
                self._tier_discard(r)
            # everything that was live at entry is terminal now
            dur = time.perf_counter() - t0
            self._m["drain_seconds"].set(dur)
            self._set_pool_gauges()
            completed = sum(1 for r in start if r.status == "completed")
            expired = sum(1 for r in start
                          if r.status == "deadline_exceeded")
            return {"seconds": dur, "completed": completed,
                    "expired": expired}
        finally:
            with self._lock:
                self._drain_active = False
                pending = self._pending_drain
                self._pending_drain = None
            if pending is not None:
                # a preemption signal arrived while this drain ran: the
                # work is done, exit now
                self._run_drain_and_exit(*pending)

    def resume_admission(self):
        """Reopen admission after a :meth:`drain` (test/maintenance
        hook; a preemption-driven drain exits the process instead)."""
        with self._lock:
            self._draining = False

    def is_ready(self):
        """Readiness (distinct from liveness): False while draining or
        closed, so a load balancer stops sending BEFORE :meth:`drain`
        finishes. Wire it to the ``ready=`` probe of
        :func:`paddle_tpu.observability.export.start_http_server` to
        expose it as ``/readyz``."""
        with self._lock:
            return not (self._draining or self._closed)

    def _run_drain_and_exit(self, grace, exit_code, on_drained):
        stats = self.drain(grace)
        if on_drained is not None:
            try:
                on_drained(stats)
            except Exception:
                pass        # exiting anyway; the drain itself succeeded
        os._exit(exit_code)

    def install_drain_handler(self, grace=30.0,
                              signals=(_signal.SIGTERM,), exit_code=0,
                              on_drained=None):
        """Hook preemption signals (default SIGTERM) for a graceful
        drain: admission stops immediately; in-flight requests finish
        or expire within ``grace`` seconds; then the process exits with
        ``exit_code`` (default 0 — a drained exit is a clean exit). A
        signal landing while a dispatch is in flight defers the drain
        to the next step/scan boundary, so engine state is never
        torn mid-update — mirroring the checkpoint callback's deferred
        emergency save. ``on_drained(stats)`` runs just before exit
        (e.g. to flush metrics).

        Must be called from the main thread (CPython signal rule).
        Returns ``{signum: previous_handler}`` so callers can restore.
        """
        prev = {}

        def _handler(signum, frame):
            with self._lock:
                self._draining = True
                if self._drain_active or self._entry_depth > 0 \
                        or self._flushing:
                    # a manual drain is running or an entry is in
                    # flight: record the exit request — drain's
                    # epilogue / the entry boundary executes it
                    self._pending_drain = (grace, exit_code, on_drained)
                    return
            self._run_drain_and_exit(grace, exit_code, on_drained)

        for s in signals:
            prev[s] = _signal.signal(s, _handler)
        return prev
