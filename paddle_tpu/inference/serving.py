"""Continuous-batching serving engine for the Llama family.

Reference capability: the reference's serving path — AnalysisPredictor +
paged `block_multi_head_attention` / `masked_multihead_attention`
kernels (`fluid/inference/api/analysis_predictor.h:100`,
`phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu`). The
reference has no in-tree continuous-batching scheduler; this engine goes
beyond it (vLLM-style): requests are admitted and retired on the fly,
every live sequence decodes one token per engine step in a single
batched program, and KV lives in a shared paged pool so ragged contexts
waste no HBM.

Design (TPU-first):
- ONE :class:`PageAllocator` shared by all layers (page structure is
  identical per layer); per-layer K/V pools are device arrays updated
  functionally.
- Prefill runs the model's own submodules densely (flash/XLA attention)
  while collecting post-rope K/V per layer, then scatters them into
  pages — per request, compiled per prompt-length bucket.
- The decode step is ONE ``to_static`` program of static shape
  [max_batch]: embed → per layer (rms_norm → qkv → rope at per-row
  positions → page write → Pallas ``paged_attention`` → o_proj →
  swiglu MLP) → logits → greedy argmax. Inactive batch slots point at a
  reserved trash page with length 1, so shapes never change and the
  executable is reused for the engine's lifetime.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, no_grad, run_op
from ..incubate.nn import functional as FI
from ..nn import functional as F
from ..ops.paged_attention import paged_attention
from .paged_cache import PageAllocator

__all__ = ["LlamaServingEngine", "Request"]


def _dynamic_take(x, pos):
    """x[:, pos:pos+1, :] with a traced scalar ``pos``."""
    import jax

    def fn(x, pos):
        return jax.lax.dynamic_slice_in_dim(x, pos, 1, axis=1)

    return run_op("dynamic_take", fn, (x, pos), differentiable=False)


def _page_write(pages, new, page_ids, offs):
    """Functional scatter of ``new [B, Hk, D]`` into head-major ``pages
    [P, Hk, page, D]`` at (page_ids[b], h, offs[b]) — one token per live
    sequence."""
    def fn(pages, new, page_ids, offs):
        hidx = jnp.arange(pages.shape[1])[None, :]
        return pages.at[page_ids[:, None], hidx, offs[:, None]].set(
            new.astype(pages.dtype))

    return run_op("paged_kv_write", fn, (pages, new, page_ids, offs),
                  differentiable=False)


def _page_write_seq(pages, new, page_ids, offs):
    """Scatter a whole sequence ``new [S, Hk, D]`` into ``pages`` at
    (page_ids[s], h, offs[s]) — the prefill write, inside the compiled
    program (trash-page tail entries absorb the bucket padding)."""
    def fn(pages, new, page_ids, offs):
        hidx = jnp.arange(pages.shape[1])[None, :]
        return pages.at[page_ids[:, None], hidx, offs[:, None]].set(
            new.astype(pages.dtype))

    return run_op("paged_kv_write_seq", fn, (pages, new, page_ids, offs),
                  differentiable=False)


class Request:
    """One generation request (seq_id is assigned by the engine)."""

    def __init__(self, prompt_ids, max_new_tokens=16, eos_token_id=None):
        self.prompt_ids = np.asarray(prompt_ids, np.int64).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.output_ids: list[int] = []
        self.seq_id = None
        self.done = False


class LlamaServingEngine:
    def __init__(self, model, max_batch=4, page_size=16, num_pages=128,
                 max_pages_per_seq=None):
        self.model = model
        cfg = model.config
        self.max_batch = max_batch
        self.page_size = page_size
        # page num_pages-1 is the trash page for inactive batch slots
        self.alloc = PageAllocator(num_pages - 1, page_size,
                                   max_pages_per_seq)
        self.width = self.alloc.max_pages_per_seq
        self.trash_page = num_pages - 1
        dt = model.parameters()[0].dtype
        hk, d = cfg.num_key_value_heads, cfg.head_dim
        # head-major [P, Hk, page, D] — the Pallas kernel's tiling layout
        shape = (num_pages, hk, page_size, d)
        self.k_pools = [Tensor(jnp.zeros(shape, jnp.dtype(str(dt))))
                        for _ in range(cfg.num_hidden_layers)]
        self.v_pools = [Tensor(jnp.zeros(shape, jnp.dtype(str(dt))))
                        for _ in range(cfg.num_hidden_layers)]
        self._live: dict[int, Request] = {}
        self._next_id = 0
        self._decode_static = None
        self._prefill_static = None

    # ------------------------------------------------------------------
    # prefill
    # ------------------------------------------------------------------
    def _prefill_forward(self, ids, last_pos, page_ids, offs, k_pools,
                         v_pools):
        """Dense forward of one prompt [1, Sb] (bucket-padded; causal
        attention keeps the padded tail from touching the real prefix)
        that also scatters the post-rope K/V into the page pools INSIDE
        the compiled program (one XLA call per request; the bucket
        padding's scatter targets are the trash page). ``last_pos`` is a
        traced scalar so every prompt length in the bucket shares one
        program. Returns (next token id, new k_pools, new v_pools)."""
        from ..tensor import creation, search

        m = self.model.model
        cfg = self.model.config
        b, s = ids.shape[0], ids.shape[1]
        pos = creation.arange(0, s, dtype="int64").reshape([1, s])
        x = m.embed_tokens(ids)
        new_k, new_v = [], []
        for li, layer in enumerate(m.layers):
            h = layer.input_layernorm(x)
            att = layer.self_attn
            q = att.q_proj(h).reshape([b, s, att.num_heads, att.head_dim])
            k = att.k_proj(h).reshape([b, s, att.num_kv_heads, att.head_dim])
            v = att.v_proj(h).reshape([b, s, att.num_kv_heads, att.head_dim])
            q, k, v = FI.fused_rotary_position_embedding(
                q, k, v, position_ids=pos, rotary_emb_base=cfg.rope_theta)
            new_k.append(_page_write_seq(k_pools[li], k[0], page_ids, offs))
            new_v.append(_page_write_seq(v_pools[li], v[0], page_ids, offs))
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            x = x + att.o_proj(out.reshape([b, s, -1]))
            x = x + layer.mlp(layer.post_attention_layernorm(x))
        x = m.norm(x)
        h_last = _dynamic_take(x, last_pos)          # [1, 1, H]
        logits = self.model._logits(h_last)
        nxt = search.argmax(logits, axis=-1).astype("int64")
        return nxt, new_k, new_v

    PREFILL_BUCKET = 32

    def _prefill(self, req):
        n = len(req.prompt_ids)
        # bucket the padded length so ragged prompts share compiled
        # prefill programs (one per bucket, not one per length)
        bucket = -(-n // self.PREFILL_BUCKET) * self.PREFILL_BUCKET
        padded = np.zeros((1, bucket), np.int64)
        padded[0, :n] = req.prompt_ids
        ids = Tensor(jnp.asarray(padded))
        real_pages, real_offs = self.alloc.page_positions(req.seq_id, 0, n)
        page_ids = np.full((bucket,), self.trash_page, np.int32)
        offs = np.zeros((bucket,), np.int32)
        page_ids[:n] = real_pages
        offs[:n] = real_offs
        if self._prefill_static is None:
            from .. import jit
            # eager prefill pays per-op dispatch for every layer on every
            # request; compiled, each bucket is one XLA call
            # warmup="once": one eager materialization pass total —
            # later buckets go straight to compile (the eager pass costs
            # a full per-op-dispatch forward)
            self._prefill_static = jit.to_static(
                self._prefill_forward, state=[self.model], warmup="once")
        with no_grad():
            nxt, new_k, new_v = self._prefill_static(
                ids, Tensor(jnp.asarray(n - 1, jnp.int32)),
                Tensor(jnp.asarray(page_ids)), Tensor(jnp.asarray(offs)),
                self.k_pools, self.v_pools)
        self.k_pools, self.v_pools = list(new_k), list(new_v)
        first = int(np.asarray(nxt._data).reshape(-1)[0])
        self._emit(req, first)

    # ------------------------------------------------------------------
    # decode
    # ------------------------------------------------------------------
    def _decode_step(self, tokens, tables, lens, k_pools, v_pools):
        """Batched one-token decode: pure in its inputs so ``to_static``
        compiles it once. tokens [B, 1] int64; tables [B, W]; lens [B]."""
        from ..tensor import search

        m = self.model.model
        cfg = self.model.config
        b = tokens.shape[0]
        pos = (lens.astype("int64") - 1).reshape([b, 1])
        page_ids = self._gather_tables(tables, lens)
        offs = (lens - 1).astype("int32") % self.page_size
        x = m.embed_tokens(tokens)
        new_k, new_v = [], []
        for li, layer in enumerate(m.layers):
            h = layer.input_layernorm(x)
            att = layer.self_attn
            q = att.q_proj(h).reshape([b, 1, att.num_heads, att.head_dim])
            k = att.k_proj(h).reshape([b, 1, att.num_kv_heads, att.head_dim])
            v = att.v_proj(h).reshape([b, 1, att.num_kv_heads, att.head_dim])
            q, k, v = FI.fused_rotary_position_embedding(
                q, k, v, position_ids=pos, rotary_emb_base=cfg.rope_theta)
            kp = _page_write(k_pools[li], k[:, 0], page_ids, offs)
            vp = _page_write(v_pools[li], v[:, 0], page_ids, offs)
            new_k.append(kp)
            new_v.append(vp)
            attn = paged_attention(q[:, 0], kp, vp, tables, lens)
            x = x + att.o_proj(attn.reshape([b, 1, -1]))
            x = x + layer.mlp(layer.post_attention_layernorm(x))
        x = m.norm(x)
        logits = self.model._logits(x)
        nxt = search.argmax(logits, axis=-1).astype("int64")
        return nxt, new_k, new_v

    def _gather_tables(self, tables, lens):
        """Page id holding each row's current token:
        ``tables[b, (len-1) // page_size]``."""
        page = self.page_size

        def fn(tables, lens):
            b = tables.shape[0]
            idx = (lens.astype(jnp.int32) - 1) // page
            return tables[jnp.arange(b), idx]

        return run_op("paged_table_gather", fn, (tables, lens),
                      differentiable=False)

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def add_request(self, req):
        """Admit a request (prefill immediately). Returns its seq_id."""
        if len(self._live) >= self.max_batch:
            raise MemoryError(
                f"engine full ({self.max_batch} live requests)")
        req.seq_id = self._next_id
        self._next_id += 1
        self.alloc.admit(req.seq_id, len(req.prompt_ids))
        self._live[req.seq_id] = req
        self._prefill(req)
        return req.seq_id

    def _emit(self, req, token):
        req.output_ids.append(token)
        if (req.eos_token_id is not None and token == req.eos_token_id) \
                or len(req.output_ids) >= req.max_new_tokens:
            req.done = True
            self.alloc.release(req.seq_id)
            del self._live[req.seq_id]

    def _views_np(self, live):
        """Padded (tokens?, tables, lens) numpy views for the full
        [max_batch] slot layout — pure host work, ONE H2D per array."""
        b = self.max_batch
        tables = np.full((b, self.width), self.trash_page, np.int32)
        lens = np.ones((b,), np.int32)
        for i, r in enumerate(live):
            t = self.alloc._tables[r.seq_id]
            tables[i, :len(t)] = t
            lens[i] = self.alloc._lens[r.seq_id]
        return tables, lens

    def _ensure_decode_compiled(self):
        if self._decode_static is None:
            from .. import jit
            self._decode_static = jit.to_static(
                self._decode_step, state=[self.model], warmup="once")
        return self._decode_static

    def step(self):
        """Decode one token for every live request. Returns the number of
        live requests served."""
        live = [r for r in self._live.values() if not r.done]
        if not live:
            return 0
        # account the new token BEFORE building views: the write offset
        # and the kernel's context length both include it
        for r in live:
            self.alloc.extend(r.seq_id, 1)
        tokens = np.zeros((self.max_batch, 1), np.int64)
        for i, r in enumerate(live):
            tokens[i, 0] = r.output_ids[-1] if r.output_ids \
                else r.prompt_ids[-1]
        tables, lens = self._views_np(live)
        step = self._ensure_decode_compiled()
        nxt, new_k, new_v = step(
            Tensor(jnp.asarray(tokens)), Tensor(jnp.asarray(tables)),
            Tensor(jnp.asarray(lens)), self.k_pools, self.v_pools)
        self.k_pools, self.v_pools = list(new_k), list(new_v)
        out = np.asarray(nxt._data).reshape(-1)
        for i, r in enumerate(live):
            self._emit(r, int(out[i]))
        return len(live)

    def decode_many(self, n):
        """Fast path: ``n`` chained decode steps for the current live set
        with NO host sync inside the loop — next tokens feed the next
        step as device arrays, page views are precomputed on the host,
        and the emitted tokens are fetched once at the end. Valid when no
        request can retire mid-run (no EOS; none reaches max_new_tokens
        before the n-th step)."""
        live = [r for r in self._live.values() if not r.done]
        if not live:
            return 0
        assert all(r.eos_token_id is None
                   and len(r.output_ids) + n <= r.max_new_tokens
                   for r in live), "decode_many needs retire-free steps"
        step = self._ensure_decode_compiled()
        tokens = np.zeros((self.max_batch, 1), np.int64)
        for i, r in enumerate(live):
            tokens[i, 0] = r.output_ids[-1] if r.output_ids \
                else r.prompt_ids[-1]
        tok_t = Tensor(jnp.asarray(tokens))
        outs = []
        for _ in range(n):
            for r in live:
                self.alloc.extend(r.seq_id, 1)
            tables, lens = self._views_np(live)
            nxt, new_k, new_v = step(
                tok_t, Tensor(jnp.asarray(tables)),
                Tensor(jnp.asarray(lens)), self.k_pools, self.v_pools)
            self.k_pools, self.v_pools = list(new_k), list(new_v)
            outs.append(nxt._data)
            tok_t = nxt.reshape([self.max_batch, 1])
        all_tokens = np.asarray(jnp.concatenate(outs, axis=1))  # one D2H
        for i, r in enumerate(live):
            for t in range(n):
                self._emit(r, int(all_tokens[i, t]))
        return len(live) * n

    def generate(self, prompts, max_new_tokens=16, eos_token_id=None):
        """Convenience batch API: admit all prompts (continuous batching
        handles ragged finish times), run to completion, return output id
        lists in order."""
        reqs = [Request(p, max_new_tokens, eos_token_id) for p in prompts]
        pending = list(reqs)
        while pending or any(not r.done for r in reqs):
            while pending and len(self._live) < self.max_batch:
                self.add_request(pending.pop(0))
            live = [r for r in self._live.values() if not r.done]
            # sync-free fast path while no request can retire; with
            # pending admissions cap the burst so a retirement (and the
            # admission it enables) is never far away
            if live and eos_token_id is None:
                burst = min(r.max_new_tokens - len(r.output_ids)
                            for r in live)
                if pending:
                    burst = min(burst, 8)
                if burst > 1:
                    self.decode_many(burst)
                    continue
            if not self.step() and pending:
                continue
            if not pending and all(r.done for r in reqs):
                break
        return [r.output_ids for r in reqs]
