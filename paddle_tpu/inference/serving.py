"""Continuous-batching serving engine for the Llama family.

Reference capability: the reference's serving path — AnalysisPredictor +
paged `block_multi_head_attention` / `masked_multihead_attention`
kernels (`fluid/inference/api/analysis_predictor.h:100`,
`phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu`). The
reference has no in-tree continuous-batching scheduler; this engine goes
beyond it (vLLM-style): requests are admitted and retired on the fly,
every live sequence decodes one token per engine step in a single
batched program, and KV lives in a shared paged pool so ragged contexts
waste no HBM.

Design (TPU-first):
- ONE :class:`PageAllocator` shared by all layers (page structure is
  identical per layer); per-layer K/V pools are device arrays updated
  functionally.
- Prefill runs the model's own submodules densely (flash/XLA attention)
  while collecting post-rope K/V per layer, then scatters them into
  pages — per request, compiled per prompt-length bucket.
- The decode step is ONE ``to_static`` program of static shape
  [max_batch]: embed → per layer (rms_norm → qkv → rope at per-row
  positions → page write → Pallas ``paged_attention`` → o_proj →
  swiglu MLP) → logits → greedy argmax. Inactive batch slots point at a
  reserved trash page with length 1, so shapes never change and the
  executable is reused for the engine's lifetime.
- Sustained decode runs as a **burst**: ``lax.scan`` over the same
  traced decode step, so BURST tokens per sequence cost ONE dispatch,
  one host→device transfer of (tokens, tables, lens) and one
  device→host fetch of the emitted block — the per-step host round
  trip (the dominant cost of dispatch-per-token serving) is amortized
  away. Pages for the whole burst are reserved up front; sequence
  lengths advance on device as the scan carry.
"""

from __future__ import annotations

import math
import time

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, no_grad, run_op
from ..incubate.nn import functional as FI
from ..nn import functional as F
from ..observability import compile_watch as _cw
from ..observability import flight_recorder as _fr
from ..observability import metrics as _om
from ..observability.trace import span as _span
from ..ops.paged_attention import paged_attention
from .paged_cache import PageAllocator

__all__ = ["LlamaServingEngine", "Request", "AdmissionError"]


class AdmissionError(MemoryError):
    """Typed admission rejection carrying queue/pool stats so callers
    can shed load (429, redirect, re-queue) instead of crashing.

    Subclasses :class:`MemoryError` for backward compatibility with
    callers catching the engine's old bare raise; the serving
    ``_fatal_guard`` likewise treats it as a routine rejection, not a
    crash worth a flight-recorder dump.
    """

    def __init__(self, reason, live, max_batch, free_pages, num_pages,
                 retries):
        super().__init__(
            f"{reason} (live={live}/{max_batch}, "
            f"free_pages={free_pages}/{num_pages}, "
            f"retries={retries})")
        self.reason = reason
        self.live = live
        self.max_batch = max_batch
        self.free_pages = free_pages
        self.num_pages = num_pages
        self.retries = retries

#: latency buckets tuned for serving (TTFT / per-token): 1ms .. 10s
_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _serving_metrics():
    """Standard serving metric set on the default registry (no-ops when
    ``PADDLE_TPU_METRICS=0``). Counters aggregate across engines in the
    process; gauges reflect the engine that last updated them."""
    return {
        "admitted": _om.counter(
            "serving_requests_admitted_total",
            "requests admitted into the continuous batch"),
        "completed": _om.counter(
            "serving_requests_completed_total",
            "requests retired (EOS or max_new_tokens)"),
        "evicted": _om.counter(
            "serving_requests_evicted_total",
            "admission rejections (engine full / KV pages exhausted)"),
        "admit_retries": _om.counter(
            "serving_admission_retries_total",
            "admission attempts retried after backoff while waiting "
            "for capacity"),
        "queue_depth": _om.gauge(
            "serving_queue_depth", "live requests in the engine"),
        "kv_util": _om.gauge(
            "serving_kv_page_utilization",
            "fraction of KV-cache pages in use (0 when idle)"),
        "ttft": _om.histogram(
            "serving_ttft_seconds",
            "admission -> first emitted token", buckets=_LATENCY_BUCKETS),
        "tpot": _om.histogram(
            "serving_token_latency_seconds",
            "per-token decode latency (burst dispatches amortized)",
            buckets=_LATENCY_BUCKETS),
        "prefill_tokens": _om.counter(
            "serving_prefill_tokens_total", "prompt tokens prefilled"),
        "generated": _om.counter(
            "serving_generated_tokens_total", "tokens emitted by decode"),
    }


def _fatal_guard(origin):
    """Decorator: a crash inside an engine entry point dumps a
    flight-recorder post-mortem (when one is installed) before the
    exception reaches the caller — the serving analog of a rank dying
    under the elastic watchdog. Each exception dumps at most once."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            try:
                return fn(*args, **kwargs)
            except MemoryError:
                # admission control (engine full / KV pages exhausted)
                # raises MemoryError as a ROUTINE rejection — already
                # counted by the evicted metric; it must not burn the
                # recorder's bounded dump budget. A real device OOM
                # surfaces as XlaRuntimeError and still dumps.
                raise
            except Exception as e:
                _fr.on_fatal(origin, e)
                raise
        return wrapper

    return deco


def _page_write(pages, new, page_ids, offs):
    """Functional scatter of ``new [B, Hk, D]`` into head-major ``pages
    [P, Hk, page, D]`` at (page_ids[b], h, offs[b]) — one token per live
    sequence."""
    def fn(pages, new, page_ids, offs):
        hidx = jnp.arange(pages.shape[1])[None, :]
        return pages.at[page_ids[:, None], hidx, offs[:, None]].set(
            new.astype(pages.dtype))

    return run_op("paged_kv_write", fn, (pages, new, page_ids, offs),
                  differentiable=False)


def _page_write_seq(pages, new, page_ids, offs):
    """Scatter a wave of sequences ``new [B, S, Hk, D]`` into ``pages``
    at (page_ids[b, s], h, offs[b, s]) — the prefill write, inside the
    compiled program (trash-page entries absorb bucket padding and pad
    rows)."""
    def fn(pages, new, page_ids, offs):
        hidx = jnp.arange(pages.shape[1])[None, None, :]
        return pages.at[page_ids[:, :, None], hidx, offs[:, :, None]].set(
            new.astype(pages.dtype))

    return run_op("paged_kv_write_seq", fn, (pages, new, page_ids, offs),
                  differentiable=False)


class Request:
    """One generation request (seq_id is assigned by the engine)."""

    def __init__(self, prompt_ids, max_new_tokens=16, eos_token_id=None):
        self.prompt_ids = np.asarray(prompt_ids, np.int64).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.output_ids: list[int] = []
        self.seq_id = None
        self.done = False
        self._t_admit = None          # set at admission; drives TTFT


class LlamaServingEngine:
    #: default compiled burst length — one scanned decode program serves
    #: this many tokens per sequence per dispatch
    BURST = 16

    def __init__(self, model, max_batch=16, page_size=16, num_pages=None,
                 max_pages_per_seq=None, burst=None, admit_retries=0,
                 admit_backoff=0.005):
        if num_pages is None:
            num_pages = max_batch * 24 + 8
        self.model = model
        cfg = model.config
        self.max_batch = max_batch
        self.page_size = page_size
        # Keep block tables as narrow as the workload allows: the Pallas
        # decode grid is (B, Hk, width), so a table sized to the whole
        # pool pays a grid step (and an HBM->VMEM page fetch) per UNUSED
        # table slot. max_pages_per_seq is the knob.
        self.burst = int(burst) if burst else self.BURST
        # admission backpressure: retry this many times (exponential
        # backoff from admit_backoff seconds) before a typed rejection.
        # Default 0 (instant rejection): retries only help when another
        # thread drives step()/burst and can retire a request
        # mid-backoff — opt in for such multithreaded deployments.
        self.admit_retries = int(admit_retries)
        self.admit_backoff = float(admit_backoff)
        # page num_pages-1 is the trash page for inactive batch slots
        self.alloc = PageAllocator(num_pages - 1, page_size,
                                   max_pages_per_seq)
        self.width = self.alloc.max_pages_per_seq
        self.trash_page = num_pages - 1
        dt = model.parameters()[0].dtype
        hk, d = cfg.num_key_value_heads, cfg.head_dim
        # head-major [P, Hk, page, D] — the Pallas kernel's tiling layout
        shape = (num_pages, hk, page_size, d)
        self.k_pools = [Tensor(jnp.zeros(shape, jnp.dtype(str(dt))))
                        for _ in range(cfg.num_hidden_layers)]
        self.v_pools = [Tensor(jnp.zeros(shape, jnp.dtype(str(dt))))
                        for _ in range(cfg.num_hidden_layers)]
        self._live: dict[int, Request] = {}
        self._m = _serving_metrics()
        self._next_id = 0
        self._decode_static = None
        self._prefill_static = None
        self._prefill_warm_buckets: set[int] = set()
        self._burst_static: dict[int, object] = {}  # burst length -> program

    def __state_tensors__(self):
        """State-discovery override for ``to_static``: the KV pools are
        explicit inputs/outputs of every compiled program (donated by the
        burst path) and must NOT also be captured as closure state —
        that would donate the same buffers twice. Model params enter via
        ``state=[self.model]``."""
        return []

    # ------------------------------------------------------------------
    # prefill
    # ------------------------------------------------------------------
    def _prefill_forward(self, ids, last_pos, page_ids, offs, k_pools,
                         v_pools):
        """Dense forward of a WAVE of prompts [max_batch, Sb]
        (bucket-padded; causal attention keeps each padded tail from
        touching the real prefix) that also scatters the post-rope K/V
        into the page pools INSIDE the compiled program. Pad rows and
        pad positions scatter to the trash page. One dispatch admits up
        to max_batch requests — the reference serving stack's batched
        context step (`block_multi_head_attention`) done the XLA way.
        Returns (next token id [B, 1], new k_pools, new v_pools)."""
        from ..tensor import creation, manipulation, search

        m = self.model.model
        cfg = self.model.config
        b, s = ids.shape[0], ids.shape[1]
        pos = creation.arange(0, s, dtype="int64").reshape([1, s]) \
            .expand([b, s])
        x = m.embed_tokens(ids)
        new_k, new_v = [], []
        for li, layer in enumerate(m.layers):
            h = layer.input_layernorm(x)
            att = layer.self_attn
            q = att.q_proj(h).reshape([b, s, att.num_heads, att.head_dim])
            k = att.k_proj(h).reshape([b, s, att.num_kv_heads, att.head_dim])
            v = att.v_proj(h).reshape([b, s, att.num_kv_heads, att.head_dim])
            q, k, v = FI.fused_rotary_position_embedding(
                q, k, v, position_ids=pos, rotary_emb_base=cfg.rope_theta)
            new_k.append(_page_write_seq(k_pools[li], k, page_ids, offs))
            new_v.append(_page_write_seq(v_pools[li], v, page_ids, offs))
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            x = x + att.o_proj(out.reshape([b, s, -1]))
            x = x + layer.mlp(layer.post_attention_layernorm(x))
        x = m.norm(x)
        h_last = manipulation.take_along_axis(
            x, last_pos.astype("int64").reshape([b, 1, 1])
            .expand([b, 1, x.shape[-1]]), 1)         # [B, 1, H]
        logits = self.model._logits(h_last)
        nxt = search.argmax(logits, axis=-1).astype("int64")
        return nxt, new_k, new_v

    PREFILL_BUCKET = 32

    @_fatal_guard("serving.prefill_wave")
    def _prefill_wave(self, reqs):
        """Prefill 1..max_batch admitted requests in ONE compiled call."""
        if not reqs:
            return
        b = self.max_batch
        n_max = max(len(r.prompt_ids) for r in reqs)
        # bucket the padded length so ragged prompts share compiled
        # prefill programs (one per bucket, not one per length)
        bucket = -(-n_max // self.PREFILL_BUCKET) * self.PREFILL_BUCKET
        padded = np.zeros((b, bucket), np.int64)
        page_ids = np.full((b, bucket), self.trash_page, np.int32)
        offs = np.zeros((b, bucket), np.int32)
        last_pos = np.zeros((b,), np.int32)
        for i, r in enumerate(reqs):
            n = len(r.prompt_ids)
            padded[i, :n] = r.prompt_ids
            rp, ro = self.alloc.page_positions(r.seq_id, 0, n)
            page_ids[i, :n] = rp
            offs[i, :n] = ro
            last_pos[i] = n - 1
        if self._prefill_static is None:
            from ..jit import StaticFunction

            # no lazy state (params exist, no optimizer): skip the eager
            # warmup and compile directly; donate pools for in-place
            # page writes
            self._prefill_static = StaticFunction(
                self._prefill_forward, state=[self.model], warmup="once",
                donate_inputs=True, name="serving.prefill")
            self._prefill_static._warmed_any = True
        if self._m["ttft"] is not _om.NULL \
                and bucket not in self._prefill_warm_buckets:
            # compile this bucket's program OUTSIDE the TTFT window: a
            # dummy dispatch (all page writes land in the trash page,
            # emitted tokens discarded) triggers the one-time trace +
            # compile, and the wave's admission stamps shift past it so
            # TTFT keeps one sample per request without the multi-second
            # compile skewing the histogram's +Inf bucket forever. Under
            # PADDLE_TPU_METRICS=0 this is skipped (zero-cost mandate).
            t_w = time.perf_counter()
            with no_grad():
                _, wk, wv = self._prefill_static(
                    Tensor(jnp.asarray(np.zeros((b, bucket), np.int64))),
                    Tensor(jnp.asarray(np.zeros((b,), np.int32))),
                    Tensor(jnp.asarray(np.full((b, bucket),
                                               self.trash_page,
                                               np.int32))),
                    Tensor(jnp.asarray(np.zeros((b, bucket), np.int32))),
                    self.k_pools, self.v_pools)
            self.k_pools, self.v_pools = list(wk), list(wv)
            warm_dur = time.perf_counter() - t_w
            for r in reqs:
                if r._t_admit is not None:
                    r._t_admit += warm_dur
            self._prefill_warm_buckets.add(bucket)
        with no_grad(), _span("serving.prefill_wave", wave=len(reqs),
                              bucket=bucket):
            nxt, new_k, new_v = self._prefill_static(
                Tensor(jnp.asarray(padded)),
                Tensor(jnp.asarray(last_pos)),
                Tensor(jnp.asarray(page_ids)), Tensor(jnp.asarray(offs)),
                self.k_pools, self.v_pools)
        self.k_pools, self.v_pools = list(new_k), list(new_v)
        first = np.asarray(nxt._data).reshape(-1)
        for i, r in enumerate(reqs):
            self._emit(r, int(first[i]))
        self._set_pool_gauges()

    # ------------------------------------------------------------------
    # decode
    # ------------------------------------------------------------------
    def _decode_step(self, tokens, tables, lens, k_pools, v_pools):
        """Batched one-token decode: pure in its inputs so ``to_static``
        compiles it once. tokens [B, 1] int64; tables [B, W]; lens [B]."""
        from ..tensor import search

        m = self.model.model
        cfg = self.model.config
        b = tokens.shape[0]
        pos = (lens.astype("int64") - 1).reshape([b, 1])
        page_ids = self._gather_tables(tables, lens)
        offs = (lens - 1).astype("int32") % self.page_size
        x = m.embed_tokens(tokens)
        new_k, new_v = [], []
        for li, layer in enumerate(m.layers):
            h = layer.input_layernorm(x)
            att = layer.self_attn
            q = att.q_proj(h).reshape([b, 1, att.num_heads, att.head_dim])
            k = att.k_proj(h).reshape([b, 1, att.num_kv_heads, att.head_dim])
            v = att.v_proj(h).reshape([b, 1, att.num_kv_heads, att.head_dim])
            q, k, v = FI.fused_rotary_position_embedding(
                q, k, v, position_ids=pos, rotary_emb_base=cfg.rope_theta)
            kp = _page_write(k_pools[li], k[:, 0], page_ids, offs)
            vp = _page_write(v_pools[li], v[:, 0], page_ids, offs)
            new_k.append(kp)
            new_v.append(vp)
            attn = paged_attention(q[:, 0], kp, vp, tables, lens)
            x = x + att.o_proj(attn.reshape([b, 1, -1]))
            x = x + layer.mlp(layer.post_attention_layernorm(x))
        x = m.norm(x)
        logits = self.model._logits(x)
        nxt = search.argmax(logits, axis=-1).astype("int64")
        return nxt, new_k, new_v

    def _gather_tables(self, tables, lens):
        """Page id holding each row's current token:
        ``tables[b, (len-1) // page_size]``."""
        page = self.page_size

        def fn(tables, lens):
            b = tables.shape[0]
            idx = (lens.astype(jnp.int32) - 1) // page
            return tables[jnp.arange(b), idx]

        return run_op("paged_table_gather", fn, (tables, lens),
                      differentiable=False)

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def _set_pool_gauges(self):
        self._m["queue_depth"].set(len(self._live))
        self._m["kv_util"].set(
            1.0 - self.alloc.free_pages / self.alloc.num_pages)
        if _om.enabled():
            # per-wave device-memory accounting (host metadata walks
            # only, no sync), throttled so the live-array enumeration
            # never rides the per-token decode path, + a rate-limited
            # flight-recorder snapshot
            _cw.sample_device_memory(min_interval=1.0)
            _fr.periodic_snapshot()

    def _admit(self, req):
        attempt = 0
        while True:
            reason = None
            if len(self._live) >= self.max_batch:
                reason = "engine full"
            else:
                if req.seq_id is None:
                    req.seq_id = self._next_id
                    self._next_id += 1
                try:
                    self.alloc.admit(req.seq_id, len(req.prompt_ids))
                except MemoryError:
                    reason = "KV page pool exhausted"
            if reason is None:
                break
            if attempt >= self.admit_retries:
                self._m["evicted"].inc()
                raise AdmissionError(
                    reason, live=len(self._live),
                    max_batch=self.max_batch,
                    free_pages=self.alloc.free_pages,
                    num_pages=self.alloc.num_pages, retries=attempt)
            # bounded backoff: a concurrent step()/burst may retire a
            # request and release its pages before the retry
            attempt += 1
            self._m["admit_retries"].inc()
            time.sleep(self.admit_backoff * (2 ** (attempt - 1)))
        self._live[req.seq_id] = req
        req._t_admit = time.perf_counter()
        self._m["admitted"].inc()
        self._m["prefill_tokens"].inc(len(req.prompt_ids))
        self._set_pool_gauges()
        return req.seq_id

    def add_request(self, req):
        """Admit a request (prefill immediately). Returns its seq_id."""
        sid = self._admit(req)
        self._prefill_wave([req])
        return sid

    def _emit(self, req, token):
        first = not req.output_ids
        req.output_ids.append(token)
        if first and req._t_admit is not None:
            self._m["ttft"].observe(time.perf_counter() - req._t_admit)
        self._m["generated"].inc()
        if (req.eos_token_id is not None and token == req.eos_token_id) \
                or len(req.output_ids) >= req.max_new_tokens:
            req.done = True
            self.alloc.release(req.seq_id)
            del self._live[req.seq_id]
            self._m["completed"].inc()
        # pool gauges are refreshed once per wave/step/burst by the
        # caller, not per emitted token — only the post-loop value is
        # observable anyway

    def _views_np(self, live):
        """Padded (tokens?, tables, lens) numpy views for the full
        [max_batch] slot layout — pure host work, ONE H2D per array."""
        b = self.max_batch
        tables = np.full((b, self.width), self.trash_page, np.int32)
        lens = np.ones((b,), np.int32)
        for i, r in enumerate(live):
            t = self.alloc._tables[r.seq_id]
            tables[i, :len(t)] = t
            lens[i] = self.alloc._lens[r.seq_id]
        return tables, lens

    def _ensure_decode_compiled(self):
        if self._decode_static is None:
            from .. import jit
            self._decode_static = jit.to_static(
                self._decode_step, state=[self.model], warmup="once",
                name="serving.decode_step")
        return self._decode_static

    @_fatal_guard("serving.step")
    def step(self):
        """Decode one token for every live request. Returns the number of
        live requests served."""
        live = [r for r in self._live.values() if not r.done]
        if not live:
            return 0
        # a cold call traces + compiles inside the timed window; that
        # one-time multi-second sample would skew the tpot histogram
        # (top bucket 10s) forever, so it is not observed
        cold = self._decode_static is None
        t0 = time.perf_counter()
        # account the new token BEFORE building views: the write offset
        # and the kernel's context length both include it
        for r in live:
            self.alloc.extend(r.seq_id, 1)
        tokens = np.zeros((self.max_batch, 1), np.int64)
        for i, r in enumerate(live):
            tokens[i, 0] = r.output_ids[-1] if r.output_ids \
                else r.prompt_ids[-1]
        tables, lens = self._views_np(live)
        step = self._ensure_decode_compiled()
        with _span("serving.decode_step", live=len(live)):
            nxt, new_k, new_v = step(
                Tensor(jnp.asarray(tokens)), Tensor(jnp.asarray(tables)),
                Tensor(jnp.asarray(lens)), self.k_pools, self.v_pools)
        self.k_pools, self.v_pools = list(new_k), list(new_v)
        out = np.asarray(nxt._data).reshape(-1)
        if not cold:
            self._m["tpot"].observe(time.perf_counter() - t0)
        for i, r in enumerate(live):
            self._emit(r, int(out[i]))
        self._set_pool_gauges()
        return len(live)

    # ------------------------------------------------------------------
    # burst decode: n steps = ONE compiled program (lax.scan)
    # ------------------------------------------------------------------
    def _decode_burst_fn(self, n):
        """Build the n-step burst: ``lax.scan`` whose body is the SAME
        Tensor-level :meth:`_decode_step` (traced, not re-implemented —
        parity with the per-step program is by construction). The carry
        is (tokens, lens, pools); tables are scan-invariant because
        pages for the whole burst are reserved before launch."""
        import jax

        def fn(tokens, tables, lens, k_pools, v_pools):
            tab = tables._data
            kp = [t._data for t in k_pools]
            vp = [t._data for t in v_pools]

            def body(carry, _):
                tok, lc, kc, vc = carry
                nxt, nk, nv = self._decode_step(
                    Tensor(tok), Tensor(tab), Tensor(lc),
                    [Tensor(a) for a in kc], [Tensor(a) for a in vc])
                nxt_arr = nxt._data.reshape(tok.shape).astype(tok.dtype)
                return ((nxt_arr, lc + 1,
                         [t._data for t in nk], [t._data for t in nv]),
                        nxt_arr[:, 0])

            (_, _, kf, vf), toks = jax.lax.scan(
                body, (tokens._data, lens._data, kp, vp), None, length=n)
            return (jnp.swapaxes(toks, 0, 1), *kf, *vf)

        return fn

    def _ensure_burst_compiled(self, n):
        sf = self._burst_static.get(n)
        if sf is None:
            from ..jit import StaticFunction

            sf = StaticFunction(self._decode_burst_fn(n),
                                state=[self.model], warmup="once",
                                donate_inputs=True,
                                name=f"serving.decode_burst[{n}]")
            # no lazy state to materialize (params exist; no optimizer):
            # skip the eager warmup — n scanned steps of per-op dispatch
            # would cost more than the compile it avoids
            sf._warmed_any = True
            self._burst_static[n] = sf
        return sf

    @_fatal_guard("serving.burst")
    def _burst(self, n):
        """Decode ``n`` tokens for every live request in one dispatch.
        Pages for all n tokens are reserved up front; requests that
        retire mid-burst (EOS / max_new_tokens) have their tail tokens
        discarded at emit time — bounded waste, no correctness impact."""
        live = [r for r in self._live.values() if not r.done]
        if not live or n <= 0:
            return 0
        # as in step(): each new burst length compiles on its first
        # call — don't let that land n inflated samples in tpot
        cold = n not in self._burst_static
        t0 = time.perf_counter()
        start_lens = {r.seq_id: self.alloc._lens[r.seq_id] for r in live}
        for r in live:
            self.alloc.extend(r.seq_id, n)
        b = self.max_batch
        tables = np.full((b, self.width), self.trash_page, np.int32)
        lens = np.ones((b,), np.int32)
        tokens = np.zeros((b, 1), np.int64)
        for i, r in enumerate(live):
            t = self.alloc._tables[r.seq_id]
            tables[i, :len(t)] = t
            lens[i] = start_lens[r.seq_id] + 1   # first new token included
            tokens[i, 0] = r.output_ids[-1] if r.output_ids \
                else r.prompt_ids[-1]
        sf = self._ensure_burst_compiled(n)
        with no_grad(), _span("serving.decode_burst", live=len(live),
                              burst=n):
            out = sf(
                Tensor(jnp.asarray(tokens)), Tensor(jnp.asarray(tables)),
                Tensor(jnp.asarray(lens)), self.k_pools, self.v_pools)
        n_layers = len(self.k_pools)
        toks = out[0]
        self.k_pools = list(out[1:1 + n_layers])
        self.v_pools = list(out[1 + n_layers:])
        all_tokens = np.asarray(toks._data)          # one D2H
        # one scan tick serves every live row: per-token latency is the
        # dispatch wall time amortized over the n ticks
        if not cold:
            tick = (time.perf_counter() - t0) / n
            for _ in range(n):
                self._m["tpot"].observe(tick)
        served = 0
        for i, r in enumerate(live):
            for t in range(n):
                if r.done:
                    break
                self._emit(r, int(all_tokens[i, t]))
                served += 1
        self._set_pool_gauges()
        return served

    def _burst_fits(self, live, n):
        """Largest burst <= n whose page reservations fit the pool."""
        page = self.page_size
        while n > 1:
            need = sum(
                max(0, -(-(self.alloc._lens[r.seq_id] + n) // page)
                    - len(self.alloc._tables[r.seq_id]))
                for r in live)
            if need <= self.alloc.free_pages:
                break
            n //= 2
        return n

    def decode_many(self, n, exact=True):
        """``n`` decode steps for the current live set, chunked into
        compiled scans: full :attr:`burst`-length bursts, then
        burst/4-length bursts, then single steps. With ``exact=False``
        the tail may overshoot by up to burst/4 - 1 ticks — callers use
        this when every live request retires by step ``n`` (the
        overshot ticks are discarded at emit time), trading a few idle
        ticks for never paying the per-step dispatch round trip.
        Returns tokens served."""
        served = 0
        small = max(self.burst // 4, 2)
        while n > 0:
            live = [r for r in self._live.values() if not r.done]
            if not live:
                break
            if n >= self.burst:
                chunk = self._burst_fits(live, self.burst)
            elif n >= small or not exact:
                chunk = self._burst_fits(live, small)
            else:
                chunk = 1
            if chunk > 1:
                served += self._burst(chunk)
                n -= chunk
            else:
                served += self.step()
                n -= 1
        return served

    @_fatal_guard("serving.generate")
    def generate(self, prompts, max_new_tokens=16, eos_token_id=None):
        """Convenience batch API: admit all prompts (continuous batching
        handles ragged finish times), run to completion, return output id
        lists in order. Admissions happen in waves — every pending
        request that fits prefills in ONE compiled call."""
        reqs = [Request(p, max_new_tokens, eos_token_id) for p in prompts]
        pending = list(reqs)
        while pending or any(not r.done for r in reqs):
            wave = []
            while pending and len(self._live) < self.max_batch:
                self._admit(pending[0])
                wave.append(pending.pop(0))
            self._prefill_wave(wave)
            live = [r for r in self._live.values() if not r.done]
            if live:
                # burst until the earliest possible retirement; with EOS
                # or pending admissions cap at the burst length so a
                # retirement (and the admission it unblocks) is never
                # far away. The tail may overshoot (exact=False): every
                # live request retires by then, so overshot ticks are
                # discarded, never mis-emitted.
                burst = min(r.max_new_tokens - len(r.output_ids)
                            for r in live)
                if pending or eos_token_id is not None:
                    burst = min(burst, self.burst)
                self.decode_many(burst, exact=False)
                continue
            if not pending and all(r.done for r in reqs):
                break
        return [r.output_ids for r in reqs]
