"""Detection ops (reference: `python/paddle/vision/ops.py` — nms:1867,
roi_align:1640, roi_pool, box kernels in `phi/kernels/gpu/`).

TPU-native notes: NMS's greedy suppression is an O(N^2) IoU matrix +
a ``lax.fori_loop`` sweep (static shapes, no data-dependent Python);
RoI align is vectorized bilinear gather-interpolation over a static
sampling grid, so XLA fuses it into a few gathers + contractions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import run_op

__all__ = ["nms", "roi_align", "roi_pool", "box_iou", "deform_conv2d",
           "DeformConv2D", "box_coder", "prior_box", "yolo_box",
           "matrix_nms", "psroi_pool", "distribute_fpn_proposals",
           "generate_proposals", "multiclass_nms3", "read_file", "decode_jpeg"]


def _iou_matrix(boxes):
    """[N, 4] xyxy -> [N, N] IoU."""
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = (x2 - x1) * (y2 - y1)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def box_iou(boxes1, boxes2):
    """Pairwise IoU between two [N,4]/[M,4] xyxy sets -> [N, M]."""
    def fn(a, b):
        x1, y1, x2, y2 = (a[:, i] for i in range(4))
        u1, v1, u2, v2 = (b[:, i] for i in range(4))
        area_a = (x2 - x1) * (y2 - y1)
        area_b = (u2 - u1) * (v2 - v1)
        ix1 = jnp.maximum(x1[:, None], u1[None, :])
        iy1 = jnp.maximum(y1[:, None], v1[None, :])
        ix2 = jnp.minimum(x2[:, None], u2[None, :])
        iy2 = jnp.minimum(y2[:, None], v2[None, :])
        inter = jnp.maximum(ix2 - ix1, 0.0) * jnp.maximum(iy2 - iy1, 0.0)
        union = area_a[:, None] + area_b[None, :] - inter
        return jnp.where(union > 0, inter / union, 0.0)

    return run_op("box_iou", fn, (boxes1, boxes2), differentiable=False)


def _nms_kept_mask(boxes, iou_threshold):
    """Greedy NMS on boxes already sorted by descending score; returns a
    bool keep-mask. lax.fori_loop over rows: a row survives iff no
    earlier surviving row overlaps it beyond the threshold."""
    iou = _iou_matrix(boxes)
    n = boxes.shape[0]

    def body(i, keep):
        # suppressed if any kept j < i has IoU > thr
        over = (iou[i] > iou_threshold) & keep \
            & (jnp.arange(n) < i)
        return keep.at[i].set(~jnp.any(over))

    return jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference `vision/ops.py:1867`. Returns indices of kept boxes
    sorted by descending score (or input order when ``scores`` is None),
    truncated to ``top_k``."""
    def fn(boxes, scores, category_idxs):
        n = boxes.shape[0]
        order = jnp.arange(n) if scores is None \
            else jnp.argsort(-scores)
        sorted_boxes = boxes[order]
        if category_idxs is None:
            keep = _nms_kept_mask(sorted_boxes, iou_threshold)
        else:
            # batched NMS: offset each category's boxes to disjoint
            # regions so cross-category IoU is 0 (standard trick — one
            # kernel instead of a per-category loop)
            cats = category_idxs[order].astype(sorted_boxes.dtype)
            span = jnp.max(sorted_boxes) - jnp.min(sorted_boxes) + 1.0
            shifted = sorted_boxes + (cats * span)[:, None]
            keep = _nms_kept_mask(shifted, iou_threshold)
        kept = order[jnp.where(keep, size=n, fill_value=-1)[0]]
        kept = kept[jnp.where(kept >= 0, size=n, fill_value=-1)[0]]
        count = int(jnp.sum(keep))
        return kept[:count] if top_k is None \
            else kept[:min(top_k, count)]

    # host-side sizes: NMS output is inherently data-dependent, so this
    # op runs eagerly (like the reference's CPU/GPU kernel returning a
    # dynamic-size tensor)
    return run_op("nms", fn, (boxes, scores, category_idxs),
                  differentiable=False)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Reference `vision/ops.py:1640` (Mask R-CNN RoI Align). x [N,C,H,W];
    boxes [R, 4] xyxy in input-image coordinates; boxes_num [N] ints
    summing to R. Output [R, C, ph, pw]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def fn(x, boxes, boxes_num):
        n, c, h, w = x.shape
        r = boxes.shape[0]
        # map each roi to its batch image
        img_idx = jnp.repeat(jnp.arange(n), boxes_num, axis=0,
                             total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        bx = boxes * spatial_scale
        x1, y1, x2, y2 = (bx[:, i] for i in range(4))
        x1, y1 = x1 - off, y1 - off
        x2, y2 = x2 - off, y2 - off
        roi_w = x2 - x1
        roi_h = y2 - y1
        if not aligned:
            roi_w = jnp.maximum(roi_w, 1.0)
            roi_h = jnp.maximum(roi_h, 1.0)
        bin_w = roi_w / pw
        bin_h = roi_h / ph
        s = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [R, ph, s] y coords and [R, pw, s] x coords
        sy = (jnp.arange(ph)[None, :, None]
              + (jnp.arange(s)[None, None, :] + 0.5) / s)
        sx = (jnp.arange(pw)[None, :, None]
              + (jnp.arange(s)[None, None, :] + 0.5) / s)
        ys = y1[:, None, None] + sy * bin_h[:, None, None]   # [R, ph, s]
        xs = x1[:, None, None] + sx * bin_w[:, None, None]   # [R, pw, s]

        def bilinear(img, yy, xx):
            """img [C, H, W]; yy [ph*s], xx [pw*s] -> [C, ph*s, pw*s]."""
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
            y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
            wy1 = jnp.clip(yy - y0, 0.0, 1.0)
            wx1 = jnp.clip(xx - x0, 0.0, 1.0)
            wy0, wx0 = 1.0 - wy1, 1.0 - wx1
            # zero contribution for samples outside the feature map
            valid_y = ((yy >= -1) & (yy <= h)).astype(img.dtype)
            valid_x = ((xx >= -1) & (xx <= w)).astype(img.dtype)
            g = lambda yi, xi: img[:, yi][:, :, xi]      # [C, len(y), len(x)]
            out = (g(y0i, x0i) * (wy0 * valid_y)[None, :, None]
                   * (wx0 * valid_x)[None, None, :]
                   + g(y0i, x1i) * (wy0 * valid_y)[None, :, None]
                   * (wx1 * valid_x)[None, None, :]
                   + g(y1i, x0i) * (wy1 * valid_y)[None, :, None]
                   * (wx0 * valid_x)[None, None, :]
                   + g(y1i, x1i) * (wy1 * valid_y)[None, :, None]
                   * (wx1 * valid_x)[None, None, :])
            return out

        def per_roi(ri):
            img = x[img_idx[ri]]                        # [C, H, W]
            yy = ys[ri].reshape(-1)                     # [ph*s]
            xx = xs[ri].reshape(-1)                     # [pw*s]
            vals = bilinear(img, yy, xx)                # [C, ph*s, pw*s]
            vals = vals.reshape(c, ph, s, pw, s)
            return jnp.mean(vals, axis=(2, 4))          # [C, ph, pw]

        return jax.vmap(per_roi)(jnp.arange(r))

    return run_op("roi_align", fn, (x, boxes, boxes_num))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Reference `vision/ops.py` roi_pool (max pooling per bin, Fast
    R-CNN). Same layout as :func:`roi_align`."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def fn(x, boxes, boxes_num):
        n, c, h, w = x.shape
        r = boxes.shape[0]
        img_idx = jnp.repeat(jnp.arange(n), boxes_num, axis=0,
                             total_repeat_length=r)
        bx = jnp.round(boxes * spatial_scale)
        x1 = bx[:, 0].astype(jnp.int32)
        y1 = bx[:, 1].astype(jnp.int32)
        x2 = jnp.maximum(bx[:, 2].astype(jnp.int32), x1 + 1)
        y2 = jnp.maximum(bx[:, 3].astype(jnp.int32), y1 + 1)

        ww = jnp.arange(w)
        hh = jnp.arange(h)

        def per_roi(ri):
            img = x[img_idx[ri]]
            # bin edges (float) over the roi
            ys = y1[ri] + (y2[ri] - y1[ri]) * jnp.arange(ph + 1) / ph
            xs = x1[ri] + (x2[ri] - x1[ri]) * jnp.arange(pw + 1) / pw

            def pool_bin(by, bx_):
                y_lo = jnp.floor(ys[by]).astype(jnp.int32)
                y_hi = jnp.ceil(ys[by + 1]).astype(jnp.int32)
                x_lo = jnp.floor(xs[bx_]).astype(jnp.int32)
                x_hi = jnp.ceil(xs[bx_ + 1]).astype(jnp.int32)
                m = ((hh >= y_lo) & (hh < jnp.maximum(y_hi, y_lo + 1)))[
                    :, None] & \
                    ((ww >= x_lo) & (ww < jnp.maximum(x_hi, x_lo + 1)))[
                    None, :]
                m = m & (hh[:, None] < h) & (ww[None, :] < w)
                return jnp.max(
                    jnp.where(m[None], img, -jnp.inf), axis=(1, 2))

            grid = jax.vmap(lambda by: jax.vmap(
                lambda bx_: pool_bin(by, bx_))(jnp.arange(pw)))(
                jnp.arange(ph))                          # [ph, pw, C]
            return jnp.transpose(grid, (2, 0, 1))

        return jax.vmap(per_roi)(jnp.arange(r))

    return run_op("roi_pool", fn, (x, boxes, boxes_num))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference `vision/ops.py:753`,
    CUDA kernel `phi/kernels/gpu/deformable_conv_kernel.cu`).

    x [N, Cin, H, W]; offset [N, 2*dg*kh*kw, Ho, Wo] ordered (y, x) per
    tap; optional mask [N, dg*kh*kw, Ho, Wo] (v2 modulation); weight
    [Cout, Cin/groups, kh, kw]. TPU-native: every kernel tap becomes one
    batched bilinear gather over its offset field, accumulated into an
    im2col-like tensor that contracts with the weights on the MXU — no
    per-position scalar loops.
    """
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def fn(x, offset, weight, bias, mask):
        n, cin, h, w = x.shape
        cout, cin_g, kh, kw = weight.shape
        ho = (h + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        wo = (w + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        dg = deformable_groups
        off = offset.reshape(n, dg, kh * kw, 2, ho, wo)
        if mask is not None:
            mk = mask.reshape(n, dg, kh * kw, ho, wo)
        # base sampling grid per tap: [kh*kw, Ho, Wo]
        base_y = (jnp.arange(ho) * stride[0] - padding[0])[None, :, None] \
            + (jnp.arange(kh) * dilation[0])[:, None, None].repeat(
                kw, axis=0).reshape(kh * kw, 1, 1)
        base_x = (jnp.arange(wo) * stride[1] - padding[1])[None, None, :] \
            + jnp.tile(jnp.arange(kw) * dilation[1], kh)[:, None, None]
        ys = base_y[None, None] + off[:, :, :, 0]       # [N, dg, K, Ho, Wo]
        xs = base_x[None, None] + off[:, :, :, 1]

        # bilinear sample x at (ys, xs) for each deformable group's
        # channel slice: returns [N, dg, C/dg, K, Ho, Wo]
        cg = cin // dg
        xg = x.reshape(n, dg, cg, h, w)

        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy1 = (ys - y0)[:, :, None]                     # [N, dg, 1, K, ...]
        wx1 = (xs - x0)[:, :, None]
        wy0, wx0 = 1.0 - wy1, 1.0 - wx1
        valid = ((ys > -1) & (ys < h) & (xs > -1) & (xs < w))[:, :, None]

        def gather(yi, xi):
            yi = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
            flat = yi * w + xi                          # [N, dg, K, Ho, Wo]
            xf = xg.reshape(n, dg, cg, h * w)
            # take_along_axis over the flattened spatial dim
            idx = flat.reshape(n, dg, 1, -1)
            out = jnp.take_along_axis(
                xf, jnp.broadcast_to(idx, (n, dg, cg, idx.shape[-1])),
                axis=-1)
            return out.reshape(n, dg, cg, kh * kw, ho, wo)

        sampled = (gather(y0, x0) * wy0 * wx0
                   + gather(y0, x0 + 1) * wy0 * wx1
                   + gather(y0 + 1, x0) * wy1 * wx0
                   + gather(y0 + 1, x0 + 1) * wy1 * wx1)
        sampled = jnp.where(valid, sampled, 0.0)
        if mask is not None:
            sampled = sampled * mk[:, :, None]
        # [N, Cin, K, Ho, Wo] -> grouped contraction with the weights
        col = sampled.reshape(n, cin, kh * kw, ho, wo)
        colg = col.reshape(n, groups, cin // groups, kh * kw, ho, wo)
        wg = weight.reshape(groups, cout // groups, cin_g, kh * kw)
        out = jnp.einsum("ngckhw,gock->ngohw", colg, wg,
                         preferred_element_type=jnp.float32)
        out = out.reshape(n, cout, ho, wo).astype(x.dtype)
        if bias is not None:
            out = out + bias.reshape(1, cout, 1, 1)
        return out

    return run_op("deform_conv2d", fn, (x, offset, weight, bias, mask))


class DeformConv2D:
    """Layer wrapper over :func:`deform_conv2d` (reference
    `vision/ops.py:DeformConv2D`). Holds weight/bias; offset (and v2
    mask) are runtime inputs, as in the reference."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        from .. import nn

        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
        # reuse Conv2D's parameter creation (fan-in init, attrs)
        self._conv = nn.Conv2D(in_channels, out_channels, ks, stride=stride,
                               padding=padding, dilation=dilation,
                               groups=groups, weight_attr=weight_attr,
                               bias_attr=bias_attr)
        self.weight = self._conv.weight
        self.bias = self._conv.bias

    def parameters(self):
        return self._conv.parameters()

    def __call__(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._cfg)


# -- reference detection-op parity batch (phi/api/yaml: box_coder,
#    prior_box, yolo_box, matrix_nms, psroi_pool,
#    distribute_fpn_proposals, generate_proposals) --------------------------
from ..tensor.registry import defop  # noqa: E402


@defop(differentiable=False)
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0):
    """Encode/decode boxes against priors (reference op `box_coder`,
    kernel `phi/kernels/cpu/box_coder_kernel.cc` — formulas match
    EncodeCenterSize/DecodeCenterSize exactly, including the +1
    width/height for unnormalized boxes)."""
    pb = jnp.asarray(prior_box, jnp.float32)
    tb = jnp.asarray(target_box, jnp.float32)
    one = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + one
    ph = pb[:, 3] - pb[:, 1] + one
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    if prior_box_var is None:
        var = jnp.ones((pb.shape[0], 4), jnp.float32)
    elif isinstance(prior_box_var, (list, tuple)):
        var = jnp.broadcast_to(jnp.asarray(prior_box_var, jnp.float32),
                               (pb.shape[0], 4))
    else:
        var = jnp.asarray(prior_box_var, jnp.float32)
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + one
        th = tb[:, 3] - tb[:, 1] + one
        tcx = (tb[:, 0] + tb[:, 2]) / 2
        tcy = (tb[:, 1] + tb[:, 3]) / 2
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)     # [N, M, 4]
        return out / var[None, :, :]
    if code_type != "decode_center_size":
        raise ValueError(f"bad code_type {code_type!r}")
    # decode: target [N, M, 4]; prior broadcast along `axis`
    exp = (slice(None), None) if axis == 0 else (None, slice(None))
    pw_, ph_ = pw[exp], ph[exp]
    pcx_, pcy_ = pcx[exp], pcy[exp]
    var_ = var[exp + (slice(None),)]
    cx = var_[..., 0] * tb[..., 0] * pw_ + pcx_
    cy = var_[..., 1] * tb[..., 1] * ph_ + pcy_
    w = jnp.exp(var_[..., 2] * tb[..., 2]) * pw_
    h = jnp.exp(var_[..., 3] * tb[..., 3]) * ph_
    return jnp.stack([cx - w / 2, cy - h / 2,
                      cx + w / 2 - one, cy + h / 2 - one], axis=-1)


@defop(differentiable=False)
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes (reference op `prior_box`,
    `phi/kernels/cpu/prior_box_kernel.cc`). Returns (boxes, variances)
    each [H, W, num_priors, 4]."""
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    max_sizes = list(max_sizes or [])
    cx = (np.arange(fw) + offset) * step_w        # [W]
    cy = (np.arange(fh) + offset) * step_h        # [H]
    whs = []                                       # (w/2, h/2) per prior
    for s, mn in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((mn / 2, mn / 2))
            if max_sizes:
                mx = max_sizes[s]
                whs.append((np.sqrt(mn * mx) / 2,) * 2)
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((mn * np.sqrt(ar) / 2, mn / np.sqrt(ar) / 2))
        else:
            for ar in ars:
                whs.append((mn * np.sqrt(ar) / 2, mn / np.sqrt(ar) / 2))
            if max_sizes:
                mx = max_sizes[s]
                whs.append((np.sqrt(mn * mx) / 2,) * 2)
    wh = np.asarray(whs, np.float32)              # [P, 2]
    ccx = np.broadcast_to(cx[None, :, None], (fh, fw, wh.shape[0]))
    ccy = np.broadcast_to(cy[:, None, None], (fh, fw, wh.shape[0]))
    boxes = np.stack([(ccx - wh[None, None, :, 0]) / iw,
                      (ccy - wh[None, None, :, 1]) / ih,
                      (ccx + wh[None, None, :, 0]) / iw,
                      (ccy + wh[None, None, :, 1]) / ih], axis=-1)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.broadcast_to(np.asarray(variance, np.float32),
                            boxes.shape).copy()
    return jnp.asarray(boxes), jnp.asarray(vars_)


@defop(differentiable=False)
def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """YOLOv3 head decode (reference op `yolo_box`,
    `phi/kernels/funcs/yolo_box_util.h:GetYoloBox` — same center/size
    formulas, clipping, and confidence gating)."""
    x = jnp.asarray(x, jnp.float32)
    n, _, h, w = x.shape
    an = len(anchors) // 2
    aw = jnp.asarray(anchors[0::2], jnp.float32)
    ah = jnp.asarray(anchors[1::2], jnp.float32)
    isz = jnp.asarray(img_size, jnp.float32)       # [N, 2] = (h, w)
    img_h = isz[:, 0][:, None, None, None]
    img_w = isz[:, 1][:, None, None, None]
    in_w = downsample_ratio * w
    in_h = downsample_ratio * h
    if iou_aware:
        ious = jax.nn.sigmoid(x[:, :an].reshape(n, an, 1, h, w))
        x = x[:, an:]
    v = x.reshape(n, an, 5 + int(class_num), h, w)
    gi = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gj = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    scale, bias = float(scale_x_y), -0.5 * (float(scale_x_y) - 1)
    cx = (gi + jax.nn.sigmoid(v[:, :, 0]) * scale + bias) * img_w / w
    cy = (gj + jax.nn.sigmoid(v[:, :, 1]) * scale + bias) * img_h / h
    bw = jnp.exp(v[:, :, 2]) * aw[None, :, None, None] * img_w / in_w
    bh = jnp.exp(v[:, :, 3]) * ah[None, :, None, None] * img_h / in_h
    conf = jax.nn.sigmoid(v[:, :, 4])
    if iou_aware:
        conf = conf ** (1 - iou_aware_factor) \
            * ious[:, :, 0] ** iou_aware_factor
    x1, y1 = cx - bw / 2, cy - bh / 2
    x2, y2 = cx + bw / 2, cy + bh / 2
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    keep = (conf > conf_thresh).astype(jnp.float32)
    boxes = jnp.stack([x1, y1, x2, y2], axis=2) * keep[:, :, None]
    scores = jax.nn.sigmoid(v[:, :, 5:]) * (conf * keep)[:, :, None]
    boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(n, -1, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, -1, int(class_num))
    return boxes, scores


@defop(differentiable=False)
def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None):
    """Assign RoIs to FPN levels (reference op
    `distribute_fpn_proposals`,
    `phi/kernels/impl/distribute_fpn_proposals_kernel_impl.h`):
    level = floor(refer_level + log2(sqrt(area) / refer_scale)),
    clamped to [min_level, max_level]. Returns (rois per level,
    restore_index) with each level's rois gathered in order."""
    rois = jnp.asarray(fpn_rois, jnp.float32)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = jnp.sqrt(ws * hs)
    lvl = jnp.floor(jnp.log2(scale / float(refer_scale) + 1e-8)) \
        + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    order = jnp.argsort(lvl, stable=True)
    restore = jnp.argsort(order, stable=True)
    multi_rois, counts = [], []
    for level in range(int(min_level), int(max_level) + 1):
        mask = lvl == level
        counts.append(jnp.sum(mask.astype(jnp.int32)))
        # stable partition: rois of this level in original order,
        # padded region filled by duplicating the sort gather (callers
        # use the per-level count to slice)
        sel = jnp.argsort(jnp.where(mask, 0, 1), stable=True)
        multi_rois.append(rois[sel])
    return tuple(multi_rois) + (restore,) + tuple(counts)


@defop(differentiable=False)
def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=-1, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True):
    """Matrix NMS (reference op `matrix_nms`,
    `phi/kernels/impl/matrix_nms_kernel_impl.h` — SOLOv2's parallel
    soft suppression). bboxes [N, M, 4], scores [N, C, M]; returns
    ([N, K, 6] (class, score, box) sorted by decayed score, padded with
    -1 rows, and per-image kept counts [N])."""
    b = jnp.asarray(bboxes, jnp.float32)
    s = jnp.asarray(scores, jnp.float32)
    n, c, m = s.shape
    top_k = m if nms_top_k < 0 else min(int(nms_top_k), m)

    def one_class(boxes, sc):
        order = jnp.argsort(-sc)[:top_k]
        bs, ss = boxes[order], sc[order]
        valid = ss > score_threshold
        x1, y1, x2, y2 = bs[:, 0], bs[:, 1], bs[:, 2], bs[:, 3]
        one = 0.0 if normalized else 1.0
        area = (x2 - x1 + one) * (y2 - y1 + one)
        ix1 = jnp.maximum(x1[:, None], x1[None, :])
        iy1 = jnp.maximum(y1[:, None], y1[None, :])
        ix2 = jnp.minimum(x2[:, None], x2[None, :])
        iy2 = jnp.minimum(y2[:, None], y2[None, :])
        iw = jnp.maximum(ix2 - ix1 + one, 0)
        ih = jnp.maximum(iy2 - iy1 + one, 0)
        inter = iw * ih
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                  1e-10)
        upper = jnp.tril(iou, k=-1)                 # [i, j<i]: iou with
        #                                             higher-scored box j
        # compensate iou of j = its own max iou with anything above it
        comp = jnp.max(upper, axis=1)
        if use_gaussian:
            decay = jnp.exp((comp[None, :] ** 2 - upper ** 2)
                            / gaussian_sigma)
        else:
            decay = (1 - upper) / jnp.maximum(1 - comp[None, :], 1e-10)
        decay = jnp.where(jnp.tril(jnp.ones_like(iou), k=-1) > 0,
                          decay, jnp.inf)
        dec = jnp.min(decay, axis=1)     # over higher-scored boxes j < i
        dec = jnp.where(jnp.isinf(dec), 1.0, dec)
        out_s = jnp.where(valid, ss * dec, -1.0)
        return bs, out_s

    outs, cnts = [], []
    for bi in range(n):
        rows = []
        for ci in range(c):
            if ci == background_label:
                continue
            bs, ds = one_class(b[bi], s[bi, ci])
            keep = ds > post_threshold
            rows.append(jnp.concatenate(
                [jnp.full((bs.shape[0], 1), ci, jnp.float32),
                 jnp.where(keep, ds, -1.0)[:, None],
                 jnp.where(keep[:, None], bs, -1.0)], axis=1))
        if not rows:  # every class was the background class
            rows = [jnp.full((1, 6), -1.0, jnp.float32)]
        allr = jnp.concatenate(rows, axis=0)
        order = jnp.argsort(-allr[:, 1])
        k = allr.shape[0] if keep_top_k < 0 else min(int(keep_top_k),
                                                     allr.shape[0])
        top = allr[order[:k]]
        cnts.append(jnp.sum((top[:, 1] > 0).astype(jnp.int32)))
        outs.append(top)
    return jnp.stack(outs), jnp.stack(cnts)


@defop(differentiable=False)
def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Position-sensitive RoI pooling (reference op `psroi_pool`,
    `phi/kernels/gpu/psroi_pool_kernel.cu`): channel block (i, j) of
    the output grid average-pools its own C/(k*k) input channels over
    the (i, j) spatial bin."""
    oh, ow = (output_size if isinstance(output_size, (list, tuple))
              else (output_size, output_size))
    x = jnp.asarray(x, jnp.float32)
    rois = jnp.asarray(boxes, jnp.float32)
    n, c, h, w = x.shape
    out_c = c // (oh * ow)
    nb = np.asarray(boxes_num).astype(np.int64)
    batch_of = np.repeat(np.arange(nb.shape[0]), nb)

    def pool_one(roi, img):
        x1 = roi[0] * spatial_scale
        y1 = roi[1] * spatial_scale
        x2 = roi[2] * spatial_scale
        y2 = roi[3] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w, bin_h = rw / ow, rh / oh
        # mask-based average per bin: differentiable-free gather of the
        # whole feature map with per-bin membership weights
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        out = []
        for i in range(oh):
            for j in range(ow):
                hs = jnp.floor(y1 + i * bin_h)
                he = jnp.ceil(y1 + (i + 1) * bin_h)
                ws_ = jnp.floor(x1 + j * bin_w)
                we = jnp.ceil(x1 + (j + 1) * bin_w)
                mask = ((ys[:, None] >= hs) & (ys[:, None] < he)
                        & (xs[None, :] >= ws_) & (xs[None, :] < we))
                cnt = jnp.maximum(jnp.sum(mask), 1)
                chans = img[(i * ow + j) * out_c:(i * ow + j + 1) * out_c]
                out.append(jnp.sum(chans * mask[None], axis=(1, 2)) / cnt)
        return jnp.stack(out, axis=0).reshape(oh, ow, out_c) \
            .transpose(2, 0, 1)

    return jnp.stack([pool_one(rois[r], x[batch_of[r]])
                      for r in range(rois.shape[0])])


@defop(differentiable=False)
def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False):
    """RPN proposal generation (reference op `generate_proposals`,
    `phi/kernels/gpu/generate_proposals_kernel.cu`): decode anchor
    deltas, clip to image, filter small boxes, NMS, keep top-N. Single
    image ([1, ...] inputs); returns (rois [post_nms_top_n, 4],
    roi_scores, count) padded with zeros."""
    sc = jnp.asarray(scores, jnp.float32)[0]        # [A, H, W]
    bd = jnp.asarray(bbox_deltas, jnp.float32)[0]   # [A*4, H, W]
    a, h, w = sc.shape
    anc = jnp.asarray(anchors, jnp.float32).reshape(-1, 4)
    var = jnp.asarray(variances, jnp.float32).reshape(-1, 4)
    s_flat = sc.transpose(1, 2, 0).reshape(-1)
    d = bd.reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
    off = 1.0 if pixel_offset else 0.0
    aw = anc[:, 2] - anc[:, 0] + off
    ah = anc[:, 3] - anc[:, 1] + off
    acx = anc[:, 0] + aw / 2
    acy = anc[:, 1] + ah / 2
    cx = var[:, 0] * d[:, 0] * aw + acx
    cy = var[:, 1] * d[:, 1] * ah + acy
    bw = jnp.exp(jnp.minimum(var[:, 2] * d[:, 2], 10.0)) * aw
    bh = jnp.exp(jnp.minimum(var[:, 3] * d[:, 3], 10.0)) * ah
    props = jnp.stack([cx - bw / 2, cy - bh / 2,
                       cx + bw / 2 - off, cy + bh / 2 - off], axis=1)
    ih, iw = (jnp.asarray(img_size, jnp.float32).reshape(-1)[0],
              jnp.asarray(img_size, jnp.float32).reshape(-1)[1])
    props = jnp.stack([jnp.clip(props[:, 0], 0, iw - off),
                       jnp.clip(props[:, 1], 0, ih - off),
                       jnp.clip(props[:, 2], 0, iw - off),
                       jnp.clip(props[:, 3], 0, ih - off)], axis=1)
    pw = props[:, 2] - props[:, 0] + off
    ph = props[:, 3] - props[:, 1] + off
    ok = (pw >= min_size) & (ph >= min_size)
    s_flat = jnp.where(ok, s_flat, -1e10)
    top = min(int(pre_nms_top_n), s_flat.shape[0])
    order = jnp.argsort(-s_flat)[:top]
    props, s_top = props[order], s_flat[order]
    keep = _nms_kept_mask(props, nms_thresh)
    s_kept = jnp.where(keep & (s_top > -1e9), s_top, -1e10)
    order2 = jnp.argsort(-s_kept)[:int(post_nms_top_n)]
    rois = props[order2]
    rs = s_kept[order2]
    count = jnp.sum((rs > -1e9).astype(jnp.int32))
    valid = (rs > -1e9)[:, None]
    return jnp.where(valid, rois, 0.0), jnp.where(valid[:, 0], rs, 0.0), \
        count


@defop(differentiable=False)
def multiclass_nms3(bboxes, scores, score_threshold=0.05, nms_top_k=-1,
                    keep_top_k=100, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=-1, rois_num=None):
    """Per-class greedy NMS + cross-class top-k (reference op
    `multiclass_nms3`, `phi/kernels/funcs/detection/nms_util.h`).
    bboxes [N, M, 4], scores [N, C, M]; returns ([N, keep_top_k, 6]
    rows (class, score, box) padded with -1, kept counts [N])."""
    b = jnp.asarray(bboxes, jnp.float32)
    s = jnp.asarray(scores, jnp.float32)
    n, c, m = s.shape
    top_k = m if nms_top_k < 0 else min(int(nms_top_k), m)
    outs, cnts = [], []
    for bi in range(n):
        rows = []
        for ci in range(c):
            if ci == background_label:
                continue
            sc = s[bi, ci]
            order = jnp.argsort(-sc)[:top_k]
            bs, ss = b[bi][order], sc[order]
            keep = _nms_kept_mask(bs, nms_threshold) \
                & (ss > score_threshold)
            rows.append(jnp.concatenate(
                [jnp.full((top_k, 1), ci, jnp.float32),
                 jnp.where(keep, ss, -1.0)[:, None],
                 jnp.where(keep[:, None], bs, -1.0)], axis=1))
        if not rows:  # every class was the background class
            rows = [jnp.full((1, 6), -1.0, jnp.float32)]
        allr = jnp.concatenate(rows, axis=0)
        order = jnp.argsort(-allr[:, 1])
        k = allr.shape[0] if keep_top_k < 0 else min(int(keep_top_k),
                                                     allr.shape[0])
        top = allr[order[:k]]
        cnts.append(jnp.sum((top[:, 1] > 0).astype(jnp.int32)))
        outs.append(top)
    return jnp.stack(outs), jnp.stack(cnts)


@defop(differentiable=False)
def read_file(filename):
    """Read a file's bytes as a uint8 tensor (reference op
    `read_file`)."""
    with open(filename, "rb") as f:
        data = f.read()
    return jnp.asarray(np.frombuffer(data, np.uint8))


@defop(differentiable=False)
def decode_jpeg(x, mode="unchanged"):
    """Decode a JPEG byte tensor to CHW uint8 (reference op
    `decode_jpeg`, `phi/kernels/gpu/decode_jpeg_kernel.cu` — nvjpeg
    there; PIL on the host here, feeding the device pipeline)."""
    import io

    from PIL import Image

    raw = bytes(np.asarray(x).astype(np.uint8).tobytes())
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return jnp.asarray(arr)


@defop()
def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0):
    """YOLOv3 training loss (reference op `yolo_loss`,
    `phi/kernels/cpu/yolo_loss_kernel.cc` — same decode, anchor
    matching, ignore mask, location/objectness/class terms and
    (2 - w*h) box scale). x [N, M*(5+C), H, W]; gt_box [N, B, 4]
    (cx, cy, w, h normalized); gt_label [N, B]. Returns loss [N]."""
    x = jnp.asarray(x, jnp.float32)
    gt = jnp.asarray(gt_box, jnp.float32)
    lbl = jnp.asarray(gt_label).astype(jnp.int32)
    n, _, h, w = x.shape
    m = len(anchor_mask)
    an_num = len(anchors) // 2
    c = int(class_num)
    input_size = downsample_ratio * h
    aw_all = jnp.asarray(anchors[0::2], jnp.float32)
    ah_all = jnp.asarray(anchors[1::2], jnp.float32)
    mask_arr = np.asarray(anchor_mask, np.int64)
    scale, sbias = float(scale_x_y), -0.5 * (float(scale_x_y) - 1)
    if use_label_smooth:
        smooth = min(1.0 / c, 1.0 / 40)
        pos_t, neg_t = 1.0 - smooth, smooth
    else:
        pos_t, neg_t = 1.0, 0.0
    score = jnp.ones(lbl.shape, jnp.float32) if gt_score is None \
        else jnp.asarray(gt_score, jnp.float32)

    def sce(z, t):
        return jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))

    def iou_cwh(c1x, c1y, w1, h1, c2x, c2y, w2, h2):
        ov_w = jnp.minimum(c1x + w1 / 2, c2x + w2 / 2) \
            - jnp.maximum(c1x - w1 / 2, c2x - w2 / 2)
        ov_h = jnp.minimum(c1y + h1 / 2, c2y + h2 / 2) \
            - jnp.maximum(c1y - h1 / 2, c2y - h2 / 2)
        inter = jnp.where((ov_w < 0) | (ov_h < 0), 0.0, ov_w * ov_h)
        return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)

    def per_image(xi, gts, lbls, scores):
        v = xi.reshape(m, 5 + c, h, w)
        gi_grid = jnp.arange(w, dtype=jnp.float32)[None, None, :]
        gj_grid = jnp.arange(h, dtype=jnp.float32)[None, :, None]
        aw = aw_all[mask_arr][:, None, None]
        ah = ah_all[mask_arr][:, None, None]
        px = (gi_grid + jax.nn.sigmoid(v[:, 0]) * scale + sbias) / w
        py = (gj_grid + jax.nn.sigmoid(v[:, 1]) * scale + sbias) / h
        pw = jnp.exp(v[:, 2]) * aw / input_size
        ph = jnp.exp(v[:, 3]) * ah / input_size
        valid = (gts[:, 2] > 0) & (gts[:, 3] > 0)
        # ignore mask: best IoU of each prediction vs any valid gt
        ious = iou_cwh(px[..., None], py[..., None], pw[..., None],
                       ph[..., None], gts[None, None, None, :, 0],
                       gts[None, None, None, :, 1],
                       gts[None, None, None, :, 2],
                       gts[None, None, None, :, 3])
        ious = jnp.where(valid[None, None, None, :], ious, 0.0)
        best = jnp.max(ious, axis=-1)
        obj_mask = jnp.where(best > ignore_thresh, -1.0, 0.0)  # [m, h, w]
        # gt -> best anchor (shape-only IoU over ALL anchors)
        an_iou = iou_cwh(0.0, 0.0, aw_all[None, :] / input_size,
                         ah_all[None, :] / input_size,
                         0.0, 0.0, gts[:, 2:3], gts[:, 3:4])
        best_n = jnp.argmax(an_iou, axis=1)                     # [B]
        # map to this head's mask slot (-1 = not ours)
        mask_pos = jnp.full((an_num,), -1, jnp.int32) \
            .at[jnp.asarray(mask_arr)].set(jnp.arange(m, dtype=jnp.int32))
        slot = mask_pos[best_n]
        gi = jnp.clip((gts[:, 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gts[:, 1] * h).astype(jnp.int32), 0, h - 1)
        take = valid & (slot >= 0)
        # positive-sample scatter into the objectness mask (last wins,
        # like the reference's t loop)
        obj_mask = obj_mask.at[
            jnp.where(take, slot, m), gj, gi].set(
            scores, mode="drop")
        # location + class losses gathered at each gt's cell
        sslot = jnp.maximum(slot, 0)
        ent = v[sslot, :, gj, gi]                   # [B, 5+c]
        tx = gts[:, 0] * w - gi
        ty = gts[:, 1] * h - gj
        tw_ = jnp.log(jnp.maximum(
            gts[:, 2] * input_size / aw_all[best_n], 1e-9))
        th_ = jnp.log(jnp.maximum(
            gts[:, 3] * input_size / ah_all[best_n], 1e-9))
        bscale = (2.0 - gts[:, 2] * gts[:, 3]) * scores
        loc = (sce(ent[:, 0], tx) + sce(ent[:, 1], ty)
               + jnp.abs(ent[:, 2] - tw_) + jnp.abs(ent[:, 3] - th_)) \
            * bscale
        cls_t = jnp.where(
            jax.nn.one_hot(lbls, c, dtype=jnp.float32) > 0, pos_t, neg_t)
        cls = jnp.sum(sce(ent[:, 5:], cls_t), axis=1) * scores
        gt_loss = jnp.sum(jnp.where(take, loc + cls, 0.0))
        # objectness loss over the whole grid
        obj_logit = v[:, 4]
        obj_l = jnp.where(obj_mask > 1e-5, sce(obj_logit, 1.0) * obj_mask,
                          jnp.where(obj_mask > -0.5,
                                    sce(obj_logit, 0.0), 0.0))
        return gt_loss + jnp.sum(obj_l)

    return jax.vmap(per_image)(x, gt, lbl, score)
