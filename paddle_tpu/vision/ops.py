"""Detection ops (reference: `python/paddle/vision/ops.py` — nms:1867,
roi_align:1640, roi_pool, box kernels in `phi/kernels/gpu/`).

TPU-native notes: NMS's greedy suppression is an O(N^2) IoU matrix +
a ``lax.fori_loop`` sweep (static shapes, no data-dependent Python);
RoI align is vectorized bilinear gather-interpolation over a static
sampling grid, so XLA fuses it into a few gathers + contractions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import run_op

__all__ = ["nms", "roi_align", "roi_pool", "box_iou", "deform_conv2d",
           "DeformConv2D"]


def _iou_matrix(boxes):
    """[N, 4] xyxy -> [N, N] IoU."""
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = (x2 - x1) * (y2 - y1)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def box_iou(boxes1, boxes2):
    """Pairwise IoU between two [N,4]/[M,4] xyxy sets -> [N, M]."""
    def fn(a, b):
        x1, y1, x2, y2 = (a[:, i] for i in range(4))
        u1, v1, u2, v2 = (b[:, i] for i in range(4))
        area_a = (x2 - x1) * (y2 - y1)
        area_b = (u2 - u1) * (v2 - v1)
        ix1 = jnp.maximum(x1[:, None], u1[None, :])
        iy1 = jnp.maximum(y1[:, None], v1[None, :])
        ix2 = jnp.minimum(x2[:, None], u2[None, :])
        iy2 = jnp.minimum(y2[:, None], v2[None, :])
        inter = jnp.maximum(ix2 - ix1, 0.0) * jnp.maximum(iy2 - iy1, 0.0)
        union = area_a[:, None] + area_b[None, :] - inter
        return jnp.where(union > 0, inter / union, 0.0)

    return run_op("box_iou", fn, (boxes1, boxes2), differentiable=False)


def _nms_kept_mask(boxes, iou_threshold):
    """Greedy NMS on boxes already sorted by descending score; returns a
    bool keep-mask. lax.fori_loop over rows: a row survives iff no
    earlier surviving row overlaps it beyond the threshold."""
    iou = _iou_matrix(boxes)
    n = boxes.shape[0]

    def body(i, keep):
        # suppressed if any kept j < i has IoU > thr
        over = (iou[i] > iou_threshold) & keep \
            & (jnp.arange(n) < i)
        return keep.at[i].set(~jnp.any(over))

    return jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference `vision/ops.py:1867`. Returns indices of kept boxes
    sorted by descending score (or input order when ``scores`` is None),
    truncated to ``top_k``."""
    def fn(boxes, scores, category_idxs):
        n = boxes.shape[0]
        order = jnp.arange(n) if scores is None \
            else jnp.argsort(-scores)
        sorted_boxes = boxes[order]
        if category_idxs is None:
            keep = _nms_kept_mask(sorted_boxes, iou_threshold)
        else:
            # batched NMS: offset each category's boxes to disjoint
            # regions so cross-category IoU is 0 (standard trick — one
            # kernel instead of a per-category loop)
            cats = category_idxs[order].astype(sorted_boxes.dtype)
            span = jnp.max(sorted_boxes) - jnp.min(sorted_boxes) + 1.0
            shifted = sorted_boxes + (cats * span)[:, None]
            keep = _nms_kept_mask(shifted, iou_threshold)
        kept = order[jnp.where(keep, size=n, fill_value=-1)[0]]
        kept = kept[jnp.where(kept >= 0, size=n, fill_value=-1)[0]]
        count = int(jnp.sum(keep))
        return kept[:count] if top_k is None \
            else kept[:min(top_k, count)]

    # host-side sizes: NMS output is inherently data-dependent, so this
    # op runs eagerly (like the reference's CPU/GPU kernel returning a
    # dynamic-size tensor)
    return run_op("nms", fn, (boxes, scores, category_idxs),
                  differentiable=False)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Reference `vision/ops.py:1640` (Mask R-CNN RoI Align). x [N,C,H,W];
    boxes [R, 4] xyxy in input-image coordinates; boxes_num [N] ints
    summing to R. Output [R, C, ph, pw]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def fn(x, boxes, boxes_num):
        n, c, h, w = x.shape
        r = boxes.shape[0]
        # map each roi to its batch image
        img_idx = jnp.repeat(jnp.arange(n), boxes_num, axis=0,
                             total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        bx = boxes * spatial_scale
        x1, y1, x2, y2 = (bx[:, i] for i in range(4))
        x1, y1 = x1 - off, y1 - off
        x2, y2 = x2 - off, y2 - off
        roi_w = x2 - x1
        roi_h = y2 - y1
        if not aligned:
            roi_w = jnp.maximum(roi_w, 1.0)
            roi_h = jnp.maximum(roi_h, 1.0)
        bin_w = roi_w / pw
        bin_h = roi_h / ph
        s = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [R, ph, s] y coords and [R, pw, s] x coords
        sy = (jnp.arange(ph)[None, :, None]
              + (jnp.arange(s)[None, None, :] + 0.5) / s)
        sx = (jnp.arange(pw)[None, :, None]
              + (jnp.arange(s)[None, None, :] + 0.5) / s)
        ys = y1[:, None, None] + sy * bin_h[:, None, None]   # [R, ph, s]
        xs = x1[:, None, None] + sx * bin_w[:, None, None]   # [R, pw, s]

        def bilinear(img, yy, xx):
            """img [C, H, W]; yy [ph*s], xx [pw*s] -> [C, ph*s, pw*s]."""
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
            y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
            wy1 = jnp.clip(yy - y0, 0.0, 1.0)
            wx1 = jnp.clip(xx - x0, 0.0, 1.0)
            wy0, wx0 = 1.0 - wy1, 1.0 - wx1
            # zero contribution for samples outside the feature map
            valid_y = ((yy >= -1) & (yy <= h)).astype(img.dtype)
            valid_x = ((xx >= -1) & (xx <= w)).astype(img.dtype)
            g = lambda yi, xi: img[:, yi][:, :, xi]      # [C, len(y), len(x)]
            out = (g(y0i, x0i) * (wy0 * valid_y)[None, :, None]
                   * (wx0 * valid_x)[None, None, :]
                   + g(y0i, x1i) * (wy0 * valid_y)[None, :, None]
                   * (wx1 * valid_x)[None, None, :]
                   + g(y1i, x0i) * (wy1 * valid_y)[None, :, None]
                   * (wx0 * valid_x)[None, None, :]
                   + g(y1i, x1i) * (wy1 * valid_y)[None, :, None]
                   * (wx1 * valid_x)[None, None, :])
            return out

        def per_roi(ri):
            img = x[img_idx[ri]]                        # [C, H, W]
            yy = ys[ri].reshape(-1)                     # [ph*s]
            xx = xs[ri].reshape(-1)                     # [pw*s]
            vals = bilinear(img, yy, xx)                # [C, ph*s, pw*s]
            vals = vals.reshape(c, ph, s, pw, s)
            return jnp.mean(vals, axis=(2, 4))          # [C, ph, pw]

        return jax.vmap(per_roi)(jnp.arange(r))

    return run_op("roi_align", fn, (x, boxes, boxes_num))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Reference `vision/ops.py` roi_pool (max pooling per bin, Fast
    R-CNN). Same layout as :func:`roi_align`."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def fn(x, boxes, boxes_num):
        n, c, h, w = x.shape
        r = boxes.shape[0]
        img_idx = jnp.repeat(jnp.arange(n), boxes_num, axis=0,
                             total_repeat_length=r)
        bx = jnp.round(boxes * spatial_scale)
        x1 = bx[:, 0].astype(jnp.int32)
        y1 = bx[:, 1].astype(jnp.int32)
        x2 = jnp.maximum(bx[:, 2].astype(jnp.int32), x1 + 1)
        y2 = jnp.maximum(bx[:, 3].astype(jnp.int32), y1 + 1)

        ww = jnp.arange(w)
        hh = jnp.arange(h)

        def per_roi(ri):
            img = x[img_idx[ri]]
            # bin edges (float) over the roi
            ys = y1[ri] + (y2[ri] - y1[ri]) * jnp.arange(ph + 1) / ph
            xs = x1[ri] + (x2[ri] - x1[ri]) * jnp.arange(pw + 1) / pw

            def pool_bin(by, bx_):
                y_lo = jnp.floor(ys[by]).astype(jnp.int32)
                y_hi = jnp.ceil(ys[by + 1]).astype(jnp.int32)
                x_lo = jnp.floor(xs[bx_]).astype(jnp.int32)
                x_hi = jnp.ceil(xs[bx_ + 1]).astype(jnp.int32)
                m = ((hh >= y_lo) & (hh < jnp.maximum(y_hi, y_lo + 1)))[
                    :, None] & \
                    ((ww >= x_lo) & (ww < jnp.maximum(x_hi, x_lo + 1)))[
                    None, :]
                m = m & (hh[:, None] < h) & (ww[None, :] < w)
                return jnp.max(
                    jnp.where(m[None], img, -jnp.inf), axis=(1, 2))

            grid = jax.vmap(lambda by: jax.vmap(
                lambda bx_: pool_bin(by, bx_))(jnp.arange(pw)))(
                jnp.arange(ph))                          # [ph, pw, C]
            return jnp.transpose(grid, (2, 0, 1))

        return jax.vmap(per_roi)(jnp.arange(r))

    return run_op("roi_pool", fn, (x, boxes, boxes_num))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference `vision/ops.py:753`,
    CUDA kernel `phi/kernels/gpu/deformable_conv_kernel.cu`).

    x [N, Cin, H, W]; offset [N, 2*dg*kh*kw, Ho, Wo] ordered (y, x) per
    tap; optional mask [N, dg*kh*kw, Ho, Wo] (v2 modulation); weight
    [Cout, Cin/groups, kh, kw]. TPU-native: every kernel tap becomes one
    batched bilinear gather over its offset field, accumulated into an
    im2col-like tensor that contracts with the weights on the MXU — no
    per-position scalar loops.
    """
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def fn(x, offset, weight, bias, mask):
        n, cin, h, w = x.shape
        cout, cin_g, kh, kw = weight.shape
        ho = (h + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        wo = (w + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        dg = deformable_groups
        off = offset.reshape(n, dg, kh * kw, 2, ho, wo)
        if mask is not None:
            mk = mask.reshape(n, dg, kh * kw, ho, wo)
        # base sampling grid per tap: [kh*kw, Ho, Wo]
        base_y = (jnp.arange(ho) * stride[0] - padding[0])[None, :, None] \
            + (jnp.arange(kh) * dilation[0])[:, None, None].repeat(
                kw, axis=0).reshape(kh * kw, 1, 1)
        base_x = (jnp.arange(wo) * stride[1] - padding[1])[None, None, :] \
            + jnp.tile(jnp.arange(kw) * dilation[1], kh)[:, None, None]
        ys = base_y[None, None] + off[:, :, :, 0]       # [N, dg, K, Ho, Wo]
        xs = base_x[None, None] + off[:, :, :, 1]

        # bilinear sample x at (ys, xs) for each deformable group's
        # channel slice: returns [N, dg, C/dg, K, Ho, Wo]
        cg = cin // dg
        xg = x.reshape(n, dg, cg, h, w)

        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy1 = (ys - y0)[:, :, None]                     # [N, dg, 1, K, ...]
        wx1 = (xs - x0)[:, :, None]
        wy0, wx0 = 1.0 - wy1, 1.0 - wx1
        valid = ((ys > -1) & (ys < h) & (xs > -1) & (xs < w))[:, :, None]

        def gather(yi, xi):
            yi = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
            flat = yi * w + xi                          # [N, dg, K, Ho, Wo]
            xf = xg.reshape(n, dg, cg, h * w)
            # take_along_axis over the flattened spatial dim
            idx = flat.reshape(n, dg, 1, -1)
            out = jnp.take_along_axis(
                xf, jnp.broadcast_to(idx, (n, dg, cg, idx.shape[-1])),
                axis=-1)
            return out.reshape(n, dg, cg, kh * kw, ho, wo)

        sampled = (gather(y0, x0) * wy0 * wx0
                   + gather(y0, x0 + 1) * wy0 * wx1
                   + gather(y0 + 1, x0) * wy1 * wx0
                   + gather(y0 + 1, x0 + 1) * wy1 * wx1)
        sampled = jnp.where(valid, sampled, 0.0)
        if mask is not None:
            sampled = sampled * mk[:, :, None]
        # [N, Cin, K, Ho, Wo] -> grouped contraction with the weights
        col = sampled.reshape(n, cin, kh * kw, ho, wo)
        colg = col.reshape(n, groups, cin // groups, kh * kw, ho, wo)
        wg = weight.reshape(groups, cout // groups, cin_g, kh * kw)
        out = jnp.einsum("ngckhw,gock->ngohw", colg, wg,
                         preferred_element_type=jnp.float32)
        out = out.reshape(n, cout, ho, wo).astype(x.dtype)
        if bias is not None:
            out = out + bias.reshape(1, cout, 1, 1)
        return out

    return run_op("deform_conv2d", fn, (x, offset, weight, bias, mask))


class DeformConv2D:
    """Layer wrapper over :func:`deform_conv2d` (reference
    `vision/ops.py:DeformConv2D`). Holds weight/bias; offset (and v2
    mask) are runtime inputs, as in the reference."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        from .. import nn

        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
        # reuse Conv2D's parameter creation (fan-in init, attrs)
        self._conv = nn.Conv2D(in_channels, out_channels, ks, stride=stride,
                               padding=padding, dilation=dilation,
                               groups=groups, weight_attr=weight_attr,
                               bias_attr=bias_attr)
        self.weight = self._conv.weight
        self.bias = self._conv.bias

    def parameters(self):
        return self._conv.parameters()

    def __call__(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._cfg)
