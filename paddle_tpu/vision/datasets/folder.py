"""Folder datasets (reference: `python/paddle/vision/datasets/folder.py:107`
``DatasetFolder`` / ``ImageFolder``).

A directory tree of ``root/class_x/img.ext`` becomes a labeled dataset;
``ImageFolder`` is the unlabeled flat variant. Loading is PIL on the
host (the device pipeline starts at the DataLoader's numpy batches).
"""

from __future__ import annotations

import os

import numpy as np

from ...io import Dataset

__all__ = ["DatasetFolder", "ImageFolder"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def default_loader(path):
    from PIL import Image

    with open(path, "rb") as f:
        img = Image.open(f)
        return img.convert("RGB")


def has_valid_extension(filename, extensions):
    return filename.lower().endswith(tuple(extensions))


def make_dataset(directory, class_to_idx, extensions=None,
                 is_valid_file=None):
    """(path, class_index) samples from a class-per-subdir tree
    (reference folder.py:make_dataset)."""
    if (extensions is None) == (is_valid_file is None):
        raise ValueError(
            "pass exactly one of extensions / is_valid_file")
    if is_valid_file is None:
        def is_valid_file(p):
            return has_valid_extension(p, extensions)
    samples = []
    for cls in sorted(class_to_idx):
        d = os.path.join(directory, cls)
        if not os.path.isdir(d):
            continue
        for root, _, fnames in sorted(os.walk(d, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                if is_valid_file(path):
                    samples.append((path, class_to_idx[cls]))
    return samples


class DatasetFolder(Dataset):
    """``root/<class>/<image>`` tree -> (image, label) dataset
    (reference folder.py:107)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = make_dataset(root, self.class_to_idx, extensions,
                                    is_valid_file)
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")
        self.targets = [s[1] for s in self.samples]

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat recursive image list, no labels (reference folder.py
    ``ImageFolder``)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if is_valid_file is None:
            def is_valid_file(p):
                return has_valid_extension(p, extensions)
        samples = []
        for r, _, fnames in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(r, fname)
                if is_valid_file(path):
                    samples.append(path)
        if not samples:
            raise RuntimeError(f"no valid files under {root}")
        self.samples = samples

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
