"""Flowers-102 and VOC2012 datasets (reference:
`python/paddle/vision/datasets/flowers.py`, `voc2012.py`).

Real archives are parsed when their files are given (this build has
zero egress, so nothing downloads); without them each dataset falls
back to a deterministic synthetic task with the same shapes and label
spaces, clearly labeled as synthetic.
"""

from __future__ import annotations

import io
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Flowers", "VOC2012"]


class Flowers(Dataset):
    """102-category flowers (reference flowers.py): jpegs in a tgz,
    labels + split ids in MATLAB files."""

    num_classes = 102

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, backend="cv2"):
        if mode not in ("train", "valid", "test"):
            raise ValueError(f"bad mode {mode!r}")
        self.mode = mode
        self.transform = transform
        self.synthetic = data_file is None
        if self.synthetic:
            rng = np.random.RandomState(
                {"train": 1, "valid": 2, "test": 3}[mode])
            n = {"train": 204, "valid": 102, "test": 102}[mode]
            self._labels = rng.randint(0, self.num_classes, (n,))
            self._imgs = None
            self._rng_seed = int(rng.randint(1 << 30))
            return
        import scipy.io as sio

        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self._ids = setid[key].reshape(-1)          # 1-based image ids
        labels = sio.loadmat(label_file)["labels"].reshape(-1)
        self._labels = labels[self._ids - 1] - 1    # 0-based classes
        self._tar = tarfile.open(data_file)
        self._members = {m.name.split("/")[-1]: m
                         for m in self._tar.getmembers()
                         if m.name.endswith(".jpg")}

    def __getitem__(self, idx):
        if self.synthetic:
            c = int(self._labels[idx])
            rng = np.random.RandomState(self._rng_seed + idx)
            img = np.full((64, 64, 3), c * 2, np.uint8) \
                + rng.randint(0, 20, (64, 64, 3)).astype(np.uint8)
        else:
            from PIL import Image

            name = f"image_{int(self._ids[idx]):05d}.jpg"
            f = self._tar.extractfile(self._members[name])
            img = np.asarray(Image.open(io.BytesIO(f.read()))
                             .convert("RGB"))
        label = int(self._labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label], np.int64)

    def __len__(self):
        return len(self._labels)


class VOC2012(Dataset):
    """Pascal VOC 2012 segmentation (reference voc2012.py): (image,
    mask) pairs from the devkit tar; 21 classes (incl background)."""

    num_classes = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend="cv2"):
        if mode not in ("train", "valid", "trainval"):
            raise ValueError(f"bad mode {mode!r}")
        self.mode = mode
        self.transform = transform
        self.synthetic = data_file is None
        if self.synthetic:
            rng = np.random.RandomState({"train": 5, "valid": 6,
                                         "trainval": 7}[mode])
            self._n = {"train": 40, "valid": 20, "trainval": 60}[mode]
            self._rng_seed = int(rng.randint(1 << 30))
            return
        self._tar = tarfile.open(data_file)
        names = {m.name: m for m in self._tar.getmembers()}
        split = {"train": "train.txt", "valid": "val.txt",
                 "trainval": "trainval.txt"}[mode]
        seg_dir = "VOCdevkit/VOC2012/ImageSets/Segmentation/"
        ids = self._tar.extractfile(names[seg_dir + split]) \
            .read().decode().split()
        self._ids = ids
        self._names = names

    def __getitem__(self, idx):
        if self.synthetic:
            rng = np.random.RandomState(self._rng_seed + idx)
            img = rng.randint(0, 255, (64, 64, 3)).astype(np.uint8)
            mask = np.zeros((64, 64), np.uint8)
            c = rng.randint(1, self.num_classes)
            x0, y0 = rng.randint(0, 32, 2)
            mask[y0:y0 + 24, x0:x0 + 24] = c
            if self.transform is not None:
                img = self.transform(img)
            return img, mask
        from PIL import Image

        vid = self._ids[idx]
        base = "VOCdevkit/VOC2012/"
        img = np.asarray(Image.open(io.BytesIO(self._tar.extractfile(
            self._names[base + f"JPEGImages/{vid}.jpg"]).read()))
            .convert("RGB"))
        mask = np.asarray(Image.open(io.BytesIO(self._tar.extractfile(
            self._names[base + f"SegmentationClass/{vid}.png"]).read())))
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return self._n if self.synthetic else len(self._ids)
