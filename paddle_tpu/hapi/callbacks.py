"""hapi callbacks (reference: `python/paddle/hapi/callbacks.py`)."""

from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "History", "MetricsCallback",
           "CheckpointCallback", "config_callbacks"]


class Callback:
    """Base callback: every hook is a no-op (reference callbacks.py:66)."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch textual progress (reference callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose and self.params.get("epochs"):
            print(f"Epoch {epoch + 1}/{self.params['epochs']}")

    def _fmt(self, logs):
        return " - ".join(
            f"{k}: {np.asarray(v).reshape(-1)[0]:.4f}"
            if isinstance(v, (int, float, np.generic, np.ndarray, list))
            else f"{k}: {v}" for k, v in (logs or {}).items())

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            print(f"step {step}/{self.steps or '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"epoch {epoch + 1} done in {dt:.1f}s - "
                  f"{self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class History(Callback):
    """Records per-epoch logs (keras-style convenience)."""

    def on_train_begin(self, logs=None):
        self.history = []

    def on_epoch_end(self, epoch, logs=None):
        self.history.append(dict(logs or {}))


class ModelCheckpoint(Callback):
    """Save every ``save_freq`` epochs (reference ModelCheckpoint)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference
    EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 min_delta=0, baseline=None, save_best_model=False,
                 save_dir=None):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.save_best_model = save_best_model
        self.save_dir = save_dir
        self.wait = 0
        self.best = None
        self.stopped_epoch = None

    def _better(self, cur, best):
        if self.mode == "max":
            return cur > best + self.min_delta
        return cur < best - self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        # prefer the validation metric: fit prefixes eval logs with
        # "eval_" (the reference feeds EarlyStopping raw eval logs)
        cur = logs.get(f"eval_{self.monitor}", logs.get(self.monitor))
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self.best is None and self.baseline is not None:
            self.best = float(self.baseline)  # must beat the baseline
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.save_dir:
                self.model.save(os.path.join(self.save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                self.model.stop_training = True


class MetricsCallback(Callback):
    """Publishes training-loop signals into the observability registry
    (``paddle_tpu.observability``): per-step wall time, instantaneous
    ips, and an MFU estimate.

    - ``batch_size``: samples per step; enables the ``train_ips`` gauge.
    - ``flops_per_sample``: forward FLOPs for ONE sample. If omitted but
      ``input_size`` is given (a full input shape with batch dim 1, e.g.
      ``(1, 4)``), it is estimated at ``on_train_begin`` via
      ``hapi.model_summary.flops``.
    - ``peak_flops``: the accelerator's peak FLOP/s; enables the
      ``train_mfu`` gauge as ``train_flops_multiplier * flops_per_sample
      * batch_size / step_time / peak_flops`` (the multiplier defaults
      to 3.0 — forward + backward ~= 2x forward).
    - ``flops_watch`` (default ``"hapi.train_step"``): when the compile
      watcher holds a ``cost_analysis`` FLOPs gauge for that callable
      (``paddle_tpu_xla_program_flops{callable=...}``), MFU reads the
      COMPILED step's exact FLOPs (forward + backward + update, per
      step, already batch-inclusive) instead of the ``model_summary``
      analytic count — so fused-loss and MoE models, whose hooked
      forward under-/over-counts, report honest MFU. ``None`` disables
      the gauge read (analytic accounting only).
    - ``sample_memory`` (default True): per-step device-memory gauges
      (``paddle_tpu_device_bytes_in_use`` / ``..._live_array_bytes``,
      see ``observability.compile_watch.sample_device_memory``) plus a
      rate-limited flight-recorder metrics snapshot — host metadata
      walks only, no device sync.

    Metric names: ``train_steps_total``, ``train_step_seconds``,
    ``train_ips``, ``train_mfu``, ``train_loss``.
    """

    #: step-time buckets: 1ms .. 60s
    STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

    def __init__(self, batch_size=None, flops_per_sample=None,
                 input_size=None, peak_flops=None,
                 train_flops_multiplier=3.0, registry=None,
                 sample_memory=True, flops_watch="hapi.train_step"):
        super().__init__()
        from ..observability import metrics as om
        reg = registry if registry is not None else om.default_registry()
        self.sample_memory = bool(sample_memory)
        self._registry = registry
        self.flops_watch = flops_watch
        self.batch_size = batch_size
        self.flops_per_sample = flops_per_sample
        self.input_size = input_size
        self.peak_flops = peak_flops
        self.train_flops_multiplier = float(train_flops_multiplier)
        self._steps = reg.counter("train_steps_total",
                                  "optimizer steps taken")
        self._step_time = reg.histogram("train_step_seconds",
                                        "wall time per train step",
                                        buckets=self.STEP_BUCKETS)
        self._ips = reg.gauge("train_ips",
                              "instantaneous samples per second")
        self._mfu = reg.gauge("train_mfu",
                              "model FLOPs utilization estimate (0..1)")
        self._loss = reg.gauge("train_loss", "last train-step loss")
        self._t0 = None

    def on_train_begin(self, logs=None):
        if self.flops_per_sample is None and self.input_size is not None:
            from .model_summary import flops as _flops
            net = getattr(self.model, "network", self.model)
            try:
                self.flops_per_sample = _flops(net, self.input_size)
            except Exception:
                self.flops_per_sample = None   # un-hookable nets: no MFU

    def _watched_step_flops(self):
        """FLOPs of the last program the compile watcher recorded for
        ``flops_watch`` — the cost_analysis gauge, peeked so an absent
        watch (METRICS=0, jit=False, un-analyzed backend) never mints an
        empty gauge child; None falls back to the analytic count."""
        if not self.flops_watch:
            return None
        from ..observability import metrics as om
        reg = self._registry if self._registry is not None \
            else om.default_registry()
        fam = reg.get("paddle_tpu_xla_program_flops")
        if fam is None:
            return None
        child = fam.peek(self.flops_watch)
        if child is None:
            return None
        v = child.value
        return v if v and v > 0 else None

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self._steps.inc()
        self._step_time.observe(dt)
        loss = (logs or {}).get("loss")
        if loss is not None:
            self._loss.set(float(np.asarray(loss).reshape(-1)[0]))
        if self.batch_size and dt > 0:
            self._ips.set(self.batch_size / dt)
        if self.peak_flops and dt > 0:
            step_flops = self._watched_step_flops()
            if step_flops:
                # exact per-step FLOPs of the compiled program
                # (cost_analysis counts fwd+bwd+update, whole batch) —
                # needs no batch_size: the gauge is batch-inclusive
                self._mfu.set(step_flops / dt / self.peak_flops)
            elif self.flops_per_sample and self.batch_size:
                achieved = (self.train_flops_multiplier
                            * self.flops_per_sample
                            * self.batch_size / dt)
                self._mfu.set(achieved / self.peak_flops)
        if self.sample_memory:
            from ..observability import compile_watch, flight_recorder
            if compile_watch.enabled():
                compile_watch.sample_device_memory(self._registry,
                                                   min_interval=1.0)
                flight_recorder.periodic_snapshot()


class CheckpointCallback(Callback):
    """Fault-tolerant, step-granular checkpointing through
    :class:`~paddle_tpu.distributed.checkpoint_manager
    .CheckpointManager` — the training-side half of the elastic recovery
    loop (reference: `fleet/elastic/manager.py` checkpoint-and-relaunch;
    compare :class:`ModelCheckpoint`, which writes per-epoch and not
    atomically).

    - every ``save_freq_steps`` optimizer steps the network state is
      committed atomically; with ``async_save`` the fit loop is blocked
      only for the device-to-host snapshot.
    - on ``on_train_begin`` the latest committed step is restored in
      place (parameters AND the step counter), so a relaunched worker
      continues at ``restored_step + 1``. The checkpoint root comes
      from ``dir`` or ``$PADDLE_TPU_RESUME_DIR`` — what
      ``launch_elastic(resume_dir=...)`` exports to every generation.
    - on SIGTERM (the TPU preemption notice / the elastic supervisor's
      teardown) the handler only sets a flag; the emergency save runs
      at the NEXT batch boundary — a signal landing mid-optimizer-step
      would otherwise snapshot half-updated parameters into a
      checksum-valid checkpoint — then the process exits.
    - only ``save_rank`` (default 0) commits: every worker of a
      generation receives the same ``PADDLE_TPU_RESUME_DIR``, and
      concurrent commits to one directory would tear each other's
      saves. All ranks restore. (``save_rank=None`` saves everywhere —
      only for distinct per-rank directories.)

    ``global_step`` counts completed optimizer steps monotonically
    across epochs; restore refreshes weights and that counter, while
    epoch/dataloader positioning stays the caller's concern.
    """

    def __init__(self, dir=None, save_freq_steps=100, max_to_keep=5,
                 async_save=True, restore=True, on_preemption=True,
                 manager=None, save_rank=0):
        super().__init__()
        if manager is None:
            from ..distributed.checkpoint_manager import (
                CheckpointManager, resume_dir_from_env)
            root = dir or resume_dir_from_env()
            if not root:
                raise ValueError(
                    "CheckpointCallback needs dir=..., manager=..., or "
                    "$PADDLE_TPU_RESUME_DIR (set by "
                    "launch_elastic(resume_dir=...))")
            manager = CheckpointManager(root, max_to_keep=max_to_keep,
                                        async_save=async_save)
        self.manager = manager
        self.save_freq_steps = int(save_freq_steps)
        self.restore = restore
        self.on_preemption = on_preemption
        self.save_rank = save_rank
        self.global_step = 0
        self.restored_step = None
        self._dirty = False
        self._preempt_signum = None
        self._prev_sigterm = None

    def _net(self):
        return getattr(self.model, "network", self.model)

    def _state(self):
        return {"model": self._net().state_dict()}

    def _is_saver(self):
        if self.save_rank is None:
            return True
        rank = os.environ.get("PADDLE_TRAINER_ID")
        if rank is None:
            try:
                import jax
                rank = jax.process_index()
            except Exception:
                rank = 0
        return int(rank) == int(self.save_rank)

    def on_train_begin(self, logs=None):
        if self.restore:
            # state_dict() returns the live parameter Tensors, so
            # restore_latest fills the network in place
            step = self.manager.restore_latest(self._state())
            if step is not None:
                self.restored_step = step
                self.global_step = step + 1
        if self.on_preemption:
            import signal
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               self._on_preempt_signal)

    def _on_preempt_signal(self, signum, frame):
        # flag only: a mid-optimizer-step save would commit parameters
        # half old-step, half new-step — consistent-looking on disk,
        # corresponding to no step boundary. The next batch boundary
        # saves and exits.
        self._preempt_signum = signum

    def on_train_batch_end(self, step, logs=None):
        from ..testing import faults as _faults
        _faults.fire("train.step", step=self.global_step)
        done = self.global_step          # the step just completed
        self.global_step += 1
        self._dirty = True
        saver = self._is_saver()
        if saver and (done + 1) % self.save_freq_steps == 0:
            self.manager.save(self._state(), done)
            self._dirty = False
        if self._preempt_signum is not None:
            if saver:
                self.manager._m_preempt.inc()
                try:
                    self.manager.save(self._state(), done,
                                      blocking=True)
                except Exception:
                    pass             # exiting anyway; already counted
            os._exit(128 + self._preempt_signum)

    def on_train_end(self, logs=None):
        if self._is_saver() and self._dirty and self.global_step > 0:
            self.manager.save(self._state(), self.global_step - 1,
                              blocking=True)
            self._dirty = False
        self.manager.wait()
        if self.on_preemption and self._prev_sigterm is not None:
            import signal
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference LRScheduler callback:
    by default once per epoch)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        lr = getattr(self.model._optimizer, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


def config_callbacks(callbacks, model, epochs=None, steps=None,
                     log_freq=10, verbose=2, save_freq=1, save_dir=None,
                     metrics=None):
    cbs = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbs):
        cbs.insert(0, ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbs):
        cbs.append(LRScheduler())
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbs):
        cbs.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, History) for c in cbs):
        cbs.append(History())
    clist = CallbackList(cbs)
    clist.set_model(model)
    clist.set_params({"epochs": epochs, "steps": steps,
                      "verbose": verbose, "metrics": metrics or []})
    return clist
