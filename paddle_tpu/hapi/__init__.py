"""hapi — the high-level ``Model.fit`` training API.

Reference: `python/paddle/hapi/model.py:1052` (``Model``), ``.fit:1750``,
``.evaluate:1999``, ``.predict``; callbacks in `hapi/callbacks.py`.
TPU-native twist: ``prepare(..., jit=True)`` (the default) wraps the train
and eval steps in ``paddle_tpu.jit.to_static``, so ``Model.fit`` drives
ONE compiled XLA program per step instead of per-op eager dispatch —
metrics stream on host from the step outputs.
"""

from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor
from .. import jit as jit_mod
from ..metric import Metric
from . import callbacks as callbacks_mod
from .callbacks import (  # noqa: F401
    Callback, ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
    History, MetricsCallback, CheckpointCallback, config_callbacks,
)

__all__ = ["Model", "Input", "Callback", "ProgBarLogger",
           "ModelCheckpoint", "EarlyStopping", "LRScheduler", "History",
           "MetricsCallback", "CheckpointCallback"]


class Input:
    """Shape/dtype spec placeholder (reference hapi Input). Tracing makes
    it optional here; kept for API parity."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = shape
        self.dtype = dtype
        self.name = name


def _to_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _as_batch(batch):
    """DataLoader batches arrive as (inputs..., label) tuples/lists."""
    if isinstance(batch, (list, tuple)):
        if len(batch) == 1:
            return [_to_tensor(batch[0])], []
        return ([_to_tensor(b) for b in batch[:-1]],
                [_to_tensor(batch[-1])])
    return [_to_tensor(batch)], []


class Model:
    """High-level trainer wrapping a ``nn.Layer`` (reference
    model.py:1052)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._jit = True
        self._train_step = None
        self._eval_fwd = None
        self.stop_training = False

    # -- setup --------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, jit=True,
                amp_configs=None):
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss must be callable (a Layer or function)")
        self._loss = loss
        metrics = metrics or []
        for m in metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be paddle.metric.Metric, "
                                f"got {type(m)}")
        self._metrics = metrics
        self._jit = jit
        self._amp = amp_configs or None
        return self

    # -- single-batch ops ---------------------------------------------------
    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            raise RuntimeError("prepare() with a loss before training")
        out_list = outputs if isinstance(outputs, (list, tuple)) \
            else [outputs]
        return self._loss(*out_list, *labels)

    def _make_train_step(self):
        net, opt = self.network, self._optimizer

        def step(*args):
            n_label = self._n_labels
            if n_label:
                inputs, labels = args[:-n_label], args[-n_label:]
            else:
                inputs, labels = args, ()
            if self._amp:
                from .. import amp as amp_pkg
                with amp_pkg.auto_cast(**self._amp):
                    outputs = net(*inputs)
            else:
                outputs = net(*inputs)
            loss = self._compute_loss(outputs, list(labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss, outputs

        if self._jit:
            return jit_mod.to_static(step, state=[net, opt],
                                     name="hapi.train_step")
        return step

    def train_batch(self, inputs, labels=None):
        """One optimizer step; returns {'loss': float, <metric>: value}."""
        if self._train_step is None:
            self._n_labels = len(labels or [])
            self._train_step = self._make_train_step()
        inputs = [_to_tensor(i) for i in (inputs if isinstance(
            inputs, (list, tuple)) else [inputs])]
        labels = [_to_tensor(l) for l in (labels or [])]
        self.network.train()
        loss, outputs = self._train_step(*inputs, *labels)
        logs = {"loss": float(loss)}
        for m in self._metrics:
            _metric_update(m, outputs, labels)
            logs.update(_metric_logs(m))
        return logs

    def eval_batch(self, inputs, labels=None):
        inputs = [_to_tensor(i) for i in (inputs if isinstance(
            inputs, (list, tuple)) else [inputs])]
        labels = [_to_tensor(l) for l in (labels or [])]
        self.network.eval()
        from ..framework.tensor import no_grad
        with no_grad():
            outputs = self.network(*inputs)
        logs = {}
        if self._loss is not None and labels:
            logs["loss"] = float(self._compute_loss(outputs, labels))
        for m in self._metrics:
            _metric_update(m, outputs, labels)
            logs.update(_metric_logs(m))
        return logs

    def predict_batch(self, inputs):
        inputs = [_to_tensor(i) for i in (inputs if isinstance(
            inputs, (list, tuple)) else [inputs])]
        self.network.eval()
        from ..framework.tensor import no_grad
        with no_grad():
            out = self.network(*inputs)
        return out

    # -- loops --------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle, drop_last=False,
                num_workers=0):
        from ..io import DataLoader, Dataset
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None):
        """Reference model.py:1750. Trains with per-epoch eval and
        callback hooks; returns the History callback."""
        loader = self._loader(train_data, batch_size, shuffle,
                              drop_last=drop_last,
                              num_workers=num_workers)
        eval_loader = self._loader(eval_data, batch_size, False,
                                   num_workers=num_workers)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(
            callbacks, self, epochs=epochs, steps=steps,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=_metric_names(self._metrics))
        self.stop_training = False
        cbks.on_train_begin()
        logs = {}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                inputs, labels = _as_batch(batch)
                logs = self.train_batch(inputs, labels)
                cbks.on_train_batch_end(step, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs = dict(logs)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        for c in cbks.callbacks:
            if isinstance(c, History):
                return c
        return None

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._loader(eval_data, batch_size, False,
                              num_workers=num_workers)
        cbks = config_callbacks(callbacks, self, verbose=verbose,
                                metrics=_metric_names(self._metrics))
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs, losses, weights = {}, [], []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            inputs, labels = _as_batch(batch)
            logs = self.eval_batch(inputs, labels)
            if "loss" in logs:
                losses.append(logs["loss"])
                weights.append(inputs[0].shape[0])  # sample-weighted mean
            cbks.on_eval_batch_end(step, logs)
        if losses:
            logs["loss"] = float(np.average(losses, weights=weights))
        for m in self._metrics:
            logs.update(_metric_logs(m))
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=True, verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False,
                              num_workers=num_workers)
        outs = []
        for batch in loader:
            inputs, _ = _as_batch(batch)
            out = self.predict_batch(inputs)
            outs.append(out.numpy() if isinstance(out, Tensor) else out)
        if stack_outputs and outs and isinstance(outs[0], np.ndarray):
            return [np.concatenate(outs, axis=0)]
        return outs

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save
        save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None \
                and getattr(self._optimizer, "state_dict", None):
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load
        state = load(path + ".pdparams")
        self.network.set_state_dict(state)
        import os
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(path + ".pdopt"):
            opt_state = load(path + ".pdopt")
            if getattr(self._optimizer, "set_state_dict", None):
                self._optimizer.set_state_dict(opt_state)

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        n = sum(int(np.prod(p.shape)) for p in self.network.parameters())
        lines = [f"Model: {type(self.network).__name__}",
                 f"Total params: {n:,}"]
        s = "\n".join(lines)
        print(s)
        return {"total_params": n}


def _mname(m):
    n = m.name()
    return n if isinstance(n, str) else n[0]


def _metric_names(metrics):
    out = []
    for m in metrics:
        n = m.name()
        out.extend([n] if isinstance(n, str) else list(n))
    return out


def _metric_update(m, outputs, labels):
    """Feed one batch to a metric. compute() may return a single array or
    a tuple — only a tuple is splatted into update() (star-unpacking a
    bare [B, k] array would feed update one ROW per positional arg)."""
    pred = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
    res = m.compute(pred, *labels)
    if isinstance(res, tuple):
        m.update(*res)
    else:
        m.update(res)


def _metric_logs(m):
    names = m.name()
    vals = m.accumulate()
    if isinstance(names, str):
        return {names: vals}
    return dict(zip(names, vals if isinstance(vals, (list, tuple))
                    else [vals]))

from .model_summary import summary, flops  # noqa: F401,E402
from . import hub  # noqa: F401,E402
