"""``paddle.sparse.nn.functional`` — sparse conv3d / attention.

Reference: `python/paddle/sparse/nn/functional/{conv.py, attention.py}`
with CUDA kernels `phi/kernels/sparse/gpu/conv_kernel.cu` (gather-gemm-
scatter) and `fused_attention_kernel.cu`.

TPU-native design:
- **subm_conv3d** (submanifold: output pattern == input pattern, the
  backbone of sparse 3-D CNNs): the coordinate hash-map the CUDA kernel
  builds on device is HOST bookkeeping here (indices are concrete in
  eager mode); per kernel offset the neighbor pairs become one gather +
  matmul + scatter-add — the gather-gemm-scatter scheme with the gemm
  on the MXU.
- **conv3d** (standard, pattern grows): densify -> `lax.conv` ->
  re-sparsify. On TPU the MXU conv beats gather-scatter for the
  occupancies where a dense intermediate fits; the sparse format is
  kept at the API boundary.
- **attention**: per-query softmax restricted to a sparse [S, S] mask
  pattern via segment ops over the mask's stored coordinates.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...framework.tensor import run_op
from .. import SparseCooTensor

__all__ = ["conv3d", "subm_conv3d", "attention"]


def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v),) * 3


_PLAN_CACHE = {}


def _subm_plan(idx_key, idx_shape, kd, kh, kw, idx):
    """Gather plan per (pattern, kernel): a training loop re-applies the
    same sparsity pattern every step, so the O(nnz * k^3) host-side
    neighbor walk runs once and the (ins, outs) arrays are reused."""
    key = (idx_key, idx_shape, kd, kh, kw)
    plan = _PLAN_CACHE.get(key)
    if plan is not None:
        return plan
    nnz = idx.shape[1]
    site_of = {tuple(idx[:, i]): i for i in range(nnz)}
    gathers = []                              # (offset, in_rows, out_rows)
    for oz in range(kd):
        for oy in range(kh):
            for ox in range(kw):
                dz, dy, dx = oz - kd // 2, oy - kh // 2, ox - kw // 2
                ins, outs = [], []
                for i in range(nnz):
                    n, d, h, w = idx[:, i]
                    j = site_of.get((n, d + dz, h + dy, w + dx))
                    if j is not None:
                        ins.append(j)
                        outs.append(i)
                if ins:
                    gathers.append(((oz, oy, ox),
                                    np.asarray(ins, np.int32),
                                    np.asarray(outs, np.int32)))
    if len(_PLAN_CACHE) > 64:                 # bound host memory
        _PLAN_CACHE.clear()
    _PLAN_CACHE[key] = gathers
    return gathers


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, name=None):
    """Submanifold sparse conv: x SparseCooTensor [N, D, H, W, C]
    (dense channel dim), weight [kd, kh, kw, C_in, C_out]. Output keeps
    x's coordinate pattern (stride must be 1 — the submanifold
    definition)."""
    if _triple(stride) != (1, 1, 1):
        raise ValueError("subm_conv3d requires stride 1")
    idx = np.asarray(x._indices)              # [4, nnz]: n, d, h, w
    nnz = idx.shape[1]
    wshape = weight.shape
    kd, kh, kw = int(wshape[0]), int(wshape[1]), int(wshape[2])
    gathers = _subm_plan(idx.tobytes(), idx.shape, kd, kh, kw, idx)

    def fn(vals, w, b):
        out = jnp.zeros((nnz, w.shape[-1]), vals.dtype)
        for (oz, oy, ox), ins, outs in gathers:
            contrib = vals[ins] @ w[oz, oy, ox]
            out = out.at[outs].add(contrib)
        if b is not None:
            out = out + b
        return out

    args = (x._values, weight) + ((bias,) if bias is not None else ())
    if bias is not None:
        vals = run_op("sparse_subm_conv3d", fn, args)
    else:
        vals = run_op("sparse_subm_conv3d",
                      lambda v, w: fn(v, w, None), args)
    out_shape = tuple(x._mat.shape[:-1]) + (int(wshape[-1]),)
    return SparseCooTensor(x._indices, vals, out_shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, name=None):
    """Standard sparse conv3d (output pattern grows with the receptive
    field): densify, run the MXU conv, re-sparsify the result."""
    st = _triple(stride)
    pd = _triple(padding)
    dense = x.to_dense()                      # [N, D, H, W, C]

    def fn(dense, w, b):
        out = jax.lax.conv_general_dilated(
            dense, w, window_strides=st,
            padding=[(p, p) for p in pd],
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if b is not None:
            out = out + b
        return out

    args = (dense, weight) + ((bias,) if bias is not None else ())
    if bias is not None:
        out = run_op("sparse_conv3d", fn, args)
    else:
        out = run_op("sparse_conv3d", lambda d, w: fn(d, w, None), args)
    # re-sparsify: pattern from the concrete result (eager op, like the
    # reference kernel whose output nnz is data-dependent)
    arr = np.asarray(out._data)
    mask = np.abs(arr).sum(-1) > 0
    coords = np.stack(np.nonzero(mask))       # [4, nnz_out]
    from ...tensor import manipulation  # noqa: F401  (tape gather below)
    rows = out[tuple(jnp.asarray(c) for c in coords)]
    return SparseCooTensor(jnp.asarray(coords), rows, arr.shape)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-pattern attention (reference
    `sparse/nn/functional/attention.py`): q/k/v [B, H, S, D]; the [S, S]
    sparse ``sparse_mask`` names which (query, key) pairs participate;
    softmax is per query row over its stored keys only."""
    if isinstance(sparse_mask, SparseCooTensor):
        rows = np.asarray(sparse_mask._indices)[-2]
        cols = np.asarray(sparse_mask._indices)[-1]
    else:
        indptr = np.asarray(sparse_mask._indptr)
        counts = np.diff(indptr)
        rows = np.repeat(np.arange(len(counts)), counts)
        cols = np.asarray(sparse_mask._cols)
    s_len = int(sparse_mask.shape[-2])
    rows_j = jnp.asarray(rows, jnp.int32)
    cols_j = jnp.asarray(cols, jnp.int32)

    def fn(q, k, v):
        d = q.shape[-1]
        qs = jnp.take(q, rows_j, axis=2)      # [B, H, nnz, D]
        ks = jnp.take(k, cols_j, axis=2)
        scores = jnp.einsum("bhnd,bhnd->bhn", qs, ks) / jnp.sqrt(
            jnp.asarray(d, jnp.float32))
        # segment softmax per query row
        smax = jax.ops.segment_max(jnp.moveaxis(scores, -1, 0), rows_j,
                                   num_segments=s_len)  # [S, B, H]
        smax = jnp.moveaxis(smax, 0, -1)
        p = jnp.exp(scores - jnp.take(smax, rows_j, axis=-1))
        denom = jax.ops.segment_sum(jnp.moveaxis(p, -1, 0), rows_j,
                                    num_segments=s_len)
        denom = jnp.moveaxis(denom, 0, -1)
        p = p / jnp.maximum(jnp.take(denom, rows_j, axis=-1), 1e-20)
        vs = jnp.take(v, cols_j, axis=2)      # [B, H, nnz, D]
        contrib = p[..., None] * vs
        out = jax.ops.segment_sum(jnp.moveaxis(contrib, 2, 0), rows_j,
                                  num_segments=s_len)  # [S, B, H, D]
        return jnp.moveaxis(out, 0, 2)

    return run_op("sparse_attention", fn, (query, key, value))
