"""``paddle.sparse.nn`` — sparse layers (reference `python/paddle/sparse/nn`)."""

from . import functional  # noqa: F401
from .functional import attention  # noqa: F401

from ...framework.tensor import Parameter
import jax
import jax.numpy as jnp

__all__ = ["Conv3D", "SubmConv3D", "ReLU", "functional", "attention"]


class _ConvBase:
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, subm=False):
        from ...framework import random as frandom

        k = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size,) * 3
        self._cfg = dict(stride=stride, padding=padding)
        self._subm = subm
        fan_in = in_channels * int(jnp.prod(jnp.asarray(k)))
        self.weight = Parameter(jax.random.normal(
            frandom.next_key(), tuple(k) + (in_channels, out_channels),
            jnp.float32) * (1.0 / fan_in ** 0.5))
        self.bias = Parameter(jnp.zeros((out_channels,), jnp.float32))

    def parameters(self):
        return [self.weight, self.bias]

    def __call__(self, x):
        fn = functional.subm_conv3d if self._subm else functional.conv3d
        return fn(x, self.weight, self.bias, **self._cfg)


class Conv3D(_ConvBase):
    """Standard sparse conv3d (reference sparse/nn/layer/conv.py)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, subm=False)


class SubmConv3D(_ConvBase):
    """Submanifold sparse conv3d: output pattern == input pattern."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, subm=True)


class ReLU:
    def __call__(self, x):
        from .. import relu
        return relu(x)

    def parameters(self):
        return []
