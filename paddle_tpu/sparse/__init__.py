"""``paddle.sparse`` — COO/CSR sparse tensors.

Reference: `python/paddle/sparse/` (`creation.py` sparse_coo_tensor /
sparse_csr_tensor, unary/binary/matmul ops backed by
`phi/kernels/sparse/`). TPU-native backend: ``jax.experimental.sparse``
BCOO/BCSR — XLA lowers sparse contractions to gather/scatter+MXU
segment ops. Values participate in the autograd tape (gradients flow to
``values()`` and to dense operands of ``matmul``); indices are static.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..framework.tensor import Tensor, run_op

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "matmul", "add", "multiply", "relu", "abs",
           "sin", "tanh", "sqrt", "pow", "neg", "is_same_shape",
           "masked_matmul", "nn"]


def _values_in(x):
    return x._values


class _SparseBase:
    def __init__(self, mat, values_tensor):
        self._mat = mat              # BCOO/BCSR with values_tensor._data
        self._values = values_tensor  # tape-tracked values

    @property
    def shape(self):
        return list(self._mat.shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def nnz(self):
        return int(self._mat.nse)

    def values(self):
        return self._values

    def to_dense(self):
        def fn(v):
            return self._with_values(v).todense()

        return run_op("sparse_to_dense", fn, (self._values,))

    def _with_values(self, v):
        raise NotImplementedError

    def _rebuild(self):
        return self._with_values(self._values._data)

    def __repr__(self):
        return (f"{type(self).__name__}(shape={self.shape}, "
                f"nnz={self.nnz}, dtype={self.dtype})")


class SparseCooTensor(_SparseBase):
    def __init__(self, indices, values_tensor, shape):
        self._indices = jnp.asarray(indices)
        mat = jsparse.BCOO((values_tensor._data, self._indices.T),
                           shape=tuple(shape))
        super().__init__(mat, values_tensor)

    def indices(self):
        # paddle layout: [sparse_dim, nnz] (what sparse_coo_tensor takes)
        return Tensor(self._indices, stop_gradient=True)

    def _with_values(self, v):
        return jsparse.BCOO((v, self._indices.T), shape=self._mat.shape)

    def coalesce(self):
        m = self._rebuild().sum_duplicates()
        vals = Tensor(m.data, stop_gradient=self._values.stop_gradient)
        return SparseCooTensor(m.indices.T, vals, m.shape)

    def to_sparse_csr(self):
        m = jsparse.BCSR.from_bcoo(self._rebuild().sum_duplicates())
        vals = Tensor(m.data, stop_gradient=self._values.stop_gradient)
        return SparseCsrTensor._wrap(m, vals)


class SparseCsrTensor(_SparseBase):
    def __init__(self, crows, cols, values_tensor, shape):
        self._indptr = jnp.asarray(crows, jnp.int32)
        self._cols = jnp.asarray(cols, jnp.int32)
        mat = jsparse.BCSR((values_tensor._data, self._cols, self._indptr),
                           shape=tuple(shape))
        super().__init__(mat, values_tensor)

    @classmethod
    def _wrap(cls, m, vals):
        obj = cls.__new__(cls)
        obj._indptr = m.indptr
        obj._cols = m.indices
        _SparseBase.__init__(obj, m, vals)
        return obj

    def crows(self):
        return Tensor(self._indptr, stop_gradient=True)

    def cols(self):
        return Tensor(self._cols, stop_gradient=True)

    def _with_values(self, v):
        return jsparse.BCSR((v, self._cols, self._indptr),
                            shape=self._mat.shape)

    def to_sparse_coo(self, sparse_dim=None):
        m = self._rebuild().to_bcoo()
        vals = Tensor(m.data, stop_gradient=self._values.stop_gradient)
        return SparseCooTensor(m.indices.T, vals, m.shape)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """Reference creation.py sparse_coo_tensor: indices [ndim, nnz]."""
    idx = np.asarray(indices)
    vals = values if isinstance(values, Tensor) \
        else Tensor(np.asarray(values), dtype=dtype,
                    stop_gradient=stop_gradient)
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    vals = values if isinstance(values, Tensor) \
        else Tensor(np.asarray(values), dtype=dtype,
                    stop_gradient=stop_gradient)
    return SparseCsrTensor(crows, cols, vals, shape)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def matmul(x, y, name=None):
    """sparse @ dense (reference sparse/matmul.py). Grads flow to the
    sparse values and the dense operand."""
    if isinstance(y, _SparseBase):
        raise NotImplementedError("sparse @ sparse: densify one side")
    rebuild = x._with_values

    def fn(v, d):
        return rebuild(v) @ d

    return run_op("sparse_matmul", fn, (x._values, y))


def add(x, y, name=None):
    """coo + coo -> coo (concatenated coordinates, duplicates implicit —
    ``to_dense`` sums them, like an uncoalesced reference tensor);
    sparse + dense -> dense."""
    if isinstance(y, _SparseBase):
        if not (isinstance(x, SparseCooTensor)
                and isinstance(y, SparseCooTensor)):
            raise NotImplementedError(
                "sparse add of CSR tensors: convert with to_sparse_coo()")
        if list(x.shape) != list(y.shape):
            raise ValueError(f"shape mismatch: {x.shape} vs {y.shape}")
        vals = run_op("sparse_add_values",
                      lambda a, b: jnp.concatenate([a, b]),
                      (x._values, y._values))
        idx = np.concatenate([np.asarray(x._indices),
                              np.asarray(y._indices)], axis=1)
        return SparseCooTensor(idx, vals, x._mat.shape)
    return run_op("sparse_add_dense",
                  lambda v, d: x._with_values(v).todense() + d,
                  (x._values, y))


def multiply(x, y, name=None):
    """elementwise sparse * dense — keeps sparsity: each stored value is
    scaled by the dense entry at its own coordinates."""
    if isinstance(x, SparseCooTensor):
        idx = tuple(np.asarray(x._indices))          # [ndim, nnz] static
    else:
        indptr = np.asarray(x._indptr)
        counts = np.diff(indptr)
        rows = np.repeat(np.arange(len(counts)), counts)
        idx = (rows, np.asarray(x._cols))

    def fn(v, d):
        return v * d[idx]

    return _rewrap(x, run_op("sparse_multiply", fn, (x._values, y)))


def _unary(name, jfn):
    def op(x):
        return _rewrap(x, run_op(f"sparse_{name}", jfn, (x._values,)))
    op.__name__ = name
    return op


def _rewrap(x, vals):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x._indices, vals, x._mat.shape)
    return SparseCsrTensor._wrap(x._with_values(vals._data), vals)


relu = _unary("relu", lambda v: jnp.maximum(v, 0))
abs = _unary("abs", jnp.abs)
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
neg = _unary("neg", jnp.negative)


def pow(x, factor):
    vals = run_op("sparse_pow", lambda v: jnp.power(v, factor),
                  (x._values,))
    return _rewrap(x, vals)


def masked_matmul(x, y, mask, name=None):
    """SDDMM (reference `sparse/matmul.py:masked_matmul`,
    `phi/kernels/sparse/gpu/matmul_kernel.cu`): dense @ dense evaluated
    ONLY at ``mask``'s stored coordinates; returns a sparse tensor with
    mask's pattern. Grads flow to both dense operands."""
    if isinstance(mask, SparseCooTensor):
        rows, cols = (np.asarray(mask._indices)[-2],
                      np.asarray(mask._indices)[-1])
    else:
        indptr = np.asarray(mask._indptr)
        counts = np.diff(indptr)
        rows = np.repeat(np.arange(len(counts)), counts)
        cols = np.asarray(mask._cols)

    def fn(a, b):
        # value n = a[.., rows[n], :] . b[.., :, cols[n]]
        ar = jnp.take(a, jnp.asarray(rows), axis=-2)
        bc = jnp.take(b, jnp.asarray(cols), axis=-1)
        return jnp.einsum("...nd,...dn->...n", ar, bc)

    vals = run_op("sparse_masked_matmul", fn, (x, y))
    return _rewrap(mask, vals)


from . import nn  # noqa: E402,F401
