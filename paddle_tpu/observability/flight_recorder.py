"""Crash flight recorder: a bounded ring of recent telemetry + a
post-mortem bundle dumped on fatal errors.

A rank that dies under the elastic watchdog, an OOM mid-step, or a NaN
blow-up in amp leaves nothing behind today but a stack trace. The
flight recorder keeps the last few minutes of cheap telemetry — host
spans (the PR-1 trace ring), XLA compile events (the compile watcher's
ring), and periodic metric snapshots — and on a fatal signal writes a
post-mortem bundle under ``<log_dir>/postmortem/<run>/``:

- ``trace.json`` — chrome trace (spans + compile events); loads in
  Perfetto / ``chrome://tracing``.
- ``metrics.json`` — strict-JSON registry snapshot plus the ring of
  periodic snapshots (round-trips through ``json.loads``).
- ``compile_log.txt`` — one line per recent XLA compile.
- ``env.json`` — environment/config: PADDLE*/JAX*/XLA* env vars, jax
  version + devices, argv, pid.
- ``error.txt`` — the traceback, when an exception triggered the dump.

:func:`install` hooks ``sys.excepthook``; the distributed watchdog's
timeout path, ``amp.debugging.check_numerics`` hits, and the serving
engine / elastic launcher's fatal paths call :func:`on_fatal`. The
module-level :func:`dump` is the manual trigger. All of it obeys the
PR-1 kill switch: under ``PADDLE_TPU_METRICS=0`` ``install()`` is a
no-op and no files are ever written.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from collections import deque

from . import trace as otrace
from .export import _json_value, json_snapshot
from .metrics import default_registry, enabled

__all__ = ["FlightRecorder", "install", "uninstall", "installed", "dump",
           "on_fatal", "periodic_snapshot"]

#: dump ceiling per process — repeated NaN hits must not fill the disk
MAX_DUMPS = 8

#: minimum seconds between exception-less dumps from the SAME origin
#: (a NaN storm across ops in one bad step must not burn the whole
#: MAX_DUMPS budget before a genuinely distinct fatal gets its bundle)
ORIGIN_DUMP_INTERVAL = 30.0

_installed: "FlightRecorder | None" = None
_install_lock = threading.Lock()
_last_origin_dump: dict = {}


def _json_safe(obj):
    """Recursively make ``obj`` strict-JSON serializable: non-finite
    floats become their Prometheus markers (a NaN span arg — the very
    blow-up the recorder exists for — must not make trace.json
    unloadable) and unknown types stringify instead of aborting the
    dump."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        # one marker convention for the whole package: the exporter's
        # "+Inf"/"-Inf"/"NaN" rendering (export._json_value)
        return _json_value(obj)
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return str(obj)


class FlightRecorder:
    """Bounded telemetry ring + post-mortem dumper for one process."""

    def __init__(self, log_dir="./paddle_tpu_log", snapshot_interval=15.0,
                 snapshot_capacity=32, registry=None, trace_buffer=None):
        self.log_dir = str(log_dir)
        self.snapshot_interval = float(snapshot_interval)
        self._registry = registry
        self._trace_buffer = trace_buffer
        self._snapshots: deque = deque(maxlen=int(snapshot_capacity))
        self._last_snapshot = 0.0
        self._snap_lock = threading.Lock()
        self._dump_lock = threading.Lock()
        self._dumps = 0
        self._prev_excepthook = None
        self._hooked = False

    # -- periodic telemetry ---------------------------------------------
    def note_snapshot(self, force=False):
        """Append a metrics snapshot to the ring, rate-limited to one per
        ``snapshot_interval`` seconds (cheap enough for per-step call
        sites). No-op under ``PADDLE_TPU_METRICS=0``."""
        if not enabled():
            return False
        now = time.monotonic()
        with self._snap_lock:
            if not force and now - self._last_snapshot \
                    < self.snapshot_interval:
                return False
            self._last_snapshot = now
        reg = self._registry if self._registry is not None \
            else default_registry()
        entry = {"unix_time": time.time(), "snapshot": json_snapshot(reg)}
        # append under the lock: a crash dump snapshots the ring with
        # list() from another thread (watchdog/excepthook), and a
        # concurrent unlocked append would raise mid-iteration and cost
        # the bundle its metrics.json
        with self._snap_lock:
            self._snapshots.append(entry)
        return True

    # -- hooks ----------------------------------------------------------
    def install(self):
        """Hook ``sys.excepthook`` (chains to the previous hook) and
        register as the process's active recorder."""
        global _installed
        if not self._hooked:
            self._hooked = True
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._excepthook
        _installed = self
        return self

    def uninstall(self):
        global _installed
        if self._hooked:
            self._hooked = False
            # only unhook if nobody hooked after us
            if sys.excepthook is self._excepthook:
                sys.excepthook = self._prev_excepthook \
                    or sys.__excepthook__
        if _installed is self:
            _installed = None

    def _excepthook(self, exc_type, exc, tb):
        # _hooked check: when another library layered its hook over ours
        # and uninstall() therefore couldn't unhook, we stay in its
        # chain — chain through, but an uninstalled recorder must not
        # keep writing bundles
        if self._hooked \
                and not issubclass(exc_type,
                                   (KeyboardInterrupt, SystemExit)) \
                and not getattr(exc, "_paddle_tpu_fr_dumped", False):
            try:
                self.dump(reason="excepthook", exc=(exc_type, exc, tb))
            except Exception:
                pass            # the original error must still surface
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    # -- the bundle -----------------------------------------------------
    def dump(self, reason="manual", exc=None, info=None):
        """Write one post-mortem bundle; returns its directory, or None
        when disabled / over the per-process dump ceiling."""
        if not enabled():
            return None
        with self._dump_lock:
            if self._dumps >= MAX_DUMPS:
                return None
            self._dumps += 1
            out_dir = os.path.join(self.log_dir, "postmortem",
                                   otrace.unique_run_name())
            os.makedirs(out_dir, exist_ok=True)
            # each artifact independently: one bad writer must not cost
            # the rest of the bundle (the budget is already spent)
            for write in (self._write_trace, self._write_metrics,
                          self._write_compile_log,
                          lambda d: self._write_env(d, reason, info)):
                try:
                    write(out_dir)
                except Exception:
                    pass
            if exc is not None:
                try:
                    self._write_error(out_dir, exc)
                except Exception:
                    pass
            return out_dir

    def _write_trace(self, out_dir):
        from . import compile_watch

        buf = self._trace_buffer if self._trace_buffer is not None \
            else otrace.default_buffer()
        events = buf.events()
        for ev in compile_watch.recent_compile_events():
            events.append({
                "name": f"xla_compile:{ev.get('name', '?')}",
                "cat": "xla_compile",
                "ph": "X",
                "ts": ev.get("ts", 0.0),
                "dur": ev.get("dur", 0.0),
                "pid": os.getpid(),
                "tid": 0,
                "args": {k: v for k, v in ev.items()
                         if k not in ("ts", "dur", "name")},
            })
        events.sort(key=lambda e: e.get("ts", 0.0))
        with open(os.path.join(out_dir, "trace.json"), "w") as f:
            json.dump(_json_safe({"traceEvents": events,
                                  "displayTimeUnit": "ms"}), f,
                      allow_nan=False)

    def _write_metrics(self, out_dir):
        reg = self._registry if self._registry is not None \
            else default_registry()
        with self._snap_lock:
            history = list(self._snapshots)
        doc = {"snapshot": json_snapshot(reg), "history": history}
        with open(os.path.join(out_dir, "metrics.json"), "w") as f:
            # allow_nan=False proves the strict-JSON guarantee at write
            # time instead of at the consumer
            json.dump(doc, f, allow_nan=False)

    def _write_compile_log(self, out_dir):
        from . import compile_watch

        lines = []
        for ev in compile_watch.recent_compile_events():
            parts = [f"{ev.get('kind', 'compile')}",
                     f"name={ev.get('name', '?')}",
                     f"dur_ms={ev.get('dur', 0.0) / 1e3:.1f}"]
            for k in ("flops", "bytes_accessed", "peak_temp_bytes",
                      "signature"):
                if k in ev:
                    parts.append(f"{k}={ev[k]}")
            lines.append("  ".join(str(p) for p in parts))
        with open(os.path.join(out_dir, "compile_log.txt"), "w") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))

    def _write_env(self, out_dir, reason, info):
        doc = {
            "reason": reason,
            "unix_time": time.time(),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "python": sys.version.split()[0],
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith(("PADDLE", "JAX", "XLA", "TPU",
                                     "LIBTPU", "FLAGS_"))},
        }
        if info:
            doc["info"] = info
        try:
            import jax
            doc["jax_version"] = jax.__version__
            doc["backend"] = jax.default_backend()
            doc["devices"] = [str(d) for d in jax.devices()]
        except Exception:
            pass
        with open(os.path.join(out_dir, "env.json"), "w") as f:
            # _json_safe: on_fatal(**info) may carry the very NaN the
            # dump is about — a bare NaN token would break the strict-
            # JSON guarantee on exactly the bundle it matters for
            json.dump(_json_safe(doc), f, indent=2, sort_keys=True)

    @staticmethod
    def _write_error(out_dir, exc):
        if isinstance(exc, BaseException):
            exc = (type(exc), exc, exc.__traceback__)
        with open(os.path.join(out_dir, "error.txt"), "w") as f:
            f.write("".join(traceback.format_exception(*exc)))


# ---------------------------------------------------------------------------
# module-level lifecycle — what the serving engine / launcher / watchdog
# and amp call without holding a recorder reference
# ---------------------------------------------------------------------------
def install(log_dir="./paddle_tpu_log", **kwargs):
    """Create + install the process flight recorder. Returns it, or None
    under ``PADDLE_TPU_METRICS=0`` (nothing hooked, no files ever).
    Installing again re-points the existing recorder's ``log_dir`` (and
    any other passed settings) rather than silently keeping the old
    destination."""
    if not enabled():
        return None
    with _install_lock:
        rec = _installed
        if rec is not None:
            rec.log_dir = str(log_dir)
            for key, value in kwargs.items():
                if key == "snapshot_interval":
                    rec.snapshot_interval = float(value)
                elif key == "snapshot_capacity":
                    rec._snapshots = deque(rec._snapshots,
                                           maxlen=int(value))
                elif key == "registry":
                    rec._registry = value
                elif key == "trace_buffer":
                    rec._trace_buffer = value
                else:
                    raise TypeError(
                        f"install() got an unexpected keyword {key!r}")
            return rec
        return FlightRecorder(log_dir, **kwargs).install()


def uninstall():
    rec = _installed
    if rec is not None:
        rec.uninstall()
    _last_origin_dump.clear()


def installed():
    """The active recorder, or None."""
    return _installed


def dump(reason="manual", exc=None, info=None):
    """Dump a post-mortem bundle through the installed recorder (None
    when none is installed or metrics are disabled)."""
    rec = _installed
    if rec is None or not enabled():
        return None
    return rec.dump(reason=reason, exc=exc, info=info)


def on_fatal(origin, exc=None, **info):
    """Fatal-path hook for the serving engine, elastic launcher,
    watchdog timeouts, and amp numerics hits: dumps when a recorder is
    installed, never raises, never blocks the caller's own error. An
    exception is dumped once, however many nested fatal paths (and
    finally the excepthook) see it on the way out."""
    rec = _installed
    if rec is None or not enabled():
        return None
    if exc is not None and getattr(exc, "_paddle_tpu_fr_dumped", False):
        return None
    # rate-limit per origin — with or without an exception object: a
    # storm of same-origin hits (NaNs on every op of one bad step, a
    # too-large prompt rejected with a FRESH MemoryError per request)
    # must not exhaust the MAX_DUMPS budget before a genuinely distinct
    # fatal gets its bundle
    now = time.monotonic()
    if now - _last_origin_dump.get(origin, -ORIGIN_DUMP_INTERVAL) \
            < ORIGIN_DUMP_INTERVAL:
        # skipped, NOT marked dumped: if this exception still kills the
        # process, the excepthook bundle (a different origin) proceeds
        return None
    _last_origin_dump[origin] = now
    try:
        out = rec.dump(reason=origin, exc=exc, info=info or None)
    except Exception:
        return None
    if exc is not None:
        try:
            exc._paddle_tpu_fr_dumped = True
        except Exception:
            pass
    return out


def periodic_snapshot(force=False):
    """Rate-limited metric snapshot into the installed recorder's ring
    (call sites: hapi step, serving wave). No-op when uninstalled."""
    rec = _installed
    if rec is None:
        return False
    return rec.note_snapshot(force=force)
