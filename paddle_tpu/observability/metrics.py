"""Thread-safe runtime metrics: Counter / Gauge / Histogram + registry.

Reference capability: the serving/trainer metric surfaces of production
TPU stacks (TTFT/TPOT histograms, KV-page utilization gauges, per-step
MFU — see ISSUE/PAPERS: "Ragged Paged Attention", arXiv:2604.15464).
The reference framework itself exposes no runtime counters; this module
is the measurement substrate every perf PR reports against.

Design:

- :class:`Counter` (monotonic), :class:`Gauge` (set/inc/dec or callback
  via ``set_function``), :class:`Histogram` (fixed upper-bound buckets,
  mergeable across processes/registries) — all guarded by a per-metric
  lock, all supporting labeled children (``m.labels("GET")``).
- :class:`MetricsRegistry` — name -> metric map with idempotent
  get-or-create factories; a process-global default registry behind
  :func:`default_registry` plus module-level :func:`counter` /
  :func:`gauge` / :func:`histogram` helpers.
- Zero-cost no-op mode: with ``PADDLE_TPU_METRICS=0`` in the environment
  every factory returns the shared :data:`NULL` metric whose methods do
  nothing, and the registry records nothing — instrumented hot paths pay
  one no-op method call and produce byte-identical outputs.
"""

from __future__ import annotations

import bisect
import math
import os
import threading

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "NULL",
    "DEFAULT_BUCKETS", "default_registry", "counter", "gauge", "histogram",
    "enabled",
]


def enabled():
    """Metrics are on unless ``PADDLE_TPU_METRICS=0`` (checked per
    factory call so tests can toggle the environment)."""
    return os.environ.get("PADDLE_TPU_METRICS", "1") != "0"


class _NullMetric:
    """Shared do-nothing metric returned by every factory in no-op mode;
    also its own ``labels`` child so call chains stay valid."""

    __slots__ = ()

    def inc(self, n=1):
        pass

    def dec(self, n=1):
        pass

    def set(self, value):
        pass

    def set_function(self, fn):
        pass

    def observe(self, value):
        pass

    def merge(self, other):
        pass

    def snapshot(self):
        return [], 0.0

    def labels(self, *values, **labelkw):
        return self

    def remove(self, *values):
        pass

    @property
    def value(self):
        return 0.0

    @property
    def count(self):
        return 0

    @property
    def sum(self):
        return 0.0


NULL = _NullMetric()


class _Metric:
    """Base: name/help/labels plumbing. A labelless metric carries its
    own value; a labeled one only owns children keyed by label values."""

    kind = "untyped"

    def __init__(self, name, help="", labelnames=()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple, _Metric] = {}
        self._lock = threading.Lock()

    def _new_child(self):
        return type(self)(self.name, self.help)

    def labels(self, *values, **labelkw):
        """Child metric for one label-value combination (created on
        first use). Accepts positional values or keyword form."""
        if labelkw:
            if values:
                raise ValueError("pass label values positionally OR by "
                                 "keyword, not both")
            try:
                values = tuple(labelkw[k] for k in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e.args[0]!r} for "
                                 f"{self.name}") from None
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got "
                f"{len(values)} value(s)")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._new_child()
                self._children[values] = child
            return child

    def peek(self, *values):
        """Child metric for one label-value combination, or None when it
        was never created — a read that, unlike :meth:`labels`, never
        mints an empty child into the export."""
        values = tuple(str(v) for v in values)
        with self._lock:
            return self._children.get(values)

    def remove(self, *values):
        """Drop the child for one label-value combination (no-op when
        absent) — lets short-lived instruments bound label cardinality
        and stop exporting stale samples."""
        values = tuple(str(v) for v in values)
        with self._lock:
            self._children.pop(values, None)

    def _check_unlabeled(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call "
                f".labels(...) first")

    def samples(self):
        """[(label_values, leaf_metric)] — () -> self when unlabeled."""
        if self.labelnames:
            with self._lock:
                return sorted(self._children.items())
        return [((), self)]


class Counter(_Metric):
    """Monotonically increasing count."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._value = 0.0

    def inc(self, n=1):
        self._check_unlabeled()
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge(_Metric):
    """Instantaneous value; settable or backed by a callback."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._value = 0.0
        self._fn = None

    def set(self, value):
        self._check_unlabeled()
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, n=1):
        self._check_unlabeled()
        with self._lock:
            self._value += n

    def dec(self, n=1):
        self._check_unlabeled()
        with self._lock:
            self._value -= n

    def set_function(self, fn):
        """Read the gauge from ``fn()`` at collection time (e.g. pool
        utilization derived from an allocator)."""
        self._check_unlabeled()
        with self._lock:
            self._fn = fn

    @property
    def value(self):
        fn = self._fn
        return float(fn()) if fn is not None else self._value


#: Prometheus' classic latency buckets (seconds).
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)


def _normalize_buckets(buckets):
    """Sorted finite upper bounds. Explicit +/-Inf bounds are dropped:
    the +Inf bucket is implicit, and non-finite bounds would break the
    JSON snapshot (json.dumps emits non-standard Infinity) and the text
    exporter."""
    return tuple(sorted(float(b) for b in buckets if math.isfinite(b)))


class Histogram(_Metric):
    """Fixed-bucket histogram: ``buckets`` are inclusive upper bounds;
    an implicit +Inf bucket catches the tail. Mergeable: two histograms
    with identical buckets add elementwise (cross-process aggregation)."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = _normalize_buckets(buckets)
        if not self.buckets:
            raise ValueError("histogram needs at least one finite bucket")
        self._counts = [0] * (len(self.buckets) + 1)  # + the +Inf bucket
        self._sum = 0.0

    def _new_child(self):
        return Histogram(self.name, self.help, buckets=self.buckets)

    def observe(self, value):
        self._check_unlabeled()
        value = float(value)
        if math.isnan(value):
            # bisect_left(NaN) returns 0 (all comparisons false), which
            # would misclassify it as <= the smallest bound; +Inf is the
            # only bucket a NaN observation can honestly land in
            i = len(self.buckets)
        else:
            i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value

    def merge(self, other):
        """Add another histogram's observations into this one."""
        if tuple(other.buckets) != self.buckets:
            raise ValueError("cannot merge histograms with different "
                             "buckets")
        counts, total = other.snapshot()
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += total
        return self

    def snapshot(self):
        """``(raw_counts, sum)`` captured atomically — an exporter that
        read them as separate unlocked properties could race observe()
        and emit count != cumulative +Inf (invalid Prometheus output)."""
        with self._lock:
            return list(self._counts), self._sum

    @property
    def raw_counts(self):
        """Per-bucket (non-cumulative) counts, last entry = +Inf."""
        return list(self._counts)

    def cumulative_counts(self):
        """Prometheus-style cumulative ``le`` counts incl. +Inf."""
        out, acc = [], 0
        for c in self._counts:
            acc += c
            out.append(acc)
        return out

    @property
    def count(self):
        return sum(self._counts)

    @property
    def sum(self):
        return self._sum


class MetricsRegistry:
    """Name -> metric map. Factories are get-or-create and idempotent;
    re-registering a name as a different kind, with different labels,
    or with different buckets is an error (a silent return of the first
    registration would discard the caller's spec)."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.RLock()

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        if not enabled():
            return NULL
        labelnames = tuple(labelnames)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, labelnames, **kw)
                self._metrics[name] = m
            elif type(m) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}")
            elif m.labelnames != labelnames:
                raise ValueError(
                    f"metric {name!r} already registered with labels "
                    f"{m.labelnames}, not {labelnames}")
            elif cls is Histogram:
                want = _normalize_buckets(kw.get("buckets",
                                                 DEFAULT_BUCKETS))
                if want != m.buckets:
                    raise ValueError(
                        f"metric {name!r} already registered with "
                        f"buckets {m.buckets}, not {want}")
            return m

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name):
        with self._lock:
            return self._metrics.pop(name, None)

    def clear(self):
        with self._lock:
            self._metrics.clear()

    def collect(self):
        """Registered metrics sorted by name (a stable snapshot list)."""
        with self._lock:
            return [m for _, m in sorted(self._metrics.items())]


_default = MetricsRegistry()


def default_registry():
    """The process-global registry all built-in instrumentation uses."""
    return _default


def counter(name, help="", labelnames=()):
    return _default.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    return _default.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=DEFAULT_BUCKETS):
    return _default.histogram(name, help, labelnames, buckets=buckets)
