"""Exporters: Prometheus text format, JSON snapshots, HTTP serving.

- :func:`json_snapshot` — a pure-data (JSON-serializable) dump of a
  registry; :func:`snapshot_to_prometheus` renders such a snapshot to
  Prometheus text, and :func:`prometheus_text` composes the two — so
  text output round-trips exactly through the JSON snapshot layer
  (serialize, ship, re-render identically on another host).
- :class:`HttpService` — the ONE stdlib ``http.server`` wrapper every
  in-process endpoint builds on (the metrics scrape port, the replica
  worker's health port, the cluster's tier endpoint, and the
  OpenAI-compatible serving frontend): a route table over a threaded
  daemon server, request context helpers (JSON bodies/replies, SSE
  streaming with typed client-disconnect), ``.port`` / ``.url`` /
  ``.stop``.
- :func:`add_probe_routes` — installs the standard observability
  routes (``/metrics`` text + HEAD, ``/metrics.json`` snapshot,
  ``/healthz`` liveness with ``health_info`` merge, ``/readyz``
  readiness that turns 503 while the local engine drains) on any
  :class:`HttpService`.
- :func:`start_http_server` — the classic scrape endpoint: an
  :class:`HttpService` with just the probe routes.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time

from .metrics import default_registry

__all__ = ["json_snapshot", "snapshot_to_prometheus", "prometheus_text",
           "start_http_server", "ScrapeServer", "HttpService",
           "HttpContext", "ClientDisconnected", "add_probe_routes",
           "merge_snapshots", "aggregate_snapshot"]


def _fmt_value(v):
    if isinstance(v, str):
        return v    # non-finite marker straight from a JSON snapshot
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _json_value(v):
    """Float for the snapshot, except non-finite values become their
    Prometheus markers ("+Inf"/"-Inf"/"NaN"): json.dumps would emit bare
    Infinity/NaN — invalid JSON that strict parsers (JSON.parse, jq, Go)
    reject, breaking the documented cross-host snapshot round-trip."""
    v = float(v)
    if not math.isfinite(v):
        return _fmt_value(v)
    return v


def _escape(v):
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def _label_str(labelnames, values, extra=()):
    pairs = list(zip(labelnames, values)) + list(extra)
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in pairs) + "}"


def json_snapshot(registry=None):
    """List of metric dicts (name/help/type/labelnames/samples) holding
    only JSON-native values — ``json.dumps`` round-trips it losslessly."""
    reg = registry if registry is not None else default_registry()
    out = []
    for m in reg.collect():
        entry = {"name": m.name, "help": m.help, "type": m.kind,
                 "labelnames": list(m.labelnames), "samples": []}
        for values, leaf in m.samples():
            sample = {"labels": list(values)}
            if m.kind == "histogram":
                counts, total = leaf.snapshot()
                sample.update(buckets=list(leaf.buckets),
                              counts=counts,
                              sum=_json_value(total),
                              count=int(sum(counts)))
            else:
                sample["value"] = _json_value(leaf.value)
            entry["samples"].append(sample)
        out.append(entry)
    return out


def snapshot_to_prometheus(snapshot):
    """Render a :func:`json_snapshot` (or its JSON round-trip) to
    Prometheus exposition text (version 0.0.4)."""
    lines = []
    for entry in snapshot:
        name, kind = entry["name"], entry["type"]
        labelnames = entry.get("labelnames", [])
        if entry.get("help"):
            lines.append(f"# HELP {name} {_escape(entry['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        for sample in entry["samples"]:
            values = sample.get("labels", [])
            if kind == "histogram":
                acc = 0
                bounds = list(sample["buckets"]) + ["+Inf"]
                for bound, c in zip(bounds, sample["counts"]):
                    acc += c
                    le = "+Inf" if bound == "+Inf" else _fmt_value(bound)
                    ls = _label_str(labelnames, values, [("le", le)])
                    lines.append(f"{name}_bucket{ls} {acc}")
                ls = _label_str(labelnames, values)
                lines.append(f"{name}_sum{ls} {_fmt_value(sample['sum'])}")
                lines.append(f"{name}_count{ls} {sample['count']}")
            else:
                ls = _label_str(labelnames, values)
                lines.append(f"{name}{ls} {_fmt_value(sample['value'])}")
    return "\n".join(lines) + "\n" if lines else ""


def prometheus_text(registry=None):
    """Prometheus text for a registry (the scrape-endpoint body)."""
    return snapshot_to_prometheus(json_snapshot(registry))


def merge_snapshots(sources, label="replica"):
    """Merge per-process :func:`json_snapshot` lists into ONE snapshot
    with ``label`` prepended to every metric's labelnames — the
    one-pane cluster view: ``sources`` is an iterable of ``(label_value,
    snapshot)`` pairs and every sample keeps its original labels behind
    the new ``label`` value. A source whose entry disagrees with the
    first-seen schema for a name (different type or labelnames — a
    version-skewed replica) is skipped for that metric rather than
    corrupting the pane."""
    merged, order = {}, []
    for src_value, snapshot in sources:
        for entry in snapshot or ():
            name = entry["name"]
            names = list(entry.get("labelnames", []))
            cur = merged.get(name)
            if cur is None:
                cur = {"name": name, "help": entry.get("help", ""),
                       "type": entry["type"],
                       "labelnames": [label] + names, "samples": []}
                merged[name] = cur
                order.append(name)
            elif (cur["type"] != entry["type"]
                  or cur["labelnames"][1:] != names):
                continue
            for sample in entry.get("samples", ()):
                s = dict(sample)
                s["labels"] = ([str(src_value)]
                               + list(sample.get("labels", [])))
                cur["samples"].append(s)
    return [merged[n] for n in order]


def _add_json(a, b):
    # float() accepts the "+Inf"/"-Inf"/"NaN" snapshot markers
    return _json_value(float(a) + float(b))


def aggregate_snapshot(snapshot, drop_label="replica"):
    """Collapse ``drop_label`` out of a merged snapshot: samples that
    agree on every remaining label combine exactly — counters/gauges
    sum, histograms merge element-wise (a sample whose bucket bounds
    disagree with the first-seen bounds is skipped). Entries without
    ``drop_label`` pass through unchanged. The inverse of
    :func:`merge_snapshots` up to summation — what the SLO engine and
    tier-level dashboards consume."""
    out = []
    for entry in snapshot:
        labelnames = list(entry.get("labelnames", []))
        if drop_label not in labelnames:
            out.append(entry)
            continue
        i = labelnames.index(drop_label)
        agg, order = {}, []
        for sample in entry.get("samples", ()):
            labels = list(sample.get("labels", []))
            key = tuple(labels[:i] + labels[i + 1:])
            cur = agg.get(key)
            if entry["type"] == "histogram":
                if cur is None:
                    agg[key] = {"labels": list(key),
                                "buckets": list(sample["buckets"]),
                                "counts": list(sample["counts"]),
                                "sum": sample["sum"],
                                "count": int(sample["count"])}
                    order.append(key)
                elif list(sample["buckets"]) == cur["buckets"]:
                    cur["counts"] = [a + b for a, b in
                                     zip(cur["counts"], sample["counts"])]
                    cur["sum"] = _add_json(cur["sum"], sample["sum"])
                    cur["count"] += int(sample["count"])
            else:
                if cur is None:
                    agg[key] = {"labels": list(key),
                                "value": sample["value"]}
                    order.append(key)
                else:
                    cur["value"] = _add_json(cur["value"],
                                             sample["value"])
        out.append({"name": entry["name"], "help": entry.get("help", ""),
                    "type": entry["type"],
                    "labelnames": (labelnames[:i]
                                   + labelnames[i + 1:]),
                    "samples": [agg[k] for k in order]})
    return out


class ClientDisconnected(ConnectionError):
    """The HTTP client went away mid-response (broken pipe / reset) —
    the typed signal a streaming handler uses to cancel server-side
    work (the frontend maps it to a 499 tally + ``engine.cancel``)."""


class HttpContext:
    """Per-request view handed to :class:`HttpService` route handlers:
    request line/headers/body access plus reply helpers. A handler
    either calls ``send``/``send_json`` once, or ``stream(...)`` and
    writes chunks; returning without replying is a 500."""

    def __init__(self, handler, head_only=False):
        self._h = handler
        self._head_only = head_only
        self.method = "HEAD" if head_only else handler.command
        self.path = handler.path.split("?", 1)[0]
        self.query = handler.path.partition("?")[2]
        self.headers = handler.headers
        self.replied = False

    def body(self):
        n = int(self.headers.get("Content-Length") or 0)
        return self._h.rfile.read(n) if n else b""

    def json(self):
        """Parsed JSON body; raises ValueError on malformed input (the
        service maps it to a 400)."""
        raw = self.body()
        if not raw:
            raise ValueError("empty request body (expected JSON)")
        try:
            return json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(f"malformed JSON body: {e}") from None

    def send(self, status, body, ctype="application/json",
             headers=None):
        self.replied = True
        h = self._h
        h.send_response(int(status))
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            h.send_header(k, str(v))
        h.end_headers()
        if not self._head_only:
            h.wfile.write(body)

    def send_json(self, status, obj, headers=None):
        self.send(status, json.dumps(obj).encode(), "application/json",
                  headers)

    def stream(self, status=200, ctype="text/event-stream",
               headers=None):
        """Open an unframed streaming response (Connection: close
        delimits the body — SSE-friendly and proxy-simple). Returns a
        writer with ``.write(bytes)`` / ``.flush()``; a vanished client
        surfaces as :class:`ClientDisconnected` from the next write."""
        self.replied = True
        h = self._h
        h.send_response(int(status))
        h.send_header("Content-Type", ctype)
        h.send_header("Cache-Control", "no-cache")
        h.send_header("Connection", "close")
        for k, v in (headers or {}).items():
            h.send_header(k, str(v))
        h.end_headers()
        ctx = self

        class _Writer:
            def write(self, data):
                if ctx._head_only:
                    return
                try:
                    h.wfile.write(data)
                    h.wfile.flush()
                except (BrokenPipeError, ConnectionResetError,
                        OSError) as e:
                    raise ClientDisconnected(str(e)) from e

        return _Writer()


class HttpService:
    """Threaded stdlib HTTP server behind a route table — the shared
    implementation under the metrics scrape endpoint, the replica
    worker's health port, the cluster tier endpoint and the serving
    frontend (each used to re-wrap ``http.server`` ad hoc).

    ``route(path, handler, methods)`` registers ``handler(ctx)`` for
    exact-path matches; HEAD auto-maps to the GET handler with the
    body suppressed (Content-Length still reflects the full render).
    Handlers that raise reply 500 (ValueError: 400); unknown paths
    404. ``start()`` binds and serves on a daemon thread; ``stop()``
    shuts down and joins."""

    def __init__(self, addr="127.0.0.1", port=0, name="http"):
        self._addr = addr
        self._want_port = port
        self.name = name
        self._routes = {}
        self._prefix_routes = []
        self._httpd = None
        self._thread = None
        self.port = None
        self.url = None

    def route(self, path, handler, methods=("GET",)):
        for m in methods:
            self._routes[(m, path)] = handler
        return self

    def route_prefix(self, prefix, handler, methods=("GET",)):
        """Register ``handler(ctx)`` for any path starting with
        ``prefix`` (path-parameter routes like ``/v1/requests/<id>/
        trace``). Exact routes win; among prefixes the longest match
        wins. The handler reads the remainder off ``ctx.path``."""
        for m in methods:
            self._prefix_routes.append((m, str(prefix), handler))
        self._prefix_routes.sort(key=lambda r: -len(r[1]))
        return self

    def _match_prefix(self, method, path, head_only=False):
        for m, prefix, fn in self._prefix_routes:
            if path.startswith(prefix) and (
                    m == method or (head_only and m == "GET")):
                return fn
        return None

    def start(self):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        if self._httpd is not None:
            return self
        svc = self

        class Handler(BaseHTTPRequestHandler):
            def _dispatch(self, head_only=False):
                ctx = HttpContext(self, head_only=head_only)
                fn = svc._routes.get((ctx.method, ctx.path))
                if fn is None and head_only:
                    fn = svc._routes.get(("GET", ctx.path))
                if fn is None:
                    fn = svc._match_prefix(ctx.method, ctx.path,
                                           head_only)
                if fn is None:
                    self.send_error(404)
                    return
                try:
                    fn(ctx)
                    if not ctx.replied:
                        ctx.send_json(500, {"error": {
                            "message": "handler produced no response",
                            "type": "server_error"}})
                except ClientDisconnected:
                    pass        # the handler already cleaned up
                except ValueError as e:
                    if not ctx.replied:
                        ctx.send_json(400, {"error": {
                            "message": str(e),
                            "type": "invalid_request_error"}})
                except (BrokenPipeError, ConnectionResetError):
                    pass        # client gone mid-plain-reply
                except Exception as e:
                    if not ctx.replied:
                        ctx.send_json(500, {"error": {
                            "message": f"{type(e).__name__}: {e}",
                            "type": "server_error"}})

            def do_GET(self):
                self._dispatch()

            def do_POST(self):
                self._dispatch()

            def do_HEAD(self):
                # probes use HEAD to skip the body; the full text is
                # still rendered so Content-Length matches a GET
                self._dispatch(head_only=True)

            def log_message(self, *args):   # no stderr spam per scrape
                pass

        self._httpd = ThreadingHTTPServer((self._addr, self._want_port),
                                          Handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://{self._addr}:{self.port}/metrics"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"{self.name}-server")
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd = None
        self._thread = None


#: Back-compat alias: callers that type-checked the old handle class
#: keep working — the service IS the handle now.
ScrapeServer = HttpService


def add_probe_routes(svc, registry=None, ready=None, health_info=None,
                     snapshot_fn=None, profile_fn=None):
    """Install the standard probe routes on an :class:`HttpService`:
    ``/metrics`` (+ ``/``), ``/metrics.json``, ``/healthz``,
    ``/readyz``.

    ``ready`` is an optional zero-arg callable consulted per
    ``/readyz`` probe: truthy -> 200, falsy (or raising) -> 503 — 503
    means "alive but do not send traffic", the state a draining or
    admission-paused serving replica is in, so load balancers stop
    routing BEFORE ``drain()`` finishes. ``/healthz`` stays 200 the
    whole time (the process is healthy; restarting it would be wrong).
    With ``ready=None``, ``/readyz`` mirrors ``/healthz``.

    ``health_info`` is an optional zero-arg callable whose dict is
    merged into the ``/healthz`` document per probe (e.g. membership
    epoch + last-heartbeat age, so an operator can spot a fenced-out
    stale incarnation from the probe alone); a raising callable
    degrades to the base document rather than failing liveness.

    ``snapshot_fn`` overrides what ``/metrics`` + ``/metrics.json``
    render: a zero-arg callable returning a :func:`json_snapshot`-shaped
    list (e.g. ``ServingCluster.scrape`` — the merged one-pane cluster
    snapshot) instead of the local registry.

    ``profile_fn`` backs ``/debug/profile?seconds=N``: a callable taking
    the window in seconds and returning a Perfetto-loadable trace dict
    (e.g. ``ServingCluster.capture_profile`` for a cluster-wide merged
    capture). With ``profile_fn=None`` the route captures THIS process
    via :func:`~.perf.capture_bundle`. Returns 503 when capture is
    disabled (``PADDLE_TPU_METRICS=0``)."""
    from . import perf as _perf

    reg = registry if registry is not None else default_registry()
    t_start = time.monotonic()

    def _snapshot():
        _perf.ensure_build_info(reg)
        if snapshot_fn is not None:
            return snapshot_fn()
        return json_snapshot(reg)

    def metrics(ctx):
        ctx.send(200, snapshot_to_prometheus(_snapshot()).encode(),
                 "text/plain; version=0.0.4; charset=utf-8")

    def metrics_json(ctx):
        ctx.send_json(200, _snapshot())

    def healthz(ctx):
        doc = {"status": "ok", "pid": os.getpid(),
               "uptime_seconds": round(time.monotonic() - t_start, 3)}
        if health_info is not None:
            try:
                doc.update(health_info() or {})
            except Exception:
                pass    # liveness must not fail on extras
        ctx.send_json(200, doc)

    def readyz(ctx):
        ok = True
        if ready is not None:
            try:
                ok = bool(ready())
            except Exception:
                ok = False
        ctx.send_json(200 if ok else 503,
                      {"status": "ready" if ok else "not_ready",
                       "pid": os.getpid()})

    def debug_profile(ctx):
        import urllib.parse

        try:
            q = urllib.parse.parse_qs(ctx.query)
            seconds = float(q.get("seconds", ["1.0"])[0])
        except (ValueError, TypeError):
            ctx.send_json(400, {"error": "bad seconds parameter"})
            return
        seconds = min(max(seconds, 0.0), 30.0)    # bound the window
        try:
            if profile_fn is not None:
                bundle = profile_fn(seconds)
            else:
                bundle = _perf.capture_bundle(seconds)
        except Exception as e:
            ctx.send_json(500, {"error": f"capture failed: {e!r}"})
            return
        if bundle is None:
            ctx.send_json(503, {"error": "profiling disabled "
                                         "(PADDLE_TPU_METRICS=0)"})
            return
        ctx.send_json(200, bundle)

    svc.route("/", metrics)
    svc.route("/metrics", metrics)
    svc.route("/metrics.json", metrics_json)
    svc.route("/healthz", healthz)
    svc.route("/readyz", readyz)
    svc.route("/debug/profile", debug_profile)
    return svc


def start_http_server(port=0, addr="127.0.0.1", registry=None,
                      ready=None, health_info=None, snapshot_fn=None,
                      profile_fn=None):
    """Serve the probe routes (see :func:`add_probe_routes`) on a
    daemon thread; ``port=0`` picks a free port. Returns the running
    :class:`HttpService` (``.port`` / ``.url`` / ``.stop``)."""
    svc = HttpService(addr=addr, port=port, name="metrics")
    add_probe_routes(svc, registry=registry, ready=ready,
                     health_info=health_info, snapshot_fn=snapshot_fn,
                     profile_fn=profile_fn)
    return svc.start()
