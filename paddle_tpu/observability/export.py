"""Exporters: Prometheus text format, JSON snapshots, HTTP scrape.

- :func:`json_snapshot` — a pure-data (JSON-serializable) dump of a
  registry; :func:`snapshot_to_prometheus` renders such a snapshot to
  Prometheus text, and :func:`prometheus_text` composes the two — so
  text output round-trips exactly through the JSON snapshot layer
  (serialize, ship, re-render identically on another host).
- :func:`start_http_server` — an optional stdlib ``http.server`` scrape
  endpoint (``/metrics`` text + HEAD, ``/metrics.json`` snapshot,
  ``/healthz`` liveness probe, ``/readyz`` readiness probe that turns
  503 while the local engine drains) for the serving engine; returns a
  handle with ``.port`` / ``.url`` / ``.stop``.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time

from .metrics import default_registry

__all__ = ["json_snapshot", "snapshot_to_prometheus", "prometheus_text",
           "start_http_server", "ScrapeServer"]


def _fmt_value(v):
    if isinstance(v, str):
        return v    # non-finite marker straight from a JSON snapshot
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _json_value(v):
    """Float for the snapshot, except non-finite values become their
    Prometheus markers ("+Inf"/"-Inf"/"NaN"): json.dumps would emit bare
    Infinity/NaN — invalid JSON that strict parsers (JSON.parse, jq, Go)
    reject, breaking the documented cross-host snapshot round-trip."""
    v = float(v)
    if not math.isfinite(v):
        return _fmt_value(v)
    return v


def _escape(v):
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def _label_str(labelnames, values, extra=()):
    pairs = list(zip(labelnames, values)) + list(extra)
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in pairs) + "}"


def json_snapshot(registry=None):
    """List of metric dicts (name/help/type/labelnames/samples) holding
    only JSON-native values — ``json.dumps`` round-trips it losslessly."""
    reg = registry if registry is not None else default_registry()
    out = []
    for m in reg.collect():
        entry = {"name": m.name, "help": m.help, "type": m.kind,
                 "labelnames": list(m.labelnames), "samples": []}
        for values, leaf in m.samples():
            sample = {"labels": list(values)}
            if m.kind == "histogram":
                counts, total = leaf.snapshot()
                sample.update(buckets=list(leaf.buckets),
                              counts=counts,
                              sum=_json_value(total),
                              count=int(sum(counts)))
            else:
                sample["value"] = _json_value(leaf.value)
            entry["samples"].append(sample)
        out.append(entry)
    return out


def snapshot_to_prometheus(snapshot):
    """Render a :func:`json_snapshot` (or its JSON round-trip) to
    Prometheus exposition text (version 0.0.4)."""
    lines = []
    for entry in snapshot:
        name, kind = entry["name"], entry["type"]
        labelnames = entry.get("labelnames", [])
        if entry.get("help"):
            lines.append(f"# HELP {name} {_escape(entry['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        for sample in entry["samples"]:
            values = sample.get("labels", [])
            if kind == "histogram":
                acc = 0
                bounds = list(sample["buckets"]) + ["+Inf"]
                for bound, c in zip(bounds, sample["counts"]):
                    acc += c
                    le = "+Inf" if bound == "+Inf" else _fmt_value(bound)
                    ls = _label_str(labelnames, values, [("le", le)])
                    lines.append(f"{name}_bucket{ls} {acc}")
                ls = _label_str(labelnames, values)
                lines.append(f"{name}_sum{ls} {_fmt_value(sample['sum'])}")
                lines.append(f"{name}_count{ls} {sample['count']}")
            else:
                ls = _label_str(labelnames, values)
                lines.append(f"{name}{ls} {_fmt_value(sample['value'])}")
    return "\n".join(lines) + "\n" if lines else ""


def prometheus_text(registry=None):
    """Prometheus text for a registry (the scrape-endpoint body)."""
    return snapshot_to_prometheus(json_snapshot(registry))


class ScrapeServer:
    """Handle for a running scrape endpoint."""

    def __init__(self, httpd, thread):
        self._httpd = httpd
        self._thread = thread
        self.port = httpd.server_address[1]
        self.url = f"http://{httpd.server_address[0]}:{self.port}/metrics"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_http_server(port=0, addr="127.0.0.1", registry=None,
                      ready=None, health_info=None):
    """Serve ``/metrics`` (Prometheus text; HEAD supported for cheap
    reachability checks), ``/metrics.json``, ``/healthz`` (200 +
    uptime/pid JSON — the liveness probe serving deployments point at
    the same port), and ``/readyz`` (readiness, see below) on a daemon
    thread; ``port=0`` picks a free port. Returns
    :class:`ScrapeServer`.

    ``ready`` is an optional zero-arg callable consulted per
    ``/readyz`` probe: truthy -> 200, falsy (or raising) -> 503 — 503
    means "alive but do not send traffic", the state a draining or
    admission-paused serving replica is in, so load balancers stop
    routing BEFORE ``drain()`` finishes. ``/healthz`` stays 200 the
    whole time (the process is healthy; restarting it would be wrong).
    With ``ready=None``, ``/readyz`` mirrors ``/healthz``.

    ``health_info`` is an optional zero-arg callable whose dict is
    merged into the ``/healthz`` document per probe (e.g. membership
    epoch + last-heartbeat age, so an operator can spot a fenced-out
    stale incarnation from the probe alone); a raising callable
    degrades to the base document rather than failing liveness."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry if registry is not None else default_registry()
    t_start = time.monotonic()

    class Handler(BaseHTTPRequestHandler):
        def _payload(self):
            """(status, body, content-type) for the path, or None."""
            if self.path in ("/", "/metrics"):
                return (200, prometheus_text(reg).encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
            if self.path == "/metrics.json":
                return (200, json.dumps(json_snapshot(reg)).encode(),
                        "application/json")
            if self.path == "/healthz":
                doc = {"status": "ok", "pid": os.getpid(),
                       "uptime_seconds": round(
                           time.monotonic() - t_start, 3)}
                if health_info is not None:
                    try:
                        doc.update(health_info() or {})
                    except Exception:
                        pass    # liveness must not fail on extras
                return 200, json.dumps(doc).encode(), "application/json"
            if self.path == "/readyz":
                ok = True
                if ready is not None:
                    try:
                        ok = bool(ready())
                    except Exception:
                        ok = False
                doc = {"status": "ready" if ok else "not_ready",
                       "pid": os.getpid()}
                return (200 if ok else 503,
                        json.dumps(doc).encode(), "application/json")
            return None

        def _respond(self, head_only):
            payload = self._payload()
            if payload is None:
                self.send_error(404)
                return
            status, body, ctype = payload
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if not head_only:
                self.wfile.write(body)

        def do_GET(self):
            self._respond(head_only=False)

        def do_HEAD(self):
            # probes use HEAD to skip the body; the full text is still
            # rendered so Content-Length matches a subsequent GET
            self._respond(head_only=True)

        def log_message(self, *args):  # scrapes must not spam stderr
            pass

    httpd = ThreadingHTTPServer((addr, port), Handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return ScrapeServer(httpd, thread)
