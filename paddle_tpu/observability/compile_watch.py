"""Compile & device-memory observability: the XLA compile watcher.

The two things that dominate TPU behavior — XLA compilation and device
memory — are invisible to host-side spans: a silent recompile storm in
``jit.to_static`` or ``LlamaModel.generate`` looks identical to slow
hardware, and an OOM leaves no record of what was resident. This module
is the single choke-point every framework-owned ``jax.jit`` entry
compiles through:

- :class:`CompileWatch` — per-callable compile accounting. The first
  dispatch of a new signature compiles ahead-of-time
  (``jitted.lower(...).compile()``) so the watcher gets the exact
  compile count, a wall-clock duration histogram, and the program's
  static ``cost_analysis`` / ``memory_analysis`` (FLOPs, bytes
  accessed, peak temp memory) — no double compile, because the
  returned executable IS what the caller dispatches afterwards.
- Recompile-storm detection: when a callable exceeds N distinct
  signatures (``PADDLE_TPU_RECOMPILE_STORM_SIGS``, default 8) a storm
  counter fires with a one-line diagnosis naming the churning argument
  shapes/dtypes.
- :func:`watched_jit` — drop-in ``jax.jit`` replacement for raw jit
  entries (the compiled pipeline schedule) that routes through the same
  watcher.
- A ``jax.monitoring`` listener tallies EVERY backend compile in the
  process (``paddle_tpu_xla_backend_compile_total``) — the catch-all
  that surfaces compile churn outside the framework's own entries.
- :func:`sample_device_memory` — live-bytes/peak gauges from
  ``device.memory_stats`` + ``jax.live_arrays()`` (metadata only, no
  device sync), sampled per hapi step and per serving wave.

Everything honors the PR-1 kill switch: with ``PADDLE_TPU_METRICS=0``
:func:`watch` returns a shared no-op, callers skip the AOT path, and
dispatch stays byte-identical to the unwatched ``jax.jit`` fast path.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
import weakref
from collections import deque

from . import metrics as om
from .metrics import enabled
from .trace import _EPOCH

__all__ = [
    "CompileWatch", "NULL_WATCH", "watch", "watched_jit", "describe_args",
    "sample_device_memory", "recent_compile_events", "reset",
    "COMPILE_BUCKETS", "DEFAULT_STORM_THRESHOLD",
    "enable_persistent_cache", "persistent_cache_stats",
    "SignatureRegistry", "shape_registry",
]

#: compile-duration buckets: 10ms (tiny CPU programs) .. 300s (big TPU
#: programs); the PR-1 latency defaults top out at 10s — too short
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
                   60.0, 300.0)

DEFAULT_STORM_THRESHOLD = 8

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: what ``jax.stages.Compiled.__call__`` raises when the concrete args
#: no longer match the executable's fixed signature: TypeError for
#: shape/dtype/pytree drift, ValueError for sharding/layout drift. Every
#: AOT dispatch site catches exactly this tuple and falls back to the
#: plain jit path (which retraces such drift transparently) — no Python
#: user code runs inside the compiled call, so these cannot mask a user
#: error.
AOT_MISMATCH_ERRORS = (TypeError, ValueError)

_lock = threading.Lock()
_watches: dict[str, "CompileWatch"] = {}
_listener_installed = False
#: bounded ring of recent compile events (dicts) for the flight recorder
_events: deque = deque(maxlen=512)
#: name of the program currently compiling in this thread (enriches the
#: listener's flight-recorder entries; carries no metric state)
_tls = threading.local()


def storm_threshold():
    """Distinct-signature count past which a callable is a recompile
    storm (env ``PADDLE_TPU_RECOMPILE_STORM_SIGS``, checked per compile
    so tests can tune it)."""
    try:
        return int(os.environ.get("PADDLE_TPU_RECOMPILE_STORM_SIGS",
                                  DEFAULT_STORM_THRESHOLD))
    except ValueError:
        return DEFAULT_STORM_THRESHOLD


def _note_event(event):
    # deque.append alone is atomic, but the flight recorder snapshots
    # the ring with list() mid-crash — an unlocked append from a serving
    # thread compiling a new burst would raise "deque mutated during
    # iteration" and cost the bundle its compile history
    with _lock:
        _events.append(event)


def recent_compile_events():
    """Recent compile events (newest last) — the flight recorder's
    compile log."""
    with _lock:
        return list(_events)


def reset():
    """Drop all per-callable signature state, the event ring, and the
    memory-sample throttle/high-water (test isolation; production code
    never needs this)."""
    global _mem_peak
    with _lock:
        _watches.clear()
        _events.clear()
    _mem_last.clear()
    _mem_peak = 0


def _ensure_listener():
    """Register the process-wide ``jax.monitoring`` listeners once: every
    XLA backend compile — watched or not — lands in the global tally and
    the flight-recorder ring, and persistent-compilation-cache hit/miss
    events land in the warm-restart counters. A registration failure (a
    jax build without the API) degrades to per-callable counting only —
    it must never crash the user's first compiled step."""
    global _listener_installed
    with _lock:
        if _listener_installed:
            return
        _listener_installed = True
    try:
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_on_jax_event)
        jax.monitoring.register_event_listener(_on_jax_count_event)
    except Exception:
        pass


def _on_jax_event(name, duration, **kwargs):
    if name != _BACKEND_COMPILE_EVENT or not enabled():
        return
    om.counter("paddle_tpu_xla_backend_compile_total",
               "XLA backend compiles in this process (all sources)").inc()
    om.histogram("paddle_tpu_xla_backend_compile_seconds",
                 "XLA backend compile duration (all sources)",
                 buckets=COMPILE_BUCKETS).observe(duration)
    _note_event({
        "kind": "backend_compile",
        "name": getattr(_tls, "current", None) or "(unattributed)",
        "ts": (time.perf_counter() - _EPOCH) * 1e6 - duration * 1e6,
        "dur": duration * 1e6,
    })


#: raw persistent-cache tallies — kept as plain ints alongside the
#: metric counters so a replica worker can report its warm-start hit
#: rate over rpc even under ``PADDLE_TPU_METRICS=0``
_cache_counts = {"hits": 0, "misses": 0}


def _on_jax_count_event(name, **kwargs):
    """Count-event listener: the persistent compilation cache announces
    ``/jax/compilation_cache/cache_hits`` / ``.../cache_misses`` per
    lookup — the signal that says whether a restarted replica's compiles
    were served from disk (seconds) or paid in full (~19 s on a real
    chip)."""
    if "/jax/compilation_cache/cache_hit" in name:
        _cache_counts["hits"] += 1
        if enabled():
            om.counter("compile_cache_hit_total",
                       "XLA programs served from the persistent "
                       "compilation cache").inc()
    elif "/jax/compilation_cache/cache_miss" in name:
        _cache_counts["misses"] += 1
        if enabled():
            om.counter("compile_cache_miss_total",
                       "XLA programs compiled from scratch (persistent "
                       "cache lookup missed)").inc()


def persistent_cache_stats():
    """``{"hits", "misses", "dir"}`` for this process — independent of
    the metrics kill switch so workers can report warm-start health."""
    return {"hits": _cache_counts["hits"],
            "misses": _cache_counts["misses"],
            "dir": _cache_dir}


_cache_dir = None
_cache_lock = threading.Lock()


def default_cache_dir():
    """Default persistent-cache location: ``PADDLE_TPU_COMPILE_CACHE_DIR``
    or ``~/.cache/paddle_tpu/xla_cache``."""
    return os.environ.get("PADDLE_TPU_COMPILE_CACHE_DIR") \
        or os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "xla_cache")


def enable_persistent_cache(path=None):
    """Wire JAX's persistent compilation cache (ROADMAP item 5: kill the
    ~19 s cold start). Every backend compile is keyed by its HLO and
    stored under ``path``; a fresh process re-compiling the same serving
    programs (mixed-step shapes, decode scans) gets executables back in
    seconds. Called once per process by the serving engine — set
    ``PADDLE_TPU_COMPILE_CACHE=0`` to opt out, or
    ``PADDLE_TPU_COMPILE_CACHE_DIR`` to relocate (replicas sharing a
    host should share the directory). ``min_compile_time_secs`` is
    forced to 0 so even small programs cache — elastic restart is about
    the SUM of compiles, not the largest one.

    Returns the cache directory, or None when disabled/unavailable.
    Idempotent; hit/miss land in ``compile_cache_hit_total`` /
    ``compile_cache_miss_total`` and :func:`persistent_cache_stats`."""
    global _cache_dir
    if os.environ.get("PADDLE_TPU_COMPILE_CACHE", "1").lower() \
            in ("0", "off", "false"):
        return None
    with _cache_lock:
        if _cache_dir is not None:
            return _cache_dir
        cache = path or default_cache_dir()
        try:
            import jax

            os.makedirs(cache, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            try:
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", -1)
            except Exception:
                pass        # older jax: size gate stays at its default
            try:
                # the backend usually initializes during framework
                # import, BEFORE this config lands — jax then latches
                # "no cache" at its first compile and silently ignores
                # the directory forever; reset re-arms the lazy init so
                # the next compile picks the configured dir up
                from jax._src import compilation_cache as _jcc

                _jcc.reset_cache()
            except Exception:
                pass
        except Exception:
            return None     # unwritable dir / jax without the config
        _cache_dir = cache
    _ensure_listener()
    return _cache_dir


class SignatureRegistry:
    """Durable record of the shape signatures a named callable compiled
    — the compile watcher's in-memory ``_sigs``, persisted so the NEXT
    process knows what to pre-warm before traffic arrives.

    The file is JSON ``{key: {kind: [values]}}`` where ``key`` names one
    compile surface (the serving engine hashes its model dims + batch
    geometry into it) and each ``kind`` collects the distinct values
    seen (mixed-program token shapes, decode-scan tick counts, ...). Writes are
    read-merge-replace with a write-aside temp file, mirroring the
    FileStore stamp protocol, so concurrent replicas on one host can
    record without tearing the file (a lost race drops one record until
    its next compile re-records it — never corruption)."""

    def __init__(self, path):
        self.path = path
        self._lock = threading.Lock()

    def _load(self):
        try:
            with open(self.path) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else {}
        except (OSError, ValueError):
            return {}

    def record(self, key, kind, value):
        """Merge one (key, kind, value) into the registry. Returns True
        when the value was new for that key/kind."""
        with self._lock:
            doc = self._load()
            kinds = doc.setdefault(str(key), {})
            vals = kinds.setdefault(str(kind), [])
            if value in vals:
                return False
            vals.append(value)
            vals.sort()
            tmp = f"{self.path}.{os.getpid()}.tmp"
            try:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                with open(tmp, "w") as f:
                    json.dump(doc, f, indent=0, sort_keys=True)
                os.replace(tmp, self.path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return False
            return True

    def lookup(self, key):
        """``{kind: [values]}`` recorded for ``key`` (empty when none)."""
        with self._lock:
            return self._load().get(str(key), {})


_shape_registry = None


def shape_registry():
    """The process-default :class:`SignatureRegistry`
    (``PADDLE_TPU_SHAPE_REGISTRY`` or ``<cache_dir>/serving_shapes.json``
    next to the persistent compile cache, so replicas sharing the cache
    share the warm-up recipe)."""
    global _shape_registry
    with _cache_lock:
        if _shape_registry is None:
            path = os.environ.get("PADDLE_TPU_SHAPE_REGISTRY") \
                or os.path.join(default_cache_dir(), "serving_shapes.json")
            _shape_registry = SignatureRegistry(path)
        return _shape_registry


def _in_outer_trace():
    """True when this thread is inside an active jax trace (grad/vjp/an
    enclosing jit) — only the plain jit path composes there. O(1): the
    per-dispatch guard must not walk the model state. Falls back to
    assuming a trace when the introspection API is missing (the safe
    direction: plain jit always works)."""
    import jax

    try:
        return not jax.core.trace_state_clean()
    except Exception:
        return True


def _arg_key(args, kwargs=None):
    """Cheap hashable cache key over the call: raw (shape, dtype)
    tuples per leaf plus the pytree structure — no string formatting,
    because this runs on EVERY watched dispatch (the pipeline train
    step's hot path). The treedef matters: ``f(x, s=2.0)`` and
    ``f(x, 2.0)`` carry identical leaves but bind differently, and
    sharing a cache entry would dispatch the wrong executable. Default
    flattening (no is_leaf): custom registered pytree containers
    decompose into their array leaves instead of being identity-hashed
    as opaque leaves (which would mint a fresh signature per instance),
    and ``None`` placement is captured by the treedef. Returns None when
    a leaf is unhashable (the caller skips watching)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    out = [("~tree", treedef)]
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            out.append((tuple(shape), str(dtype)))
        elif isinstance(leaf, (bool, int, float, complex)):
            # jax.jit traces Python scalars as weak-typed values — one
            # compile per TYPE; keying on the value would AOT-compile an
            # identical program per distinct scalar (and trip the storm
            # alarm on a changing learning rate)
            out.append(("~weak", type(leaf).__name__))
        else:
            try:
                hash(leaf)
            except TypeError:
                return None
            out.append(("~static", leaf))
    return tuple(out)


def _key_desc(key):
    """Render an :func:`_arg_key` into the labeled string descriptor the
    storm diagnosis names args by — built only on compile, never on the
    dispatch hot path."""
    out = []
    for i, k in enumerate(key):
        tag, val = k
        if tag == "~tree":
            out.append(("tree", str(val)))
        elif tag == "~weak":
            out.append((f"arg{i - 1}", f"weak_{val}"))
        elif tag == "~static":
            out.append((f"arg{i - 1}", f"{type(val).__name__}={val!r}"))
        else:
            out.append((f"arg{i - 1}",
                        f"{val}[{','.join(str(int(s)) for s in tag)}]"))
    return tuple(out)


def describe_args(args, kwargs=None):
    """Labeled signature descriptor for storm diagnosis — ``("arg0",
    "float32[4,8]")`` for arrays, ``("arg1", "weak_float")`` for Python
    scalars. None when a leaf is unhashable."""
    key = _arg_key(args, kwargs)
    return None if key is None else _key_desc(key)


class _NullWatch:
    """Shared no-op watch returned when metrics are disabled — keeps
    call chains valid at zero cost."""

    __slots__ = ()

    def aot_compile(self, jitted, args, kwargs=None, desc=None):
        return None

    def timed_first_dispatch(self, jitted, args, kwargs=None, desc=None):
        return jitted(*args, **(kwargs or {}))

    def observe_signature(self, desc):
        pass

    def record_compile(self, duration, desc=None, compiled=None):
        pass

    @property
    def last_diagnosis(self):
        return None


NULL_WATCH = _NullWatch()


class CompileWatch:
    """Compile accounting for ONE named callable.

    Metric families (all labeled ``callable``), created on the default
    registry at record time so registry clears between tests cannot
    orphan children:

    - ``paddle_tpu_xla_compile_total`` — programs compiled
    - ``paddle_tpu_xla_compile_seconds`` — compile duration histogram
    - ``paddle_tpu_xla_distinct_signatures`` — distinct signatures seen
    - ``paddle_tpu_xla_recompile_storm_total`` — new signatures past the
      storm threshold
    - ``paddle_tpu_xla_program_flops`` / ``..._program_bytes_accessed``
      / ``..._program_peak_temp_bytes`` — static analysis of the most
      recently compiled program
    """

    def __init__(self, name):
        self.name = name
        self._sigs: dict[tuple, int] = {}
        self._storm_announced = False
        self.last_diagnosis = None
        self._lock = threading.Lock()

    # -- metric handles (re-resolved per record: compiles are rare) -----
    def _m(self, kind):
        if kind == "compiles":
            fam = om.counter("paddle_tpu_xla_compile_total",
                             "XLA programs compiled per callable",
                             labelnames=("callable",))
        elif kind == "seconds":
            fam = om.histogram("paddle_tpu_xla_compile_seconds",
                               "XLA compile duration per callable",
                               labelnames=("callable",),
                               buckets=COMPILE_BUCKETS)
        elif kind == "sigs":
            fam = om.gauge("paddle_tpu_xla_distinct_signatures",
                           "distinct compile signatures per callable",
                           labelnames=("callable",))
        elif kind == "storms":
            fam = om.counter(
                "paddle_tpu_xla_recompile_storm_total",
                "new signatures past the recompile-storm threshold",
                labelnames=("callable",))
        elif kind == "flops":
            fam = om.gauge("paddle_tpu_xla_program_flops",
                           "cost_analysis FLOPs of the last compiled "
                           "program", labelnames=("callable",))
        elif kind == "bytes":
            fam = om.gauge("paddle_tpu_xla_program_bytes_accessed",
                           "cost_analysis bytes accessed of the last "
                           "compiled program", labelnames=("callable",))
        else:
            fam = om.gauge("paddle_tpu_xla_program_peak_temp_bytes",
                           "memory_analysis peak temp bytes of the last "
                           "compiled program", labelnames=("callable",))
        return fam.labels(self.name)

    # -- signature bookkeeping ------------------------------------------
    def observe_signature(self, desc):
        """Track one (possibly new) signature; fires the storm counter +
        one-line diagnosis when the callable exceeds the threshold."""
        if desc is None:
            return
        announce = None
        with self._lock:
            if desc in self._sigs:
                self._sigs[desc] += 1
                return
            self._sigs[desc] = 1
            n = len(self._sigs)
            self._m("sigs").set(n)
            if n > storm_threshold():
                self._m("storms").inc()
                self.last_diagnosis = self._diagnose(n)
                if not self._storm_announced:
                    self._storm_announced = True
                    announce = self.last_diagnosis
        if announce:
            print(announce, file=sys.stderr)

    def _diagnose(self, n):
        """One line naming the churning argument shapes/dtypes."""
        by_label: dict[str, set] = {}
        order: list[str] = []
        for desc in self._sigs:
            for label, value in desc:
                if label not in by_label:
                    by_label[label] = set()
                    order.append(label)
                by_label[label].add(value)
        churn = ", ".join(
            f"{label} churns {len(by_label[label])} variants "
            f"({' | '.join(sorted(by_label[label])[:4])}"
            f"{', ...' if len(by_label[label]) > 4 else ''})"
            for label in order if len(by_label[label]) > 1)
        return (f"[compile_watch] recompile storm: {self.name!r} has "
                f"{n} distinct signatures "
                f"(threshold {storm_threshold()}); "
                f"{churn or 'churn outside tracked args'}")

    # -- the compile choke-point ----------------------------------------
    def aot_compile(self, jitted, args, kwargs=None, desc=None):
        """Lower + compile ``jitted`` for these concrete args, recording
        count, duration, and cost/memory analysis. Returns the compiled
        executable (dispatch it for all later same-signature calls), or
        None when AOT lowering is unsupported for this program — the
        caller then falls back to :meth:`timed_first_dispatch`."""
        kwargs = kwargs or {}
        _ensure_listener()
        self.observe_signature(desc)
        _tls.current = self.name
        t0 = time.perf_counter()
        try:
            compiled = jitted.lower(*args, **kwargs).compile()
        except Exception:
            return None
        finally:
            _tls.current = None
        dur = time.perf_counter() - t0
        self.record_compile(dur, desc=desc, compiled=compiled)
        return compiled

    def timed_first_dispatch(self, jitted, args, kwargs=None, desc=None):
        """Fallback when AOT lowering fails: dispatch through the jit
        wrapper and record its first-call wall time as the compile
        duration (over-counts by one execution — honest upper bound)."""
        _ensure_listener()
        self.observe_signature(desc)
        _tls.current = self.name
        t0 = time.perf_counter()
        try:
            out = jitted(*args, **(kwargs or {}))
        finally:
            _tls.current = None
        self.record_compile(time.perf_counter() - t0, desc=desc)
        return out

    def record_compile(self, duration, desc=None, compiled=None):
        """Record one compile of this callable (counter + histogram +
        static program analysis when the executable is given)."""
        self._m("compiles").inc()
        self._m("seconds").observe(duration)
        event = {
            "kind": "compile",
            "name": self.name,
            "ts": (time.perf_counter() - _EPOCH) * 1e6 - duration * 1e6,
            "dur": duration * 1e6,
        }
        if desc:
            event["signature"] = "; ".join(f"{k}={v}" for k, v in desc)
        if compiled is not None:
            flops, nbytes, temp = self._analyze(compiled)
            if flops is not None:
                self._m("flops").set(flops)
                event["flops"] = flops
            if nbytes is not None:
                self._m("bytes").set(nbytes)
                event["bytes_accessed"] = nbytes
            if temp is not None:
                self._m("temp").set(temp)
                event["peak_temp_bytes"] = temp
        _note_event(event)

    @staticmethod
    def _analyze(compiled):
        """(flops, bytes_accessed, peak_temp_bytes) from the executable's
        static analyses; None per field where the backend doesn't
        report."""
        flops = nbytes = temp = None
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if ca:
                flops = float(ca.get("flops", float("nan")))
                flops = None if flops != flops else flops
                nbytes = float(ca.get("bytes accessed", float("nan")))
                nbytes = None if nbytes != nbytes else nbytes
        except Exception:
            pass
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                temp = float(getattr(ma, "temp_size_in_bytes", None))
        except Exception:
            temp = None
        return flops, nbytes, temp


def watch(name):
    """The process-wide :class:`CompileWatch` for ``name`` (a no-op
    watch under ``PADDLE_TPU_METRICS=0`` — checked per call so tests can
    toggle the environment)."""
    if not enabled():
        return NULL_WATCH
    with _lock:
        w = _watches.get(name)
        if w is None:
            w = _watches[name] = CompileWatch(name)
        return w


def _static_arg_key(args, kwargs, static_nums, static_names):
    """Cache key for a jit with static arguments: static positions key
    by VALUE (each distinct value is its own program, exactly jit's
    cache rule), dynamic ones by the usual shape/dtype key. None when a
    static value is unhashable (jit itself would reject it)."""
    key = []
    for i, a in enumerate(args):
        if i in static_nums:
            try:
                hash(a)
            except TypeError:
                return None
            key.append(("~staticval", a))
        else:
            sub = _arg_key((a,))
            if sub is None:
                return None
            key.append(sub)
    for k in sorted(kwargs):
        v = kwargs[k]
        if k in static_names:
            try:
                hash(v)
            except TypeError:
                return None
            key.append((k, "~staticval", v))
        else:
            sub = _arg_key((v,))
            if sub is None:
                return None
            key.append((k, sub))
    return tuple(key)


def watched_jit(fun, name=None, **jit_kwargs):
    """``jax.jit`` with compile observability: each new call signature
    compiles through :meth:`CompileWatch.aot_compile` (counted, timed,
    cost-analyzed), later calls dispatch the cached executable. Under
    ``PADDLE_TPU_METRICS=0`` every call takes the plain jit fast path —
    byte-identical dispatch, no signature hashing.

    With ``static_argnums``/``static_argnames`` the AOT path is skipped
    (a ``jax.stages.Compiled`` takes only the dynamic arguments, so
    dispatching it with the original call shape would mismatch and
    double-compile); those functions dispatch plain jit, with compiles
    counted per distinct static-value signature via the timed first
    dispatch."""
    import functools

    import jax

    jitted = jax.jit(fun, **jit_kwargs)
    watch_name = name or getattr(fun, "__qualname__", None) or repr(fun)
    cache: dict[tuple, object] = {}
    nums = jit_kwargs.get("static_argnums")
    names = jit_kwargs.get("static_argnames")
    static_nums = frozenset((nums,) if isinstance(nums, int)
                            else nums or ())
    static_names = frozenset((names,) if isinstance(names, str)
                             else names or ())
    has_statics = bool(static_nums or static_names)

    @functools.wraps(fun)
    def wrapper(*args, **kwargs):
        if not enabled():
            return jitted(*args, **kwargs)
        if _in_outer_trace():
            # called inside an outer trace (grad/vjp/an enclosing jit):
            # an AOT executable cannot take tracers, but jit composes —
            # it inlines into the outer program (no separate compile to
            # watch here; the OUTER program's watcher accounts for it)
            return jitted(*args, **kwargs)
        if has_statics:
            key = _static_arg_key(args, kwargs, static_nums,
                                  static_names)
            if key is None or key in cache:
                return jitted(*args, **kwargs)
            cache[key] = None   # counted once; plain jit owns dispatch
            desc = tuple((f"arg{i}", repr(k))
                         for i, k in enumerate(key))
            return watch(watch_name).timed_first_dispatch(
                jitted, args, kwargs, desc=desc)
        key = _arg_key(args, kwargs)
        if key is None:         # unhashable static leaf: unwatchable
            return jitted(*args, **kwargs)
        compiled = cache.get(key)
        if compiled is None:
            if key in cache:    # AOT failed earlier for this signature
                return jitted(*args, **kwargs)
            w = watch(watch_name)
            compiled = w.aot_compile(jitted, args, kwargs,
                                     desc=_key_desc(key))
            cache[key] = compiled
            if compiled is None:
                return jitted(*args, **kwargs)
        try:
            from . import perf as _perf

            t0 = time.perf_counter()
            out = compiled(*args, **kwargs)
            _perf.note_dispatch(watch_name, compiled, out, t0)
            return out
        except AOT_MISMATCH_ERRORS:
            # aval drift the key cannot see (weak->strong type, a
            # sharding change): plain jit retraces transparently — stop
            # AOT-ing this signature rather than crash
            cache[key] = None
            return jitted(*args, **kwargs)

    wrapper._watch_name = watch_name
    wrapper._jitted = jitted
    return wrapper


# ---------------------------------------------------------------------------
# device-memory accounting
# ---------------------------------------------------------------------------
_mem_seq = itertools.count()
#: per-registry throttle clocks — one hot sampler (the serving wave into
#: the default registry) must not starve another registry's gauges.
#: Weak keys: a GC'd registry must neither leak its entry nor bequeath
#: its clock to a new registry reusing the same address (id() would)
_mem_last: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
#: sampler high-water of bytes_in_use, for backends that report no peak
_mem_peak = 0


def sample_device_memory(registry=None, device=None, min_interval=0.0):
    """Publish live-bytes/peak gauges from ``device.memory_stats`` and
    ``jax.live_arrays()`` — metadata walks only, no device sync. Called
    per hapi train step and per serving wave; returns the sampled dict,
    or None under ``PADDLE_TPU_METRICS=0`` (nothing touched).

    ``min_interval`` (seconds) throttles the live-array walk: hot call
    sites (a decode step per token) pass ~1s so the O(live arrays)
    enumeration never rides the latency path; a throttled call returns
    None without touching anything. The first call per registry always
    samples, and the throttle is per registry."""
    if not enabled():
        return None
    global _mem_peak
    reg = registry if registry is not None else om.default_registry()
    if min_interval:
        now = time.monotonic()
        if now - _mem_last.get(reg, -float(min_interval)) \
                < min_interval:
            return None
        _mem_last[reg] = now
    import jax

    from .. import device as device_mod

    live = jax.live_arrays()
    # hand the walked list to memory_stats: its CPU fallback sums live
    # arrays too, and the sampler must not pay the enumeration twice
    stats = device_mod.memory_stats(device, live_arrays=live)
    in_use = int(stats.get("bytes_in_use", 0))
    live_bytes = sum(int(x.nbytes) for x in live)
    sample = {
        "bytes_in_use": in_use,
        "live_array_bytes": live_bytes,
        "live_array_count": len(live),
        "source": stats.get("source", "allocator"),
        "sample_seq": next(_mem_seq),
    }
    reg.gauge("paddle_tpu_device_bytes_in_use",
              "allocator bytes in use on the default device").set(in_use)
    reg.gauge("paddle_tpu_live_array_bytes",
              "total bytes of live jax arrays in this process") \
        .set(live_bytes)
    reg.gauge("paddle_tpu_live_array_count",
              "live jax arrays in this process").set(len(live))
    peak = stats.get("peak_bytes_in_use")
    if peak is None:
        # no allocator peak (CPU / tunneled backends): the sampler's own
        # high-water — derived from the stats already fetched, not a
        # second memory_stats() walk
        _mem_peak = max(_mem_peak, in_use)
        peak = _mem_peak
    sample["peak_bytes_in_use"] = int(peak)
    reg.gauge("paddle_tpu_device_peak_bytes_in_use",
              "allocator peak bytes in use (sampler high-water when the "
              "backend does not report a peak)").set(int(peak))
    limit = stats.get("bytes_limit")
    if limit is not None:
        sample["bytes_limit"] = int(limit)
        reg.gauge("paddle_tpu_device_bytes_limit",
                  "allocator byte limit reported by the backend") \
            .set(int(limit))
    return sample
