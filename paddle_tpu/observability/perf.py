"""Performance attribution: per-callable roofline gauges, an EWMA perf
sentinel, and on-demand profiler capture.

The compile watcher already holds every AOT executable plus its static
``cost_analysis`` (FLOPs, bytes accessed). This module pairs that with
*measured* per-dispatch device time to answer "where does device time
go, and is this callable near its roofline?":

- every watched dispatch (``StaticFunction._dispatch``, ``watched_jit``)
  pays one cheap host-side timer and feeds :func:`note_dispatch`;
- on a per-callable throttle (``PADDLE_TPU_PERF_FENCE_INTERVAL``
  seconds, default 0.5; ``0`` fences every call) the timed window is
  extended through ``jax.block_until_ready`` — a *true* device-time
  sample, since an unfenced dispatch returns at enqueue;
- each fenced sample publishes the roofline gauges against the
  per-platform peak table (:data:`PEAKS`, env-overridable):
  ``paddle_tpu_perf_device_ms{callable}``,
  ``paddle_tpu_perf_attained_flops_frac{callable}`` (measured FLOP/s as
  a fraction of peak — MFU per callable) and
  ``paddle_tpu_perf_attained_hbm_bw_frac{callable}`` (attained HBM
  bandwidth fraction);
- an EWMA perf sentinel per callable (fast vs slow EWMA of fenced
  device time) counts sustained regressions — e.g. a recompile-storm
  slowdown — on ``paddle_tpu_perf_regressions_total{callable}`` and
  flight-records a diagnosis bundle (rate-limited).

Everything obeys ``PADDLE_TPU_METRICS=0`` (the watched dispatch paths
never reach this module then); ``PADDLE_TPU_PERF=0`` turns off just the
attribution layer while the rest of observability stays on.

:func:`capture_local` is the per-process half of cluster-wide on-demand
profiler capture (``/debug/profile?seconds=N`` /
``ServingCluster.capture_profile``): it runs a ``jax.profiler`` trace
over a window while the caller keeps serving, harvests any chrome-trace
events the device profiler wrote, and returns a span-shard document the
PR-17 merge machinery (:func:`~.tracing.merge_shards`) aligns into one
Perfetto-loadable bundle.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import shutil
import tempfile
import threading
import time

from . import metrics as _om
from .metrics import enabled as _metrics_enabled

__all__ = [
    "PEAKS", "enabled", "device_peaks", "note_dispatch", "observe",
    "recorders", "reset", "build_info", "ensure_build_info",
    "capture_local", "capture_bundle",
]

#: (peak FLOP/s, peak HBM bytes/s) per chip by device kind — the bf16
#: MXU peak (matching ``bench.py``'s MFU denominator) and the published
#: HBM bandwidth. CPU gets a nominal entry so the roofline fractions
#: stay meaningful (tiny) rather than absent in smoke runs.
PEAKS = {
    "TPU v2": (46e12, 700e9),
    "TPU v3": (123e12, 900e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e": (197e12, 819e9),
    "TPU v5": (459e12, 2765e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
    "TPU v6e": (918e12, 1640e9),
    "cpu": (1e12, 50e9),
}

#: fallbacks for an unknown TPU kind / non-TPU accelerator
_DEFAULT_TPU_PEAKS = (197e12, 819e9)
_DEFAULT_PEAKS = (1e12, 50e9)

#: EWMA smoothing: fast tracks the last few fenced samples, slow is the
#: baseline the sentinel compares against
_ALPHA_FAST = 0.5
_ALPHA_SLOW = 0.05
#: fenced samples before the sentinel arms (the slow EWMA must have a
#: baseline before a ratio test means anything)
_SENTINEL_MIN = 8
#: seconds between flight-recorder dumps per callable (the counter
#: still ticks every sustained regression)
_DUMP_INTERVAL = 60.0


def enabled():
    """Attribution is on when metrics are on, unless ``PADDLE_TPU_PERF=0``
    (checked per call so tests/benches can toggle the environment)."""
    return (_metrics_enabled()
            and os.environ.get("PADDLE_TPU_PERF", "1") != "0")


def _fence_interval():
    raw = os.environ.get("PADDLE_TPU_PERF_FENCE_INTERVAL")
    if not raw:
        return 0.5
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 0.5


def _sentinel_ratio():
    raw = os.environ.get("PADDLE_TPU_PERF_SENTINEL_RATIO")
    try:
        return float(raw) if raw else 1.5
    except ValueError:
        return 1.5


def _sentinel_k():
    raw = os.environ.get("PADDLE_TPU_PERF_SENTINEL_K")
    try:
        return max(1, int(raw)) if raw else 4
    except ValueError:
        return 4


# ---------------------------------------------------------------------------
# peak table
# ---------------------------------------------------------------------------
_peaks_lock = threading.Lock()
_peaks_cache = None


def device_peaks():
    """``(peak_flops_per_s, peak_hbm_bytes_per_s, device_kind)`` for the
    default device, from :data:`PEAKS`. ``PADDLE_TPU_PEAK_FLOPS``
    (FLOP/s) and ``PADDLE_TPU_PEAK_HBM_GBS`` (GB/s) override per entry —
    how an operator corrects the table for a new chip without a code
    change. Cached after the first (device-touching) call."""
    global _peaks_cache
    with _peaks_lock:
        if _peaks_cache is None:
            kind = "unknown"
            flops, bw = _DEFAULT_PEAKS
            try:
                import jax

                d = jax.devices()[0]
                kind = getattr(d, "device_kind", None) or d.platform
                if kind in PEAKS:
                    flops, bw = PEAKS[kind]
                elif d.platform == "tpu":
                    flops, bw = _DEFAULT_TPU_PEAKS
            except Exception:
                pass
            env_flops = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
            env_bw = os.environ.get("PADDLE_TPU_PEAK_HBM_GBS")
            try:
                if env_flops:
                    flops = float(env_flops)
                if env_bw:
                    bw = float(env_bw) * 1e9
            except ValueError:
                pass
            _peaks_cache = (flops, bw, str(kind))
        return _peaks_cache


# ---------------------------------------------------------------------------
# per-callable state
# ---------------------------------------------------------------------------
def _perf_metrics():
    return {
        "host_ms": _om.gauge(
            "paddle_tpu_perf_host_ms",
            "EWMA host-side dispatch wall time per watched callable "
            "(returns at enqueue — NOT device time; see "
            "paddle_tpu_perf_device_ms)", labelnames=("callable",)),
        "device_ms": _om.gauge(
            "paddle_tpu_perf_device_ms",
            "EWMA device time per watched callable from block_until_"
            "ready-fenced samples", labelnames=("callable",)),
        "flops_frac": _om.gauge(
            "paddle_tpu_perf_attained_flops_frac",
            "measured FLOP/s of the callable as a fraction of the "
            "device's peak (per-callable MFU; static cost_analysis "
            "FLOPs over fenced device time)", labelnames=("callable",)),
        "hbm_frac": _om.gauge(
            "paddle_tpu_perf_attained_hbm_bw_frac",
            "attained HBM bandwidth of the callable as a fraction of "
            "the device's peak (static bytes-accessed over fenced "
            "device time)", labelnames=("callable",)),
        "fenced": _om.counter(
            "paddle_tpu_perf_fenced_samples_total",
            "block_until_ready-fenced device-time samples taken",
            labelnames=("callable",)),
        "regressions": _om.counter(
            "paddle_tpu_perf_regressions_total",
            "sustained perf regressions the EWMA sentinel detected "
            "(fast EWMA above ratio x slow EWMA for K consecutive "
            "fenced samples)", labelnames=("callable",)),
    }


class _CallableState:
    """Rolling perf state for one named callable."""

    __slots__ = ("name", "host_ewma_ms", "device_ewma_ms", "fast_ms",
                 "slow_ms", "samples", "streak", "regressions",
                 "last_fence", "last_dump", "last_flops", "last_nbytes",
                 "_lock")

    def __init__(self, name):
        self.name = str(name)
        self.host_ewma_ms = None
        self.device_ewma_ms = None
        self.fast_ms = None
        self.slow_ms = None
        self.samples = 0
        self.streak = 0
        self.regressions = 0
        self.last_fence = None
        self.last_dump = None
        self.last_flops = None
        self.last_nbytes = None
        self._lock = threading.Lock()

    # -- cheap path: every dispatch -----------------------------------
    def note_host(self, host_s, metrics):
        ms = host_s * 1e3
        with self._lock:
            prev = self.host_ewma_ms
            self.host_ewma_ms = ms if prev is None else \
                prev + _ALPHA_FAST * (ms - prev)
            val = self.host_ewma_ms
        metrics["host_ms"].labels(self.name).set(val)

    def fence_due(self, now_mono):
        """Claim the next fenced sample slot if the throttle allows
        (the claim happens BEFORE the block, so concurrent dispatch
        threads can't pile up fences)."""
        interval = _fence_interval()
        with self._lock:
            if (self.last_fence is not None
                    and now_mono - self.last_fence < interval):
                return False
            self.last_fence = now_mono
            return True

    # -- fenced sample: gauges + sentinel -----------------------------
    def observe_device(self, device_s, flops, nbytes, metrics):
        """Fold one fenced device-time sample in; publish the roofline
        gauges and run the sentinel. Returns the sample summary."""
        ratio = _sentinel_ratio()
        k = _sentinel_k()
        ms = device_s * 1e3
        regression = False
        with self._lock:
            self.samples += 1
            if flops is not None:
                self.last_flops = flops
            if nbytes is not None:
                self.last_nbytes = nbytes
            self.device_ewma_ms = ms if self.device_ewma_ms is None \
                else self.device_ewma_ms \
                + _ALPHA_FAST * (ms - self.device_ewma_ms)
            self.fast_ms = ms if self.fast_ms is None else \
                self.fast_ms + _ALPHA_FAST * (ms - self.fast_ms)
            self.slow_ms = ms if self.slow_ms is None else \
                self.slow_ms + _ALPHA_SLOW * (ms - self.slow_ms)
            if (self.samples > _SENTINEL_MIN and self.slow_ms > 0
                    and self.fast_ms > ratio * self.slow_ms):
                self.streak += 1
            else:
                self.streak = 0
            if self.streak >= k:
                # sustained: count it, re-baseline the slow EWMA on the
                # new level (one regression = one event, not an event
                # per sample until the slow EWMA catches up), reset
                regression = True
                self.regressions += 1
                self.streak = 0
                slow_before = self.slow_ms
                self.slow_ms = self.fast_ms
            device_ms = self.device_ewma_ms
            ewma_s = device_ms / 1e3
        peak_flops, peak_bw, kind = device_peaks()
        sample = {"callable": self.name, "device_ms": device_ms,
                  "device_kind": kind, "flops": flops, "bytes": nbytes,
                  "regression": regression}
        metrics["device_ms"].labels(self.name).set(device_ms)
        metrics["fenced"].labels(self.name).inc()
        if flops and flops > 0 and ewma_s > 0 and peak_flops > 0:
            frac = min(1.0, flops / (ewma_s * peak_flops))
            sample["attained_flops_frac"] = frac
            metrics["flops_frac"].labels(self.name).set(frac)
        if nbytes and nbytes > 0 and ewma_s > 0 and peak_bw > 0:
            frac = min(1.0, nbytes / (ewma_s * peak_bw))
            sample["attained_hbm_bw_frac"] = frac
            metrics["hbm_frac"].labels(self.name).set(frac)
        if regression:
            metrics["regressions"].labels(self.name).inc()
            self._flight_record(ms, slow_before, ratio, k, sample)
        return sample

    def _flight_record(self, ms, slow_before, ratio, k, sample):
        """One postmortem bundle per sustained regression, rate-limited
        per callable (the counter still ticks every event)."""
        now = time.monotonic()
        with self._lock:
            if (self.last_dump is not None
                    and now - self.last_dump < _DUMP_INTERVAL):
                return
            self.last_dump = now
        from . import flight_recorder as _fr

        try:
            _fr.dump(reason="perf_regression", info={
                "callable": self.name,
                "device_ms_last": round(ms, 3),
                "device_ms_baseline": round(slow_before, 3),
                "slowdown_x": round(ms / max(slow_before, 1e-9), 3),
                "sentinel_ratio": ratio, "sentinel_k": k,
                "sample": {kk: vv for kk, vv in sample.items()
                           if kk != "regression"},
            })
        except Exception:
            pass    # telemetry must never break the dispatch path

    def snapshot(self):
        with self._lock:
            return {"callable": self.name,
                    "host_ewma_ms": self.host_ewma_ms,
                    "device_ewma_ms": self.device_ewma_ms,
                    "fast_ms": self.fast_ms, "slow_ms": self.slow_ms,
                    "samples": self.samples, "streak": self.streak,
                    "regressions": self.regressions,
                    "flops": self.last_flops,
                    "bytes_accessed": self.last_nbytes}


_state_lock = threading.Lock()
_states: dict[str, _CallableState] = {}
_metrics_cache = None


def _metrics():
    global _metrics_cache
    if _metrics_cache is None or isinstance(
            _metrics_cache["host_ms"], _om._NullMetric):
        # rebuilt when the kill switch flips back on mid-process (tests)
        _metrics_cache = _perf_metrics()
    return _metrics_cache


def _state(name):
    with _state_lock:
        st = _states.get(name)
        if st is None:
            st = _states[name] = _CallableState(name)
        return st


def recorders():
    """``{callable: state snapshot}`` — the sentinel/roofline state per
    watched callable (diagnostics; the gauges are the stable API)."""
    with _state_lock:
        states = list(_states.values())
    return {st.name: st.snapshot() for st in states}


def reset():
    """Drop all per-callable state and caches (tests)."""
    global _peaks_cache, _metrics_cache, _build_info_cache
    with _state_lock:
        _states.clear()
    with _peaks_lock:
        _peaks_cache = None
    _metrics_cache = None
    _build_info_cache = None
    with _cost_lock:
        _cost_cache.clear()


# ---------------------------------------------------------------------------
# static-cost cache: executable -> (flops, bytes accessed)
# ---------------------------------------------------------------------------
_cost_lock = threading.Lock()
#: keyed by id(compiled) — safe because watched executables are held
#: for the life of the process by their dispatch caches (StaticFunction
#: ._aot / watched_jit's cache); bounded as a leak backstop
_cost_cache: dict[int, tuple] = {}


def _cost_for(compiled):
    key = id(compiled)
    with _cost_lock:
        hit = _cost_cache.get(key)
    if hit is not None:
        return hit
    from .compile_watch import CompileWatch

    flops, nbytes, _ = CompileWatch._analyze(compiled)
    with _cost_lock:
        if len(_cost_cache) > 4096:
            _cost_cache.clear()
        _cost_cache[key] = (flops, nbytes)
    return flops, nbytes


# ---------------------------------------------------------------------------
# the dispatch hook
# ---------------------------------------------------------------------------
def note_dispatch(name, compiled, out, t0):
    """Account one watched dispatch of ``compiled`` under ``name`` that
    started at ``time.perf_counter()`` value ``t0`` and returned
    ``out`` (still possibly in flight — dispatch is async).

    Cheap path: fold the host wall time into the per-callable EWMA.
    When the fence throttle allows, additionally ``block_until_ready``
    the outputs — extending the timed window to a true device-time
    sample — and publish the roofline gauges + run the sentinel.
    Never raises (attribution must not break a dispatch); returns the
    fenced-sample dict when one was taken, else None."""
    if not enabled():
        return None
    try:
        now = time.perf_counter()
        st = _state(name)
        m = _metrics()
        st.note_host(now - t0, m)
        if not st.fence_due(time.monotonic()):
            return None
        import jax

        jax.block_until_ready(out)
        device_s = time.perf_counter() - t0
        flops, nbytes = _cost_for(compiled)
        return st.observe_device(device_s, flops, nbytes, m)
    except Exception:
        return None


def observe(name, device_s, flops=None, bytes_accessed=None):
    """Feed one measured device-time sample for ``name`` directly —
    what the fenced dispatch path does internally; also the injection
    point for tests and external harnesses (a Pallas bench loop, a
    hand-fenced region). Returns the sample dict, or None when
    disabled."""
    if not enabled():
        return None
    return _state(name).observe_device(
        float(device_s), flops, bytes_accessed, _metrics())


# ---------------------------------------------------------------------------
# build-info gauge
# ---------------------------------------------------------------------------
_build_info_cache = None


def build_info():
    """``{"git_commit", "jax_version", "device_kind"}`` for this
    process — what a merged cluster pane needs to identify what each
    replica is running. Cached; ``PADDLE_TPU_BUILD_COMMIT`` overrides
    the git lookup (set it in images built without a .git dir)."""
    global _build_info_cache
    if _build_info_cache is not None:
        return _build_info_cache
    commit = os.environ.get("PADDLE_TPU_BUILD_COMMIT")
    if not commit:
        try:
            import subprocess

            commit = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                cwd=os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))),
                capture_output=True, text=True,
                timeout=5).stdout.strip() or "unknown"
        except Exception:
            commit = "unknown"
    try:
        import jax

        jax_version = jax.__version__
    except Exception:
        jax_version = "unknown"
    _build_info_cache = {"git_commit": commit,
                         "jax_version": jax_version,
                         "device_kind": device_peaks()[2]}
    return _build_info_cache


def ensure_build_info(registry=None):
    """Register/refresh ``paddle_tpu_build_info`` (value 1, identity in
    the labels) on ``registry`` (default registry when None) so every
    ``/metrics`` scrape and every cluster-merged pane carries it. No-op
    under ``PADDLE_TPU_METRICS=0``."""
    if not _metrics_enabled():
        return None
    reg = registry if registry is not None else _om.default_registry()
    g = reg.gauge(
        "paddle_tpu_build_info",
        "build/runtime identity (git commit, jax version, device kind "
        "as labels; value is always 1)",
        labelnames=("git_commit", "jax_version", "device_kind"))
    info = build_info()
    g.labels(info["git_commit"], info["jax_version"],
             info["device_kind"]).set(1)
    return g


# ---------------------------------------------------------------------------
# on-demand profiler capture (the per-process half)
# ---------------------------------------------------------------------------
#: device-trace events shipped per capture, bounded so a busy chip
#: can't balloon the rpc reply / HTTP body
_MAX_DEVICE_EVENTS = 20000


def _harvest_device_trace(trace_dir, base_us, pid):
    """Chrome-trace events the jax profiler wrote under ``trace_dir``
    (``plugins/profile/<run>/*.trace.json.gz``), rebased so the capture
    window starts at ``base_us`` on this process's span clock and
    stamped with this process's pid (so the cluster merge groups them
    with the process's host spans)."""
    events = []
    pattern = os.path.join(trace_dir, "plugins", "profile",
                           "*", "*.trace.json*")
    for path in sorted(glob.glob(pattern)):
        try:
            if path.endswith(".gz"):
                with gzip.open(path, "rt") as f:
                    doc = json.load(f)
            else:
                with open(path) as f:
                    doc = json.load(f)
        except Exception:
            continue
        evs = [e for e in doc.get("traceEvents", [])
               if isinstance(e, dict) and e.get("ph") != "M"
               and isinstance(e.get("ts"), (int, float))]
        if not evs:
            continue
        t_min = min(float(e["ts"]) for e in evs)
        for e in evs:
            e = dict(e)
            e["ts"] = float(e["ts"]) - t_min + base_us
            e["pid"] = pid
            events.append(e)
    events.sort(key=lambda e: e["ts"])
    return events[:_MAX_DEVICE_EVENTS]


def capture_local(seconds, worker_name=None):
    """One on-demand profile window in THIS process: start a
    ``jax.profiler`` trace, let the caller's workload run for
    ``seconds``, stop, and return a span-shard document (worker / pid /
    epoch_unix / events — see :func:`~.tracing.local_shard`) whose
    events are the process's host spans plus any device-trace events
    the profiler produced, ready for :func:`~.tracing.merge_shards`.

    Blocks the calling thread for the window (serving/training threads
    keep running); returns an empty shard under
    ``PADDLE_TPU_METRICS=0`` (profiler never started, no files)."""
    from . import trace as _trace
    from . import tracing as _tracing

    name = worker_name or f"pid{os.getpid()}"
    if not _metrics_enabled():
        return {"worker": str(name), "pid": os.getpid(),
                "epoch_unix": _trace.epoch_unix(), "events": [],
                "profiler": {"ok": False, "reason": "metrics disabled"}}
    seconds = max(0.0, float(seconds))
    tmp = tempfile.mkdtemp(prefix="paddle_tpu_profile_")
    profiler_ok = False
    t0 = time.perf_counter()
    try:
        import jax

        jax.profiler.start_trace(tmp)
        profiler_ok = True
    except Exception:
        pass
    time.sleep(seconds)
    if profiler_ok:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            profiler_ok = False
    shard = _tracing.local_shard(name)
    device_events = []
    if profiler_ok:
        # window start on this process's span clock: device events sit
        # where the capture actually happened relative to host spans
        base_us = (t0 - _trace._EPOCH) * 1e6
        device_events = _harvest_device_trace(tmp, base_us,
                                              os.getpid())
    shutil.rmtree(tmp, ignore_errors=True)
    shard["events"] = shard["events"] + device_events
    shard["profiler"] = {"ok": profiler_ok, "seconds": seconds,
                         "device_events": len(device_events)}
    return shard


def capture_bundle(seconds, worker_name=None):
    """Single-process convenience over :func:`capture_local`: the
    merged Perfetto-loadable document (what the local ``/debug/profile``
    route serves when no cluster is behind it). None under
    ``PADDLE_TPU_METRICS=0``."""
    if not _metrics_enabled():
        return None
    from . import tracing as _tracing

    shard = capture_local(seconds, worker_name=worker_name)
    merged = _tracing.merge_shards([shard])
    merged["capture"] = {"seconds": float(seconds),
                         "workers": [shard.get("worker")],
                         "pids": [shard.get("pid")],
                         "profiler": [shard.get("profiler")]}
    return merged
