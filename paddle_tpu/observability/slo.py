"""SLO burn-rate engine: multi-window TTFT/TPOT burn rates from
cumulative histogram snapshots.

The autoscaler-ready cluster signal (ROADMAP item 6): given a latency
SLO ("99% of first tokens within 0.5 s"), the *burn rate* over a window
is how fast the error budget is being spent — ``bad_fraction /
(1 - objective)``. Burn rate 1.0 means the budget is being consumed
exactly at the sustainable pace; 10x+ over a short window is the page,
1x+ over a long window is the slow leak (the standard multi-window
multi-burn alerting shape).

:class:`SloEngine` is fed *cumulative* histogram snapshots (bucket
counts as scraped — exactly what ``ServingCluster.scrape()`` merges
from the replicas, or a local registry's histogram) and keeps a small
time-indexed ring per SLO so each window's burn rate is computed from
the *delta* of observations inside that window: ``bad = observations
above the threshold bucket``, ``burn = (bad/total) / (1 - objective)``.
A window with no observations reports burn 0.0 (no traffic burns no
budget).

Results surface as the ``serving_slo_burn_rate{slo,window}`` gauge and
on ``ServingCluster.membership_info()``. Obeys the standard
``PADDLE_TPU_METRICS=0`` kill switch.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque

from . import metrics as _om
from .metrics import enabled

__all__ = ["SloSpec", "SloEngine", "DEFAULT_WINDOWS",
           "histogram_quantile"]

#: multi-window shape: fast page / mid alert / slow leak (seconds)
DEFAULT_WINDOWS = (60.0, 300.0, 1800.0)


class SloSpec:
    """One latency SLO: ``objective`` of observations of histogram
    ``metric`` must land at or under ``threshold`` seconds."""

    __slots__ = ("name", "metric", "threshold", "objective")

    def __init__(self, name, metric, threshold, objective=0.99):
        self.name = str(name)
        self.metric = str(metric)
        self.threshold = float(threshold)
        if not 0.0 < float(objective) < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {objective}")
        self.objective = float(objective)

    def __repr__(self):
        return (f"SloSpec({self.name!r}, metric={self.metric!r}, "
                f"threshold={self.threshold}, "
                f"objective={self.objective})")


def default_slos(ttft=0.5, tpot=0.1, objective=0.99):
    """The serving pair: TTFT against ``serving_ttft_seconds``, TPOT
    against ``serving_token_latency_seconds``."""
    return (SloSpec("ttft", "serving_ttft_seconds", ttft, objective),
            SloSpec("tpot", "serving_token_latency_seconds", tpot,
                    objective))


def _split_counts(buckets, counts, threshold):
    """(good, bad) observation counts for one cumulative-bucket
    snapshot: ``bad`` = observations in buckets whose upper bound
    exceeds ``threshold`` (the +Inf bucket is always bad unless the
    threshold is infinite). Bucket granularity bounds the error — a
    threshold inside a bucket counts that whole bucket as good."""
    buckets = list(buckets)
    # rightmost bucket bound <= threshold is still "good"
    k = bisect.bisect_right(buckets, float(threshold))
    good = sum(counts[:k])
    bad = sum(counts[k:])
    return good, bad


def histogram_quantile(buckets, counts, q):
    """Prometheus-style quantile estimate from one histogram snapshot:
    finite bucket upper bounds ``buckets`` plus per-bucket
    (non-cumulative) ``counts`` with the +Inf bucket last (``len(counts)
    == len(buckets) + 1``). Works on raw snapshots and equally on the
    *delta* of two cumulative snapshots — the window shape the burn-rate
    ring keeps.

    Linear interpolation inside the landing bucket (lower bound 0.0 for
    the first bucket — the latency domain is non-negative); a quantile
    landing in the +Inf bucket clamps to the highest finite bound, as
    Prometheus does. Returns None when there are no observations or any
    count is negative (a counter reset between the two snapshots of a
    delta)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    buckets = [float(b) for b in buckets]
    counts = [float(c) for c in counts]
    if len(counts) != len(buckets) + 1:
        raise ValueError(
            f"need len(buckets)+1 counts (+Inf last), got "
            f"{len(counts)} counts for {len(buckets)} buckets")
    total = sum(counts)
    if total <= 0 or any(c < 0 for c in counts):
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        prev = cum
        cum += c
        if cum >= rank and c > 0:
            if i >= len(buckets):       # +Inf bucket: clamp
                return buckets[-1] if buckets else None
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            return lo + (hi - lo) * max(0.0, rank - prev) / c
    return buckets[-1] if buckets else None


class SloEngine:
    """Burn-rate computation over periodic cumulative snapshots.

    Feed it with :meth:`observe` (one call per SLO per scrape tick,
    cumulative bucket counts); read :meth:`burn_rates`. Ticks land in a
    bounded ring sized to the longest window, so memory stays O(windows
    / tick interval)."""

    def __init__(self, slos=None, windows=DEFAULT_WINDOWS,
                 max_points=512, registry=None):
        self.slos = tuple(slos if slos is not None else default_slos())
        self.windows = tuple(sorted(float(w) for w in windows))
        if not self.windows:
            raise ValueError("at least one window required")
        self._points = {s.name: deque(maxlen=int(max_points))
                        for s in self.slos}
        self._lock = threading.Lock()
        reg = registry if registry is not None else _om.default_registry()
        self._gauge = reg.gauge(
            "serving_slo_burn_rate",
            "error-budget burn rate per SLO and window (1.0 = budget "
            "spent exactly at the sustainable pace)",
            labelnames=("slo", "window"))

    def spec(self, name):
        for s in self.slos:
            if s.name == name:
                return s
        raise KeyError(name)

    def observe(self, slo_name, buckets, counts, now=None):
        """Record one cumulative snapshot for ``slo_name``: the
        histogram's bucket bounds + per-bucket (non-cumulative) counts
        as scraped. No-op under ``PADDLE_TPU_METRICS=0``."""
        if not enabled():
            return
        spec = self.spec(slo_name)
        good, bad = _split_counts(buckets, counts, spec.threshold)
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            self._points[spec.name].append((t, good + bad, bad))

    def observe_histogram(self, slo_name, hist, now=None):
        """Convenience: snapshot a live
        :class:`~paddle_tpu.observability.metrics.Histogram` leaf."""
        counts, _ = hist.snapshot()
        self.observe(slo_name, hist.buckets, counts, now=now)

    def _window_burn(self, spec, points, window, now):
        """Burn over [now - window, now] from the cumulative points."""
        if not points:
            return 0.0
        cutoff = now - window
        # baseline: the newest point at or before the cutoff; if every
        # point is inside the window, delta from zero (the ring covers
        # the whole history we have)
        base_total = base_bad = 0
        end_total = end_bad = None
        for t, total, bad in points:
            if t <= cutoff:
                base_total, base_bad = total, bad
            end_total, end_bad = total, bad
        d_total = end_total - base_total
        d_bad = end_bad - base_bad
        if d_total <= 0 or d_bad < 0:
            # no traffic in the window (or a counter reset behind us —
            # a replica restart zeroes its histograms): report no burn
            # rather than a negative/undefined rate
            return 0.0
        budget = 1.0 - spec.objective
        return (d_bad / d_total) / budget

    def burn_rates(self, now=None):
        """``{slo: {window_label: burn}}`` over every configured
        window, and publish each value on
        ``serving_slo_burn_rate{slo,window}``. Window labels are
        humanized seconds (``"60s"``, ``"300s"``, ...)."""
        t = time.monotonic() if now is None else float(now)
        out = {}
        with self._lock:
            snap = {name: list(pts) for name, pts in self._points.items()}
        for spec in self.slos:
            per = {}
            for w in self.windows:
                label = f"{int(w) if w == int(w) else w}s"
                burn = self._window_burn(spec, snap[spec.name], w, t)
                per[label] = round(burn, 6)
                self._gauge.labels(spec.name, label).set(per[label])
            out[spec.name] = per
        return out
