"""``paddle_tpu.observability`` — unified runtime metrics + tracing.

The measurement substrate for the serving engine, elastic launcher, and
training loop: a thread-safe metric registry (`metrics`), a host-span
tracer with chrome-trace export (`trace`), distributed trace-context
propagation + cross-process trace merging (`tracing`), Prometheus/
JSON/HTTP exporters (`export`), the XLA compile watcher +
device-memory gauges (`compile_watch`), the crash flight recorder
(`flight_recorder`), the SLO burn-rate engine (`slo`), and the perf
attribution layer — roofline gauges, the EWMA perf sentinel, and
on-demand profiler capture (`perf`).
``PADDLE_TPU_METRICS=0`` turns the whole layer into no-ops. See README
"Observability" for the standard metric names.
"""

from . import (  # noqa: F401
    compile_watch, export, flight_recorder, metrics, perf, slo, trace,
    tracing,
)
from .compile_watch import (  # noqa: F401
    sample_device_memory, watch, watched_jit,
)
from .export import (  # noqa: F401
    json_snapshot, prometheus_text, snapshot_to_prometheus,
    start_http_server,
)
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, counter, default_registry,
    enabled, gauge, histogram,
)
from .perf import (  # noqa: F401
    build_info, capture_bundle, capture_local, device_peaks,
    ensure_build_info,
)
from .slo import SloEngine, SloSpec, histogram_quantile  # noqa: F401
from .trace import export_chrome_trace, span  # noqa: F401
from .tracing import (  # noqa: F401
    TraceContext, activate, adopt, current, format_traceparent,
    parse_traceparent,
)

__all__ = [
    "metrics", "trace", "tracing", "export", "compile_watch",
    "flight_recorder", "slo", "perf",
    "TraceContext", "current", "activate", "adopt",
    "parse_traceparent", "format_traceparent",
    "SloEngine", "SloSpec", "histogram_quantile",
    "device_peaks", "build_info", "ensure_build_info",
    "capture_local", "capture_bundle",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "default_registry", "enabled",
    "span", "export_chrome_trace",
    "prometheus_text", "json_snapshot", "snapshot_to_prometheus",
    "start_http_server",
    "watch", "watched_jit", "sample_device_memory",
]
