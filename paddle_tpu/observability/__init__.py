"""``paddle_tpu.observability`` — unified runtime metrics + tracing.

The measurement substrate for the serving engine, elastic launcher, and
training loop: a thread-safe metric registry (`metrics`), a host-span
tracer with chrome-trace export (`trace`), Prometheus/JSON/HTTP
exporters (`export`), the XLA compile watcher + device-memory gauges
(`compile_watch`), and the crash flight recorder (`flight_recorder`).
``PADDLE_TPU_METRICS=0`` turns the whole layer into no-ops. See README
"Observability" for the standard metric names.
"""

from . import (  # noqa: F401
    compile_watch, export, flight_recorder, metrics, trace,
)
from .compile_watch import (  # noqa: F401
    sample_device_memory, watch, watched_jit,
)
from .export import (  # noqa: F401
    json_snapshot, prometheus_text, snapshot_to_prometheus,
    start_http_server,
)
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, counter, default_registry,
    enabled, gauge, histogram,
)
from .trace import export_chrome_trace, span  # noqa: F401

__all__ = [
    "metrics", "trace", "export", "compile_watch", "flight_recorder",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "default_registry", "enabled",
    "span", "export_chrome_trace",
    "prometheus_text", "json_snapshot", "snapshot_to_prometheus",
    "start_http_server",
    "watch", "watched_jit", "sample_device_memory",
]
