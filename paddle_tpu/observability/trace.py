"""Structured host-span tracing: a ring buffer + chrome-trace export.

``span(name)`` is a context manager AND a decorator that records a
wall-time host span (complete event) into a bounded ring buffer — cheap
enough for scheduler/launcher hot paths where the XLA device tracer
(`paddle_tpu.profiler`) is too heavy. Export writes chrome-trace JSON
under the same ``<log_dir>/plugins/profile/<run>/`` layout the profiler
uses, so TensorBoard's profile plugin and Perfetto load host spans next
to device traces.

Tracing obeys the same kill switch as metrics: ``PADDLE_TPU_METRICS=0``
makes ``span`` a no-op and records nothing.
"""

from __future__ import annotations

import functools
import itertools
import json
import os
import threading
import time
from collections import deque

from . import tracing as _tracing
from .metrics import enabled

__all__ = ["span", "TraceBuffer", "default_buffer", "get_events", "clear",
           "export_chrome_trace", "unique_run_name", "epoch_unix"]

#: process epoch — span timestamps are microseconds since this point.
#: Spans are stamped off the MONOTONIC clock (an NTP step mid-run must
#: not make a trace jump backwards); ``_EPOCH_UNIX`` records where that
#: monotonic epoch sits on the shared unix clock — the offset the
#: cross-process merge (`tracing.merge_shards`) aligns shards on.
_EPOCH = time.perf_counter()
_EPOCH_UNIX = time.time() - (time.perf_counter() - _EPOCH)


def epoch_unix():
    """Unix time (seconds) at which this process's span clock reads 0 —
    the recorded monotonic<->epoch clock offset."""
    return _EPOCH_UNIX


class TraceBuffer:
    """Bounded, thread-safe ring of chrome-trace events (oldest spans
    fall off the back once ``capacity`` is reached)."""

    def __init__(self, capacity=4096):
        self._events = deque(maxlen=int(capacity))
        self._lock = threading.Lock()

    def add(self, event):
        with self._lock:
            self._events.append(event)

    def events(self):
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        with self._lock:
            return len(self._events)


_default_buffer = TraceBuffer()


def default_buffer():
    return _default_buffer


def get_events():
    return _default_buffer.events()


def clear():
    _default_buffer.clear()


class span:
    """Record a named host span.

    Context manager::

        with span("serving.prefill", batch=4):
            ...

    Decorator (a fresh span per call)::

        @span("engine.step")
        def step(...): ...

    When a distributed :class:`~.tracing.TraceContext` is active (see
    ``tracing.activate``), the span becomes a node of that trace: it
    mints a child context for its own duration (so nested spans chain
    to it) and records ``trace_id`` / ``span_id`` / ``parent_id`` in
    its args. ``trace_ctx=`` installs a pre-allocated context verbatim
    instead — how rpc records its call span under the exact identity
    the envelope carried across the process boundary.
    """

    __slots__ = ("name", "args", "buffer", "_t0", "_trace_ctx_in",
                 "_trace_ctx", "_trace_token")

    def __init__(self, name, buffer=None, trace_ctx=None, **args):
        self.name = name
        self.args = args or None
        self.buffer = buffer
        self._t0 = None
        self._trace_ctx_in = trace_ctx
        self._trace_ctx = None
        self._trace_token = None

    def __enter__(self):
        if enabled():
            self._trace_ctx, self._trace_token = \
                _tracing._enter_span(self._trace_ctx_in)
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t0 = self._t0
        if t0 is None:
            return False
        now = time.perf_counter()
        event = {
            "name": self.name,
            "ph": "X",
            "ts": (t0 - _EPOCH) * 1e6,
            "dur": (now - t0) * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        ctx = self._trace_ctx
        if ctx is not None:
            event["args"] = dict(self.args or ())
            event["args"].update(ctx.to_wire())
            _tracing._exit_span(self._trace_token)
            self._trace_ctx = None
            self._trace_token = None
        elif self.args:
            event["args"] = dict(self.args)
        # explicit None-check: an empty TraceBuffer is falsy (__len__)
        buf = self.buffer if self.buffer is not None else _default_buffer
        buf.add(event)
        self._t0 = None
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with span(self.name, buffer=self.buffer, **(self.args or {})):
                return fn(*a, **kw)

        return wrapper


#: per-process run sequence: two runs within one strftime second must
#: not collide on the run dir and silently overwrite each other
_RUN_SEQ = itertools.count()


def unique_run_name():
    """Collision-proof run-directory name: wall-clock timestamp plus a
    pid + per-process monotonic suffix (shared by chrome-trace exports
    and flight-recorder bundles)."""
    return (f"{time.strftime('%Y_%m_%d_%H_%M_%S')}"
            f"_pid{os.getpid()}_{next(_RUN_SEQ)}")


def export_chrome_trace(dir_name, worker_name=None, buffer=None):
    """Write buffered spans as chrome-trace JSON into the profiler's
    output layout: ``<dir_name>/plugins/profile/<run>/<worker>.
    host_spans.trace.json``. Returns the written path."""
    # explicit None-check: an empty TraceBuffer is falsy (__len__)
    buf = buffer if buffer is not None else _default_buffer
    run = unique_run_name()
    out_dir = os.path.join(dir_name, "plugins", "profile", run)
    os.makedirs(out_dir, exist_ok=True)
    worker = worker_name or f"host_{os.getpid()}"
    path = os.path.join(out_dir, f"{worker}.host_spans.trace.json")
    with open(path, "w") as f:
        json.dump({"traceEvents": buf.events(),
                   "displayTimeUnit": "ms",
                   # where this process's span clock (ts=0) sits on the
                   # unix clock — lets offline tooling align single-
                   # process exports the same way the cluster collector
                   # aligns shards
                   "metadata": {"epoch_unix": _EPOCH_UNIX,
                                "pid": os.getpid()}}, f)
    return path
