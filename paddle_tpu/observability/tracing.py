"""Cross-process trace-context propagation + multi-process trace merge.

The PR-1 span tracer (`trace.py`) is strictly per-process: every event
lands in this process's ring with this process's monotonic clock. Since
the serving stack became a multi-process cluster (HTTP front door ->
router -> rpc -> subprocess replica workers), one slow request's time is
smeared invisibly across three processes. This module is the glue that
makes it ONE timeline:

- :class:`TraceContext` — W3C-trace-context-shaped identity
  (``trace_id`` / ``span_id`` / ``parent_id``), carried in a
  ``contextvars.ContextVar`` so nested :class:`~.trace.span`\\ s link
  into a parent-chained tree automatically. Minted at the HTTP front
  door (or adopted from an incoming ``traceparent`` header), injected
  into rpc envelopes by ``distributed.rpc``, restored in dispatcher
  handlers.
- Span shards — each worker periodically flushes its span ring to one
  bounded, atomically-replaced JSON file under the shared log dir
  (``trace_shards/<worker>.trace.json``), stamped with the worker's
  monotonic<->epoch clock offset.
- :func:`merge_shards` — the collector's alignment step: shifts every
  shard's monotonic timestamps onto one common base using the recorded
  offsets and emits a single Perfetto/chrome-trace-loadable document.
- :func:`span_tree` — one request's spans (by ``trace_id``) as a
  parent-nested JSON tree, what ``GET /v1/requests/<id>/trace`` serves.

Everything obeys the PR-1 kill switch: under ``PADDLE_TPU_METRICS=0``
:func:`mint` / :func:`adopt` / :func:`inject` return ``None``, no shard
file is ever written, and rpc envelopes stay byte-for-byte on the
pre-trace path.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading

from .metrics import enabled

__all__ = ["TraceContext", "current", "mint", "adopt", "activate",
           "inject", "extract", "parse_traceparent", "format_traceparent",
           "write_span_shard", "harvest_shards", "local_shard",
           "merge_shards", "span_tree", "record_clock_handshake",
           "read_clock_handshakes", "SHARD_DIR"]

#: subdirectory of a cluster log dir where workers flush span shards
SHARD_DIR = "trace_shards"

_HEX = set("0123456789abcdef")

_current: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_trace_context", default=None)


def _new_trace_id():
    return os.urandom(16).hex()


def _new_span_id():
    return os.urandom(8).hex()


class TraceContext:
    """One node of a distributed trace: which trace this work belongs
    to (``trace_id``), which span it is (``span_id``) and which span
    caused it (``parent_id``, ``None`` at the root)."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id, span_id=None, parent_id=None):
        self.trace_id = trace_id
        self.span_id = span_id or _new_span_id()
        self.parent_id = parent_id

    def child(self):
        """A fresh span under this one (same trace, new span id)."""
        return TraceContext(self.trace_id, _new_span_id(), self.span_id)

    def to_wire(self):
        """Compact dict for rpc envelopes (consumed by :func:`extract`)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id}

    def __repr__(self):
        return (f"TraceContext(trace={self.trace_id}, "
                f"span={self.span_id}, parent={self.parent_id})")


# ---------------------------------------------------------------------------
# contextvar plumbing
# ---------------------------------------------------------------------------
def current():
    """The active :class:`TraceContext`, or ``None`` (also ``None``
    whenever metrics are disabled — the kill switch wins even over an
    explicitly activated context)."""
    if not enabled():
        return None
    return _current.get()


def mint():
    """A brand-new root context (``None`` under the kill switch)."""
    if not enabled():
        return None
    return TraceContext(_new_trace_id())


def parse_traceparent(header):
    """Parse a W3C ``traceparent`` header
    (``version-traceid-spanid-flags``). Returns a :class:`TraceContext`
    whose ``span_id`` is the CALLER's span (i.e. our parent), or
    ``None`` on anything malformed — an invalid header must start a
    fresh trace, never crash a request."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(version) != 2 or not set(version) <= _HEX or version == "ff":
        return None
    if len(trace_id) != 32 or not set(trace_id) <= _HEX \
            or trace_id == "0" * 32:
        return None
    if len(span_id) != 16 or not set(span_id) <= _HEX \
            or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id)


def format_traceparent(ctx):
    """Render a context as an outgoing ``traceparent`` header."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def adopt(traceparent=None):
    """The front-door entry point: continue the caller's trace when a
    valid ``traceparent`` header arrives (our root span becomes a child
    of the remote span), else mint a fresh root. ``None`` under the
    kill switch."""
    if not enabled():
        return None
    remote = parse_traceparent(traceparent)
    if remote is not None:
        return remote.child()
    return TraceContext(_new_trace_id())


@contextlib.contextmanager
def activate(ctx):
    """Make ``ctx`` the current context for the ``with`` body (no-op
    for ``ctx=None``, so call sites don't need their own branching)."""
    if ctx is None:
        yield None
        return
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def inject():
    """Wire fields for an rpc envelope: the current context's
    :meth:`~TraceContext.to_wire` dict, or ``None`` when there is
    nothing to propagate (no active trace, or kill switch) — ``None``
    means the envelope must stay on the pre-trace byte layout."""
    ctx = current()
    return None if ctx is None else ctx.to_wire()


def extract(wire):
    """Rebuild a context from envelope wire fields; tolerant of
    ``None``, foreign, or partial dicts (missing keys degrade to a
    fresh id rather than KeyError-ing the dispatcher)."""
    if not wire or not isinstance(wire, dict) or not enabled():
        return None
    trace_id = wire.get("trace_id")
    if not trace_id:
        return None
    return TraceContext(trace_id, wire.get("span_id"),
                        wire.get("parent_id"))


# used by trace.span: mint a child of the ambient context (if any) for
# the span being opened, or install a caller-provided context verbatim
def _enter_span(explicit=None):
    if explicit is not None:
        return explicit, _current.set(explicit)
    ctx = _current.get()
    if ctx is None:
        return None, None
    child = ctx.child()
    return child, _current.set(child)


def _exit_span(token):
    if token is not None:
        _current.reset(token)


# ---------------------------------------------------------------------------
# span shards: per-worker bounded files the cluster collector harvests
# ---------------------------------------------------------------------------
_shard_lock = threading.Lock()


def local_shard(worker_name):
    """This process's span ring as a shard document (what a worker
    writes to disk, and what the collector uses for its OWN process
    without a file round-trip)."""
    from . import trace as _trace

    return {"worker": str(worker_name), "pid": os.getpid(),
            "epoch_unix": _trace.epoch_unix(),
            "events": _trace.get_events()}


def write_span_shard(dir_name, worker_name, buffer=None):
    """Flush this process's spans to
    ``<dir_name>/trace_shards/<worker>.trace.json`` (atomic replace —
    a collector never reads a torn file; repeated flushes overwrite, so
    disk usage stays bounded by the ring capacity). Returns the path,
    or ``None`` under ``PADDLE_TPU_METRICS=0`` (no file is created)."""
    if not enabled():
        return None
    from . import trace as _trace

    doc = local_shard(worker_name)
    if buffer is not None:
        doc["events"] = buffer.events()
    del _trace  # only needed transitively via local_shard
    out_dir = os.path.join(str(dir_name), SHARD_DIR)
    path = os.path.join(out_dir, f"{worker_name}.trace.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with _shard_lock:
        os.makedirs(out_dir, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    return path


def harvest_shards(dir_name):
    """All readable shard documents under ``dir_name`` (a torn or
    half-dead worker's unreadable shard is skipped, not fatal)."""
    out = []
    shard_dir = os.path.join(str(dir_name), SHARD_DIR)
    try:
        names = sorted(os.listdir(shard_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".trace.json"):
            continue
        try:
            with open(os.path.join(shard_dir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and isinstance(doc.get("events"), list):
            out.append(doc)
    return out


def merge_shards(shards):
    """One Perfetto-loadable chrome-trace document from many per-process
    shards, timestamp-aligned onto a common base.

    Every process stamps spans in microseconds since ITS OWN monotonic
    epoch; each shard records where that epoch sits on the (shared)
    unix clock (``epoch_unix``, the PR-17 clock-offset handshake). The
    merge shifts each shard by ``(its epoch - earliest epoch)`` so a
    child span can never appear to start before its cross-process
    parent from clock-base mismatch alone."""
    shards = [s for s in shards if s.get("events")]
    if not shards:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(float(s.get("epoch_unix") or 0.0) for s in shards)
    events = []
    seen_pids = set()
    for shard in shards:
        shift_us = (float(shard.get("epoch_unix") or 0.0) - base) * 1e6
        pid = shard.get("pid", 0)
        worker = shard.get("worker", f"pid{pid}")
        if pid not in seen_pids:
            seen_pids.add(pid)
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"name": str(worker)}})
        for ev in shard["events"]:
            ev = dict(ev)
            ev["ts"] = float(ev.get("ts", 0.0)) + shift_us
            ev.setdefault("pid", pid)
            events.append(ev)
    events.sort(key=lambda e: (e.get("ph") != "M",
                               float(e.get("ts", 0.0))))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def span_tree(events, trace_id):
    """The spans of ONE trace as a parent-nested tree (list of roots,
    each ``{"name", "ts", "dur", "pid", "tid", "span_id", "parent_id",
    "args", "children"}``). Input is merged (aligned) chrome-trace
    events; spans carry their identity in ``args``."""
    nodes = {}
    order = []
    for ev in events:
        args = ev.get("args") or {}
        if args.get("trace_id") != trace_id:
            continue
        sid = args.get("span_id")
        if not sid:
            continue
        extra = {k: v for k, v in args.items()
                 if k not in ("trace_id", "span_id", "parent_id")}
        nodes[sid] = {"name": ev.get("name"),
                      "ts": ev.get("ts"), "dur": ev.get("dur"),
                      "pid": ev.get("pid"), "tid": ev.get("tid"),
                      "span_id": sid,
                      "parent_id": args.get("parent_id"),
                      "args": extra, "children": []}
        order.append(sid)
    roots = []
    for sid in order:
        node = nodes[sid]
        parent = nodes.get(node["parent_id"])
        if parent is not None:
            parent["children"].append(node)
        else:
            # the parent span may live in a shard that wasn't flushed
            # yet (or was trimmed off the ring) — surface as a root
            # rather than dropping the subtree
            roots.append(node)
    for node in nodes.values():
        node["children"].sort(key=lambda n: (n["ts"] is None, n["ts"]))
    roots.sort(key=lambda n: (n["ts"] is None, n["ts"]))
    return roots


# ---------------------------------------------------------------------------
# clock-offset handshake: recorded at replica registration so the
# collector can align a worker's monotonic span clock even before (or
# without) its first shard flush
# ---------------------------------------------------------------------------
def record_clock_handshake(dir_name, worker_name):
    """Write ``<dir_name>/.traceclock.<worker>`` with this process's
    monotonic<->epoch offset (dot-prefixed: FileStore membership scans
    ignore it). Returns the path, or ``None`` under the kill switch."""
    if not enabled():
        return None
    from . import trace as _trace

    path = os.path.join(str(dir_name), f".traceclock.{worker_name}")
    doc = {"worker": str(worker_name), "pid": os.getpid(),
           "epoch_unix": _trace.epoch_unix()}
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError:
        return None
    return path


def read_clock_handshakes(dir_name):
    """``{worker: handshake doc}`` for every readable handshake file."""
    out = {}
    try:
        names = os.listdir(str(dir_name))
    except OSError:
        return out
    for name in names:
        if not name.startswith(".traceclock.") or ".tmp." in name:
            continue
        try:
            with open(os.path.join(str(dir_name), name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and doc.get("worker"):
            out[str(doc["worker"])] = doc
    return out
