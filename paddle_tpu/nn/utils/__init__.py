"""nn.utils — parameter vectorization + clip utilities.

Reference: `python/paddle/nn/utils/`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: F401

__all__ = ["parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_", "weight_norm",
           "remove_weight_norm", "spectral_norm"]


def parameters_to_vector(parameters):
    return Tensor(jnp.concatenate(
        [p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters):
    offset = 0
    for p in parameters:
        n = 1
        for s in p._data.shape:
            n *= s
        p._data = vec._data[offset:offset + n].reshape(p._data.shape) \
            .astype(p._data.dtype)
        offset += n


def weight_norm(layer, name="weight", dim=0):
    raise NotImplementedError("weight_norm: planned; use SpectralNorm or "
                              "explicit normalization for now")


def remove_weight_norm(layer, name="weight"):
    raise NotImplementedError


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    raise NotImplementedError("use nn.SpectralNorm layer")
