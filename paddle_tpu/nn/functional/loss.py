"""Loss functionals.

Reference: `python/paddle/nn/functional/loss.py`.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...tensor.registry import defop
from ...framework.tensor import Tensor, run_op

__all__ = ["cross_entropy", "softmax_with_cross_entropy", "nll_loss",
           "mse_loss", "l1_loss", "smooth_l1_loss", "binary_cross_entropy",
           "binary_cross_entropy_with_logits", "kl_div", "margin_ranking_loss",
           "hinge_embedding_loss", "cosine_embedding_loss", "ctc_loss",
           "square_error_cost", "log_loss", "sigmoid_focal_loss",
           "triplet_margin_loss", "poisson_nll_loss", "gaussian_nll_loss",
           "multi_label_soft_margin_loss", "margin_cross_entropy",
           "huber_loss", "identity_loss", "hsigmoid_loss", "edit_distance",
           "rnnt_loss"]


def _reduce(x, reduction):
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    return x


@defop()
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    """Reference: nn/functional/loss.py cross_entropy. ``input`` is logits
    (or probabilities when use_softmax=False); hard labels are class ids."""
    axis = int(axis)
    c = input.shape[axis]
    if use_softmax:
        logp = jax.nn.log_softmax(input.astype(jnp.float32), axis=axis)
    else:
        logp = jnp.log(jnp.clip(input.astype(jnp.float32), 1e-15, 1.0))
    if soft_label:
        soft = label.astype(jnp.float32)
        if label_smoothing > 0.0:
            soft = (1 - label_smoothing) * soft + label_smoothing / c
        loss = -jnp.sum(soft * logp, axis=axis)
        if weight is not None:
            wshape = [1] * logp.ndim
            wshape[axis] = -1
            loss = loss * jnp.sum(soft * weight.reshape(wshape), axis=axis)
        return _reduce(loss, reduction)
    lbl = label
    if lbl.ndim == logp.ndim:  # [N, 1] style labels
        lbl = jnp.squeeze(lbl, axis=axis)
    lbl = lbl.astype(jnp.int32)
    valid = (lbl != ignore_index)
    safe = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(logp, safe[..., None] if axis in (-1, logp.ndim - 1)
                                 else jnp.expand_dims(safe, axis), axis=axis)
    picked = jnp.squeeze(picked, axis=axis)
    if label_smoothing > 0.0:
        smooth_term = jnp.mean(logp, axis=axis)
        nll = -(1 - label_smoothing) * picked - label_smoothing * smooth_term
    else:
        nll = -picked
    nll = jnp.where(valid, nll, 0.0)
    if weight is not None:
        w = jnp.take(weight.astype(jnp.float32), safe, axis=0)
        w = jnp.where(valid, w, 0.0)
        nll = nll * w
        if reduction == "mean":
            return jnp.sum(nll) / jnp.maximum(jnp.sum(w), 1e-12)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return jnp.sum(nll) / denom
    return _reduce(nll, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax as _softmax
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


@defop()
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    lbl = label.astype(jnp.int32)
    valid = (lbl != ignore_index)
    safe = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(input, safe[:, None], axis=1)[:, 0]
    loss = -jnp.where(valid, picked, 0.0)
    if weight is not None:
        w = jnp.take(weight, safe, axis=0)
        w = jnp.where(valid, w, 0.0)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    return _reduce(loss, reduction)


@defop()
def mse_loss(input, label, reduction="mean"):
    return _reduce(jnp.square(input - label), reduction)


@defop()
def l1_loss(input, label, reduction="mean"):
    return _reduce(jnp.abs(input - label), reduction)


@defop()
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


@defop()
def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    x = jnp.clip(input.astype(jnp.float32), 1e-12, 1 - 1e-7)
    loss = -(label * jnp.log(x) + (1 - label) * jnp.log1p(-x))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop()
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    z = logit.astype(jnp.float32)
    lbl = label.astype(jnp.float32)
    # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight on y term
    if pos_weight is not None:
        log_w = (pos_weight - 1) * lbl + 1
        loss = (1 - lbl) * z + log_w * (jnp.logaddexp(0, -jnp.abs(z))
                                        + jnp.maximum(-z, 0))
    else:
        loss = jnp.maximum(z, 0) - z * lbl + jnp.logaddexp(0, -jnp.abs(z))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop()
def kl_div(input, label, reduction="mean", log_target=False):
    """input is log-probabilities (paddle convention)."""
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        safe = jnp.where(label > 0, label, 1.0)
        loss = jnp.where(label > 0, label * (jnp.log(safe) - input), 0.0)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@defop()
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    loss = jnp.maximum(0, -label * (input - other) + margin)
    return _reduce(loss, reduction)


@defop()
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    loss = jnp.where(label == 1, input, jnp.maximum(0, margin - input))
    return _reduce(loss, reduction)


@defop()
def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean"):
    cos = jnp.sum(input1 * input2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1),
        1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(0, cos - margin))
    return _reduce(loss, reduction)


@defop()
def square_error_cost(input, label):
    return jnp.square(input - label)


@defop()
def log_loss(input, label, epsilon=1e-4):
    x = jnp.clip(input, epsilon, 1 - epsilon)
    return -(label * jnp.log(x) + (1 - label) * jnp.log(1 - x))


@defop()
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label \
        + jnp.logaddexp(0, -jnp.abs(logit))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@defop()
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p),
                                 axis=-1), 1.0 / p)
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    return _reduce(jnp.maximum(0, d_pos - d_neg + margin), reduction)


@defop()
def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = label * jnp.log(label + epsilon) - label \
            + 0.5 * jnp.log(2 * jnp.pi * (label + epsilon))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via optax's implementation (XLA-friendly dynamic programming).

    Reference: nn/functional/loss.py ctc_loss (warpctc). Input layout is
    paddle's [T, N, C] unless already [N, T, C]."""
    import optax

    def fn(lp, lbl, in_len, lbl_len):
        logits = jnp.transpose(lp, (1, 0, 2)) if lp.ndim == 3 else lp
        n, t, c = logits.shape
        logit_pad = (jnp.arange(t)[None, :] >= in_len[:, None]).astype(jnp.float32)
        max_l = lbl.shape[1]
        label_pad = (jnp.arange(max_l)[None, :] >= lbl_len[:, None]).astype(jnp.float32)
        per_seq = optax.ctc_loss(logits, logit_pad, lbl, label_pad,
                                 blank_id=blank)
        if reduction == "mean":
            return jnp.mean(per_seq / jnp.maximum(lbl_len, 1))
        if reduction == "sum":
            return jnp.sum(per_seq)
        return per_seq

    return run_op("ctc_loss", fn,
                  (log_probs, labels, input_lengths, label_lengths))


@defop()
def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    """Gaussian negative log likelihood (reference
    `nn/functional/loss.py:gaussian_nll_loss`): 0.5*(log(var) +
    (input-label)^2/var), variance clamped at ``epsilon``; ``full`` adds
    the 0.5*log(2*pi) constant."""
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + (input - label) ** 2 / var)
    if full:
        loss = loss + 0.5 * jnp.log(jnp.asarray(2.0 * jnp.pi, loss.dtype))
    return _reduce(loss, reduction)


@defop()
def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean"):
    """Multi-label one-vs-all soft margin (reference
    `nn/functional/loss.py:multi_label_soft_margin_loss`): per-class
    sigmoid BCE averaged over classes."""
    logsig = jax.nn.log_sigmoid
    per_class = -(label * logsig(input) + (1 - label) * logsig(-input))
    if weight is not None:
        per_class = per_class * weight
    loss = jnp.mean(per_class, axis=-1)
    return _reduce(loss, reduction)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family combined margin softmax (reference
    `nn/functional/loss.py:margin_cross_entropy`, CUDA kernel
    `phi/kernels/gpu/margin_cross_entropy_kernel.cu`): the target
    class's logit cos(theta) becomes cos(margin1*theta + margin2) -
    margin3 before scaled softmax CE. The reference's model-parallel
    ``group`` is GSPMD's job here — shard the class dim of ``logits``
    and the same code compiles to the sharded softmax."""
    from ...framework.tensor import run_op

    m1, m2, m3, s = (float(margin1), float(margin2), float(margin3),
                     float(scale))

    def fn(logits, label):
        n, c = logits.shape
        cos = jnp.clip(logits.astype(jnp.float32), -1.0, 1.0)
        theta = jnp.arccos(cos)
        target_cos = jnp.cos(m1 * theta + m2) - m3
        onehot = jax.nn.one_hot(label.reshape(-1), c, dtype=jnp.float32)
        adjusted = jnp.where(onehot > 0, target_cos, cos) * s
        logp = jax.nn.log_softmax(adjusted, axis=-1)
        loss = -jnp.sum(onehot * logp, axis=-1, keepdims=True)
        if reduction == "mean":
            loss_out = jnp.mean(loss)
        elif reduction == "sum":
            loss_out = jnp.sum(loss)
        else:
            loss_out = loss
        return loss_out, jnp.exp(logp)

    loss, softmax = run_op("margin_cross_entropy", fn, (logits, label))
    if return_softmax:
        return loss, softmax
    return loss


@defop()
def huber_loss(input, label, delta=1.0, reduction="mean"):
    """Huber loss (reference op `huber_loss`,
    `phi/kernels/impl/huber_loss_kernel_impl.h`): quadratic within
    ``delta`` of the target, linear beyond."""
    d = float(delta)
    r = jnp.abs(input - label)
    loss = jnp.where(r <= d, 0.5 * r * r, d * (r - 0.5 * d))
    return _reduce(loss, reduction)


@defop()
def identity_loss(x, reduction="none"):
    """Pass-through loss head (reference op `identity_loss`) — reduces
    its input and marks it as the optimization target."""
    if isinstance(reduction, int):
        reduction = {0: "sum", 1: "mean", 2: "none"}[reduction]
    return _reduce(x, reduction)


@defop()
def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None):
    """Hierarchical sigmoid loss (reference op `hsigmoid_loss`,
    `phi/kernels/cpu/hsigmoid_loss_kernel.cc`). Default mode walks a
    complete binary tree over ``num_classes`` leaves (internal nodes
    0..C-2, leaf of class c at c + C - 1); custom mode takes explicit
    ``path_table``/``path_code``. Cost per sample is the summed
    BCE-with-logits of each branch decision on the path:
    sum(softplus(z) - code * z), z = x . w_node + b_node."""
    x = jnp.asarray(input)
    lbl = jnp.asarray(label).reshape(-1).astype(jnp.int32)
    n = x.shape[0]
    if path_table is not None:
        tbl = jnp.asarray(path_table).astype(jnp.int32)   # [N, L]
        code = jnp.asarray(path_code).astype(x.dtype)     # [N, L]
        valid = tbl >= 0
        tbl = jnp.maximum(tbl, 0)
    else:
        c = int(num_classes)
        depth = max(int(math.ceil(math.log2(max(c, 2)))), 1)
        # walk leaf -> root in the complete binary tree, then reverse
        leaf = lbl + (c - 1)
        steps = []
        node = leaf
        for _ in range(depth + 1):
            parent = (node - 1) // 2
            is_right = (node == 2 * parent + 2)
            at_root = node <= 0
            steps.append((jnp.where(at_root, -1, parent),
                          is_right.astype(x.dtype),
                          ~at_root))
            node = jnp.maximum(parent, 0)
        tbl = jnp.stack([s[0] for s in steps], axis=1)
        code = jnp.stack([s[1] for s in steps], axis=1)
        valid = jnp.stack([s[2] for s in steps], axis=1) & (tbl >= 0)
        tbl = jnp.maximum(tbl, 0)
    w = jnp.asarray(weight)                               # [C-1, D]
    z = jnp.einsum("nd,nld->nl", x, w[tbl])
    if bias is not None:
        z = z + jnp.asarray(bias).reshape(-1)[tbl]
    per = jax.nn.softplus(z) - code * z
    cost = jnp.sum(jnp.where(valid, per, 0.0), axis=1, keepdims=True)
    return cost


def _edit_distance_one(hyp, ref, hlen, rlen):
    """Levenshtein DP as nested scans: the outer scan walks hypothesis
    tokens (rows frozen past hlen), the inner scan threads the
    left-neighbor dependency along the reference axis."""
    s2 = ref.shape[0]
    row0 = jnp.arange(s2 + 1, dtype=jnp.float32)

    def outer(prev, i):
        first = prev[0] + 1

        def inner(left, j):
            cost = jnp.where(hyp[i] == ref[j], 0.0, 1.0)
            val = jnp.minimum(jnp.minimum(prev[j + 1] + 1, left + 1),
                              prev[j] + cost)
            return val, val

        _, rest = jax.lax.scan(inner, first, jnp.arange(s2))
        new = jnp.concatenate([first[None], rest])
        return jnp.where(i < hlen, new, prev), None

    last, _ = jax.lax.scan(outer, row0, jnp.arange(hyp.shape[0]))
    return jnp.take(last, rlen)


@defop(differentiable=False)
def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance per sequence pair (reference op
    `edit_distance`, `phi/kernels/impl/edit_distance_kernel_impl.h`).
    Returns (distance [B, 1], sequence_num [1])."""
    hyp = jnp.asarray(input)
    ref = jnp.asarray(label)
    if hyp.ndim == 3:
        hyp = hyp[..., 0]
    if ref.ndim == 3:
        ref = ref[..., 0]
    b = hyp.shape[0]
    hlen = (jnp.asarray(input_length).reshape(-1) if input_length is not None
            else jnp.full((b,), hyp.shape[1]))
    rlen = (jnp.asarray(label_length).reshape(-1) if label_length is not None
            else jnp.full((b,), ref.shape[1]))
    if ignored_tokens:
        # compact each row: drop ignored tokens, shift survivors left
        def compact(seq, ln):
            keep = jnp.ones(seq.shape, bool)
            for t in ignored_tokens:
                keep &= seq != t
            keep &= jnp.arange(seq.shape[0]) < ln
            order = jnp.argsort(~keep, stable=True)
            return seq[order], jnp.sum(keep.astype(jnp.int32))

        hyp, hlen = jax.vmap(compact)(hyp, hlen)
        ref, rlen = jax.vmap(compact)(ref, rlen)
    hlen = hlen.astype(jnp.int32)
    rlen = rlen.astype(jnp.int32)
    dist = jax.vmap(_edit_distance_one)(hyp, ref, hlen, rlen)
    if normalized:
        dist = dist / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    return dist[:, None], jnp.asarray([b], jnp.int32)


@defop(name="warprnnt")
def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean"):
    """RNN-T (transducer) loss (reference op `warprnnt`,
    `phi/kernels/cpu/warprnnt_kernel.cc` wrapping warp-transducer).

    input: [B, Tmax, Umax+1, V] joint-network logits; label [B, Umax];
    the forward variable alpha walks the (T, U) lattice — outer scan
    over time, inner scan threads the same-row emit recurrence.
    """
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: FastEmit regularization (fastemit_lambda != 0) "
            "is not implemented — pass 0.0 or apply the regularizer "
            "externally")
    logp = jax.nn.log_softmax(jnp.asarray(input, jnp.float32), axis=-1)
    labels = jnp.asarray(label).astype(jnp.int32)
    t_lens = jnp.asarray(input_lengths).reshape(-1).astype(jnp.int32)
    u_lens = jnp.asarray(label_lengths).reshape(-1).astype(jnp.int32)
    bsz, tmax, umax1, _ = logp.shape
    umax = umax1 - 1
    NEG = -1e30

    def one(lp, lbl, t_len, u_len):
        # blank[t, u] and emit[t, u] (emit consumes lbl[u])
        blank_lp = lp[:, :, blank]                         # [T, U+1]
        emit_lp = jnp.take_along_axis(
            lp[:, :umax, :], lbl[None, :, None], axis=-1)[..., 0]  # [T, U]
        u_idx = jnp.arange(umax1)

        def row(prev_alpha, t):
            # from below: alpha[t-1, u] + blank[t-1, u]
            from_below = jnp.where(
                t == 0, jnp.where(u_idx == 0, 0.0, NEG),
                prev_alpha + blank_lp[jnp.maximum(t - 1, 0)])

            # left-to-right emit recurrence within the row
            def cell(left, u):
                diag = jnp.where(u == 0, NEG,
                                 left + emit_lp[t, jnp.maximum(u - 1, 0)])
                a = jnp.logaddexp(from_below[u], diag)
                a = jnp.where(u > u_len, NEG, a)
                return a, a

            _, alpha_row = jax.lax.scan(cell, NEG, u_idx)
            return alpha_row, None

        def row_keep(carry, t):
            a, _ = row(carry, t)
            return a, a

        _, rows = jax.lax.scan(row_keep, jnp.full((umax1,), NEG),
                               jnp.arange(tmax))
        final = rows[t_len - 1]                            # [U+1]
        ll = final[u_len] + blank_lp[t_len - 1, u_len]
        return -ll

    losses = jax.vmap(one)(logp, labels, t_lens, u_lens)
    return _reduce(losses, reduction)
