"""Pooling functionals via ``lax.reduce_window``.

Reference: `python/paddle/nn/functional/pooling.py`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor.registry import defop

__all__ = ["max_pool1d", "max_pool2d", "max_pool3d",
           "avg_pool1d", "avg_pool2d", "avg_pool3d",
           "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d"]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(e) for e in v)
    return (int(v),) * n


def _pool_pad(padding, nd):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if all(isinstance(p, int) for p in padding):
        if len(padding) == nd:
            return [(p, p) for p in padding]
        if len(padding) == 2 * nd:
            return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    return [tuple(int(e) for e in p) for p in padding]


def _reduce_init(reduce_fn, dtype):
    """Identity element for a reduce_window monoid, as a Python/numpy
    scalar — array-wrapped inits defeat JAX's monoid recognition and lose
    the op's autodiff rule under jit."""
    if reduce_fn is jax.lax.add:
        return 0.0
    if jnp.issubdtype(dtype, jnp.floating):
        return float("-inf")
    return np.dtype(dtype).type(jnp.iinfo(dtype).min)


def _reduce_pool(x, kernel, stride, padding, nd, channel_last, init, op,
                 ceil_mode=False):
    k = _tuple(kernel, nd)
    s = _tuple(stride if stride is not None else kernel, nd)
    p = _pool_pad(padding, nd)
    if channel_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = ([(0, 0)] + p + [(0, 0)]) if isinstance(p, list) else p
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = ([(0, 0), (0, 0)] + p) if isinstance(p, list) else p
    # init must stay a Python scalar: JAX recognizes the (init, op) monoid
    # (sum/max/min) only for literal identities — wrapping it in an array
    # defeats the detection and the op loses its autodiff rule under jit.
    if isinstance(pads, list) and ceil_mode:
        # grow right-pad so the last partial window is included
        spatial = x.shape[1:-1] if channel_last else x.shape[2:]
        base = 1 if channel_last else 2
        pads = list(pads)
        for i in range(nd):
            size = spatial[i] + pads[base + i][0] + pads[base + i][1]
            rem = (size - k[i]) % s[i]
            if rem != 0:
                lo, hi = pads[base + i]
                pads[base + i] = (lo, hi + (s[i] - rem))
    return jax.lax.reduce_window(x, init, op, window, strides, pads), \
        (window, strides, pads)


def _max_pool(x, kernel, stride, padding, nd, data_format, ceil_mode):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    neg = _reduce_init(jax.lax.max, x.dtype)
    out, _ = _reduce_pool(x, kernel, stride, padding, nd, channel_last,
                          neg, jax.lax.max, ceil_mode)
    return out


def _avg_pool(x, kernel, stride, padding, nd, data_format, exclusive,
              ceil_mode):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    summed, (window, strides, pads) = _reduce_pool(
        x, kernel, stride, padding, nd, channel_last, 0.0, jax.lax.add,
        ceil_mode)
    if exclusive and not isinstance(pads, str):
        ones = jnp.ones(x.shape, dtype=x.dtype)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add,
                                       window, strides, pads)
        return summed / counts
    return summed / float(np.prod(_tuple(kernel, nd)))


@defop()
def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCL"):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _max_pool(x, kernel_size, stride, padding, 1, fmt, ceil_mode)


@defop()
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    return _max_pool(x, kernel_size, stride, padding, 2, data_format,
                     ceil_mode)


@defop()
def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    return _max_pool(x, kernel_size, stride, padding, 3, data_format,
                     ceil_mode)


@defop()
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _avg_pool(x, kernel_size, stride, padding, 1, fmt, exclusive,
                     ceil_mode)


@defop()
def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCHW"):
    return _avg_pool(x, kernel_size, stride, padding, 2, data_format,
                     exclusive, ceil_mode)


@defop()
def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCDHW"):
    return _avg_pool(x, kernel_size, stride, padding, 3, data_format,
                     exclusive, ceil_mode)


def _adaptive_windows(in_size, out_size):
    """start/end indices per output cell, paddle/torch adaptive convention."""
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, nd, data_format, reduce_fn):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    out_sizes = _tuple(output_size, nd)
    spatial_base = 1 if channel_last else 2
    # uniform case lowers to one strided reduce_window (fast path)
    in_sizes = x.shape[spatial_base:spatial_base + nd]
    if all(i % o == 0 for i, o in zip(in_sizes, out_sizes)):
        k = tuple(i // o for i, o in zip(in_sizes, out_sizes))
        if channel_last:
            window = (1,) + k + (1,)
        else:
            window = (1, 1) + k
        init = _reduce_init(reduce_fn, x.dtype)
        out = jax.lax.reduce_window(x, init, reduce_fn, window, window,
                                    "VALID")
        if reduce_fn is jax.lax.add:
            out = out / float(np.prod(k))
        return out
    # general case: gather per-cell slices (static loop, still one XLA graph)
    for d in range(nd):
        axis = spatial_base + d
        starts, ends = _adaptive_windows(x.shape[axis], out_sizes[d])
        pieces = []
        for s, e in zip(starts, ends):
            sl = jax.lax.slice_in_dim(x, s, e, axis=axis)
            if reduce_fn is jax.lax.add:
                pieces.append(jnp.mean(sl, axis=axis, keepdims=True))
            else:
                pieces.append(jnp.max(sl, axis=axis, keepdims=True))
        x = jnp.concatenate(pieces, axis=axis)
    return x


@defop()
def adaptive_avg_pool1d(x, output_size, data_format="NCL"):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _adaptive_pool(x, output_size, 1, fmt, jax.lax.add)


@defop()
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, data_format, jax.lax.add)


@defop()
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, data_format, jax.lax.add)


@defop()
def adaptive_max_pool1d(x, output_size, return_mask=False, data_format="NCL"):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _adaptive_pool(x, output_size, 1, fmt, jax.lax.max)


@defop()
def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, data_format, jax.lax.max)


@defop()
def adaptive_max_pool3d(x, output_size, return_mask=False,
                        data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, data_format, jax.lax.max)
